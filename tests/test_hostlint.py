"""Hostline (ISSUE 18): static protocol analysis of the serving stack.

Engine tests pin the CFG's exception/finally/with edges and the
call-graph's entry-context rooting; every rule gets a planted-positive AND
a clean-negative fixture pair — including a replay of the PR-11 histogram
scrape race and a deliberately reintroduced PR-12-style books leak, both
asserting the rendered conflict/CFG path; the committed gate is proven
green over the real serving/+obs/ surface with the reasoned allowlist; and
the hostlint/graphlint CLI pair pins the shared exit-code contract
(0 clean / 1 violation / 2 usage / 3 crash) through analysis/lintcli.py.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

from perceiver_io_tpu.analysis.hostgraph import (
    EXC,
    build_cfg,
    build_host_graph,
    build_package_graph,
    iter_paths,
    walk_own,
)
from perceiver_io_tpu.analysis.hostrules import (
    BooksSpec,
    ClockSpec,
    EventSpec,
    GrantSpec,
    HostPolicy,
    HOST_RULES,
    default_host_policy,
    host_check,
    load_allowlist,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ALLOWLIST = os.path.join(REPO, "contracts", "hostlint_allow.json")


def _fn(src: str) -> ast.AST:
    """Parse one function's source into its FunctionDef node."""
    mod = ast.parse(textwrap.dedent(src))
    return mod.body[0]


def _labels(cfg, path):
    return [cfg.nodes[i].label for i in path]


# ============================================================ CFG engine


def test_cfg_raise_reaches_handler_and_finally_guards_both_exits():
    cfg = build_cfg(_fn("""
        def f(self):
            try:
                self.a()
                raise ValueError()
            except ValueError:
                self.h()
            finally:
                self.fin()
    """))
    paths = list(iter_paths(cfg, cfg.entry, {cfg.exit, cfg.raise_exit}))
    assert paths, "CFG must have at least one entry->exit path"
    handler_seen = False
    for p in paths:
        labels = _labels(cfg, p)
        # the finally body guards EVERY way out — normal and exceptional
        assert any("self.fin()" in l for l in labels), labels
        if any("self.h()" in l for l in labels):
            handler_seen = True
    assert handler_seen, "raise edge must route into the except handler"


def test_cfg_call_in_guarded_try_gets_exception_edge():
    cfg = build_cfg(_fn("""
        def f(self):
            try:
                self.risky()
            except Exception:
                self.cleanup()
    """))
    risky = next(n for n in cfg.nodes if "self.risky()" in n.label)
    assert any(kind == EXC for _t, kind in risky.succ), (
        "a call inside a try with handlers must carry an exception edge")
    # and a path through that edge reaches the handler
    assert any(
        any("self.cleanup()" in l for l in _labels(cfg, p))
        for p in iter_paths(cfg, cfg.entry, {cfg.exit, cfg.raise_exit})
    )


def test_cfg_with_block_unwinds_through_exit_node():
    """An exception escaping a ``with`` body leaves through the synthetic
    ``<with-exit>`` node (the __exit__ chain) before the outer handler."""
    cfg = build_cfg(_fn("""
        def f(self):
            try:
                with self._lock:
                    self.risky()
            except Exception:
                self.cleanup()
    """))
    assert any(n.label.startswith("<with-exit>") for n in cfg.nodes)
    unwound = [
        p for p in iter_paths(cfg, cfg.entry, {cfg.exit, cfg.raise_exit})
        if any("self.cleanup()" in l for l in _labels(cfg, p))
    ]
    assert unwound, "the exceptional route must reach the handler"
    for p in unwound:
        assert any(l.startswith("<with-exit>") for l in _labels(cfg, p)), (
            "the exceptional route must pass the with-unwind node")


def test_cfg_compound_headers_carry_only_the_header_expression():
    """The header node of an if/while/for/with must NOT contain its nested
    body — a rule walking ``node.stmt`` would otherwise double-count body
    statements at the header (the phantom double-booking bug class)."""
    cfg = build_cfg(_fn("""
        def f(self):
            if self.cond:
                self._n["shed"] += 1
            for x in self.items:
                self._n["ok"] += 1
    """))
    for n in cfg.nodes:
        if n.stmt is None or not n.label.startswith("<"):
            continue
        assert not any(isinstance(x, ast.AugAssign) for x in ast.walk(n.stmt)), (
            f"header node {n.label!r} leaked its body into node.stmt")
    # the body statements still have their own nodes
    assert sum("self._n[" in n.label for n in cfg.nodes) == 2


def test_walk_own_skips_nested_defs():
    fn = _fn("""
        def outer(self):
            self.events.emit("a")
            def inner():
                self.events.emit("b")
            return inner
    """)
    kinds = [n.args[0].value for n in walk_own(fn)
             if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
             and n.func.attr == "emit"]
    assert kinds == ["a"], "nested def's emit must not attribute to outer"


# ==================================================== call-graph rooting


def test_call_graph_roots_through_constructor_inferred_attr():
    g = build_host_graph({"fx": textwrap.dedent("""
        class Inner:
            def work(self):
                self.leaf()
            def leaf(self):
                pass

        class Outer:
            def __init__(self):
                self.inner = Inner()
            def run(self):
                self.inner.work()
    """)})
    pmap = g.reachable_map(["fx:Outer.run"])
    assert "fx:Inner.work" in pmap, "self.inner.work() must resolve via the "\
        "constructor-inferred attribute type"
    assert "fx:Inner.leaf" in pmap, "and transitively through self-calls"
    assert g.chain(pmap, "fx:Inner.leaf")[0] == "fx:Outer.run"


def test_call_graph_resolves_through_inheritance_cluster():
    g = build_host_graph({"fx": textwrap.dedent("""
        class Base:
            def run(self):
                self.step()
        class Derived(Base):
            def step(self):
                self.leafed()
            def leafed(self):
                pass
    """)})
    pmap = g.reachable_map(["fx:Base.run"])
    assert "fx:Derived.step" in pmap, "cluster/MRO resolution: a base-class "\
        "self.step() call reaches the subclass override"


# ======================================== rule fixtures: books-exactness

_BOOKS_POLICY = HostPolicy(
    books=BooksSpec(
        terminal_outcomes=("ok", "error", "shed"),
        submit_patterns=("*submit*",),
        handoffs=("self._queue.append",),
    ),
)

# the PR-12 bug class, deliberately reintroduced: the full-queue branch
# returns without booking shed — submitted leaks
_BOOKS_LEAK = """
class Frontend:
    def submit(self, spec):
        self._n["submitted"] += 1
        if len(self._queue) >= self.depth:
            return None
        self._queue.append(spec)
        return spec
"""

_BOOKS_CLEAN = """
class Frontend:
    def submit(self, spec):
        self._n["submitted"] += 1
        if len(self._queue) >= self.depth:
            self._n["shed"] += 1
            return None
        self._queue.append(spec)
        return spec
"""

_BOOKS_DOUBLE = """
class Frontend:
    def submit(self, spec):
        self._n["submitted"] += 1
        self._n["shed"] += 1
        self._n["error"] += 1
        return None
"""

# the real surface's parametric terminal booking: _finish(outcome) writes
# self._n[outcome] — the dynamic-key write must seed the booker closure
_BOOKS_DYNAMIC = """
class Frontend:
    def submit(self, spec):
        self._n["submitted"] += 1
        self._finish(spec, "ok")
        return spec

    def _finish(self, spec, outcome):
        self._n[outcome] += 1
"""


def test_books_leak_is_flagged_with_rendered_path():
    rep = host_check({"fx": _BOOKS_LEAK}, policy=_BOOKS_POLICY)
    v = [v for v in rep.violations if v.rule == "books-exactness"]
    assert len(v) == 1 and v[0].severity == "error"
    assert "books leak" in v[0].message
    assert "self._n['submitted'] += 1" in v[0].message, "path must be rendered"
    assert "<return>" in v[0].message or "return" in v[0].message


def test_books_clean_handoff_and_terminal_pass():
    rep = host_check({"fx": _BOOKS_CLEAN}, policy=_BOOKS_POLICY)
    assert not [v for v in rep.violations if v.rule == "books-exactness"]


def test_books_double_booking_is_flagged():
    rep = host_check({"fx": _BOOKS_DOUBLE}, policy=_BOOKS_POLICY)
    v = [v for v in rep.violations if v.rule == "books-exactness"]
    assert len(v) == 1 and "double booking" in v[0].message


def test_books_dynamic_key_booker_counts_as_terminal():
    rep = host_check({"fx": _BOOKS_DYNAMIC}, policy=_BOOKS_POLICY)
    assert not [v for v in rep.violations if v.rule == "books-exactness"]


# ====================================== rule fixtures: shared-state-race

_RACE_POLICY = HostPolicy(
    serving_entries=("*:Histogram.record",),
    scrape_entries=("*:Histogram.state",),
)

# the PR-11 scrape race, replayed: record() mutates the window while a
# scrape-thread state() iterates it — no common lock
_RACE_PLANTED = """
class Histogram:
    def record(self, v):
        self._window.append(v)

    def state(self):
        return sorted(self._window)
"""

_RACE_CLEAN = """
class Histogram:
    def record(self, v):
        with self._lock:
            self._window.append(v)

    def state(self):
        with self._lock:
            return sorted(self._window)
"""


def test_race_pr11_replay_is_error_with_both_sites():
    rep = host_check({"fx": _RACE_PLANTED}, policy=_RACE_POLICY)
    v = [v for v in rep.violations if v.rule == "shared-state-race"]
    assert len(v) == 1 and v[0].severity == "error", rep.format()
    assert v[0].scope == "Histogram._window"
    # the rendered conflict names both sites and their entry chains
    assert "write:" in v[0].message and "read:" in v[0].message
    assert "Histogram.record" in v[0].message
    assert "Histogram.state" in v[0].message


def test_race_common_lock_on_both_sides_passes():
    rep = host_check({"fx": _RACE_CLEAN}, policy=_RACE_POLICY)
    assert not [v for v in rep.violations if v.rule == "shared-state-race"]


def test_race_scalar_point_read_is_info_not_error():
    rep = host_check({"fx": """
class Histogram:
    def record(self, v):
        self._count = v

    def state(self):
        return self._count
"""}, policy=_RACE_POLICY)
    v = [v for v in rep.violations if v.rule == "shared-state-race"]
    assert len(v) == 1 and v[0].severity == "info"


# ======================================= rule fixtures: clock-discipline

_CLOCK_POLICY = HostPolicy(clocks=ClockSpec())

_CLOCK_PLANTED = """
import time

class Paced:
    def __init__(self, clock=time.monotonic):
        self._clock = clock

    def step(self):
        return time.monotonic()
"""

_CLOCK_CLEAN = """
import time

class Paced:
    def __init__(self, clock=time.monotonic):
        self._clock = clock

    def step(self):
        return self._clock()
"""


def test_clock_bare_call_in_injectable_cluster_is_error():
    rep = host_check({"fx": _CLOCK_PLANTED}, policy=_CLOCK_POLICY)
    errs = [v for v in rep.violations
            if v.rule == "clock-discipline" and v.severity == "error"]
    assert len(errs) == 1 and "Paced.step" in errs[0].scope
    assert "time.monotonic" in errs[0].message


def test_clock_injected_seam_passes_and_default_is_recorded_info():
    rep = host_check({"fx": _CLOCK_CLEAN}, policy=_CLOCK_POLICY)
    by_sev = {}
    for v in rep.violations:
        if v.rule == "clock-discipline":
            by_sev.setdefault(v.severity, []).append(v)
    assert "error" not in by_sev
    # the seam default itself is the recorded allowlist, at info
    assert len(by_sev.get("info", [])) == 1
    assert "keyword default" in by_sev["info"][0].message


# ========================================= rule fixtures: grant-pairing

_GRANT_POLICY = HostPolicy(
    grants=GrantSpec(page_writers=("*write_page*",)),
)

_GRANT_LEAK = """
class Engine:
    def join(self):
        g = self.pages.alloc_tokens(4)
        if g is None:
            return False
        self.use(g)
        if self.bad:
            return False
        self.slots[0] = g
        return True
"""

_GRANT_CLEAN = """
class Engine:
    def join(self):
        g = self.pages.alloc_tokens(4)
        if g is None:
            return False
        if self.bad:
            self.pages.free_tokens(g)
            return False
        self.slots[0] = g
        return True
"""

_COW_PLANTED = """
class Engine:
    def write(self, tok):
        g = self.pages.alloc_tokens_shared(4)
        self.kv.write_page(g, tok)
        self.pages.free_tokens(g)
"""

_COW_CLEAN = """
class Engine:
    def write(self, tok):
        g = self.pages.alloc_tokens_shared(4)
        g = self.pages.cow_fork(g)
        self.kv.write_page(g, tok)
"""


def test_grant_leak_path_is_flagged_with_rendered_path():
    rep = host_check({"fx": _GRANT_LEAK}, policy=_GRANT_POLICY)
    v = [v for v in rep.violations if v.rule == "grant-pairing"]
    assert len(v) == 1 and v[0].severity == "error"
    assert "no free/release/adoption sink" in v[0].message
    assert "alloc_tokens" in v[0].message  # rendered path shows the alloc


def test_grant_freed_or_adopted_on_every_path_passes():
    rep = host_check({"fx": _GRANT_CLEAN}, policy=_GRANT_POLICY)
    assert not [v for v in rep.violations if v.rule == "grant-pairing"]


def test_shared_grant_write_without_cow_fork_is_error():
    rep = host_check({"fx": _COW_PLANTED}, policy=_GRANT_POLICY)
    v = [v for v in rep.violations if v.rule == "grant-pairing"]
    assert len(v) == 1 and "cow_fork" in v[0].message


def test_shared_grant_forked_before_write_passes():
    rep = host_check({"fx": _COW_CLEAN}, policy=_GRANT_POLICY)
    assert not [v for v in rep.violations if v.rule == "grant-pairing"]


# ========================================== rule fixtures: event-schema

_EVENT_POLICY = HostPolicy(
    events=EventSpec(
        known_kinds=frozenset({"request", "metrics"}),
        required_fields={"request": ("request_id", "outcome")},
    ),
)


def test_event_unregistered_kind_is_error():
    rep = host_check({"fx": """
class S:
    def go(self):
        self.events.emit("bogus.kind", a=1)
"""}, policy=_EVENT_POLICY)
    v = [v for v in rep.violations if v.rule == "event-schema"]
    assert len(v) == 1 and v[0].severity == "error"
    assert "unregistered event kind 'bogus.kind'" in v[0].message


def test_event_statically_missing_required_field_is_error():
    rep = host_check({"fx": """
class S:
    def go(self):
        self.events.emit("request", request_id=7)
"""}, policy=_EVENT_POLICY)
    v = [v for v in rep.violations if v.rule == "event-schema"]
    assert len(v) == 1 and v[0].severity == "error"
    assert "'outcome'" in v[0].message


def test_event_fields_harvested_through_row_dict_and_comprehension():
    rep = host_check({"fx": """
class S:
    def go(self, summary):
        row = dict(request_id=7)
        row["outcome"] = "ok"
        self.events.emit("request", **row)
        self.events.emit(
            "request",
            **{k: summary[k] for k in ("request_id", "outcome")})
"""}, policy=_EVENT_POLICY)
    assert not [v for v in rep.violations if v.rule == "event-schema"]


def test_event_dynamic_spread_degrades_to_warn_not_error():
    rep = host_check({"fx": """
class S:
    def go(self):
        self.events.emit("request", **self.snapshot())
"""}, policy=_EVENT_POLICY)
    v = [v for v in rep.violations if v.rule == "event-schema"]
    assert len(v) == 1 and v[0].severity == "warn"
    assert "not statically visible" in v[0].message


def test_event_rows_emit_checks_vocabulary_only():
    rep = host_check({"fx": """
class S:
    def go(self, rows):
        self.events.emit_rows("request", rows)
        self.events.emit_rows("bogus", rows)
"""}, policy=_EVENT_POLICY)
    v = [v for v in rep.violations if v.rule == "event-schema"]
    assert len(v) == 1 and "bogus" in v[0].message


# =================================================== registry discipline


def test_rules_are_inert_until_armed():
    rep = host_check({"fx": _BOOKS_LEAK}, policy=HostPolicy())
    assert not rep.violations
    assert len(rep.rules_skipped) == len(HOST_RULES)
    for skipped in rep.rules_skipped:
        assert "(" in skipped, "skip reason must be recorded"


def test_unknown_rule_name_raises_listing_registry():
    with pytest.raises(ValueError) as ei:
        host_check({"fx": _BOOKS_LEAK}, policy=_BOOKS_POLICY,
                   rules=("no-such-rule",))
    assert "books-exactness" in str(ei.value)


def test_allowlist_moves_hits_to_allowed_and_severity_override_applies():
    rep = host_check({"fx": _BOOKS_LEAK}, policy=_BOOKS_POLICY,
                     allow=("books-exactness:fx:Frontend.submit",))
    assert not rep.violations and len(rep.allowed) == 1
    assert rep.ok("error")
    rep2 = host_check(
        {"fx": _BOOKS_LEAK},
        policy=dataclasses_replace_books(severity_overrides={
            "books-exactness": "warn"}),
    )
    assert rep2.violations[0].severity == "warn"


def dataclasses_replace_books(**kw):
    import dataclasses

    return dataclasses.replace(_BOOKS_POLICY, **kw)


# ============================================== the real surface (gate)


def _real_graph():
    return build_package_graph([
        ("serving", os.path.join(REPO, "perceiver_io_tpu", "serving")),
        ("obs", os.path.join(REPO, "perceiver_io_tpu", "obs")),
    ])


def test_real_surface_is_green_with_committed_allowlist():
    """The dogfood gate: the shipped serving/+obs/ code lints clean at
    warn-and-above under the committed reasoned allowlist — every accepted
    hit is a visible suppression, not a weakened rule."""
    allow, entries = load_allowlist(ALLOWLIST)
    rep = host_check(_real_graph(), policy=default_host_policy(),
                     allow=tuple(allow))
    assert rep.ok("warn"), rep.format()
    assert rep.allowed, "suppressions stay visible in the report"
    # the infos that remain are the recorded seam defaults and
    # GIL-point-read notes — never silently dropped
    assert all(v.severity == "info" for v in rep.violations)


def test_real_surface_books_exactness_and_grants_have_no_raw_errors():
    """books-exactness and grant-pairing hold on the real surface with NO
    allowlist help at all — the clean-books invariant and the grant
    protocol are real properties, not suppressed ones."""
    rep = host_check(_real_graph(), policy=default_host_policy(),
                     rules=("books-exactness", "grant-pairing"))
    assert not rep.violations, rep.format()


def test_committed_allowlist_has_no_stale_entries():
    """Every committed suppression still suppresses something — a fixed
    finding must retire its allowlist entry."""
    import fnmatch

    allow, _entries = load_allowlist(ALLOWLIST)
    rep = host_check(_real_graph(), policy=default_host_policy(),
                     allow=tuple(allow))
    for pat in allow:
        assert any(
            fnmatch.fnmatch(v.key, pat) or fnmatch.fnmatch(v.rule, pat)
            for v in rep.allowed
        ), f"stale allowlist entry: {pat}"


def test_reintroduced_books_leak_is_caught_on_real_surface(tmp_path):
    """Regression plant: strip the shed booking out of the real submit()
    and the gate must light up with a rendered CFG path — the exact PR-12
    bug class the rule exists for."""
    src_path = os.path.join(REPO, "perceiver_io_tpu", "serving", "frontend.py")
    with open(src_path) as f:
        src = f.read()
    planted = src.replace('self._n["shed"] += 1', "pass")
    assert planted != src, "plant failed: shed booking not found"
    g = build_host_graph({"serving.frontend": planted})
    rep = host_check(g, policy=default_host_policy(),
                     rules=("books-exactness",))
    leaks = [v for v in rep.violations if "books leak" in v.message]
    assert leaks, "reintroduced shed-booking leak must be caught"
    assert any("path:" in v.message for v in leaks)


# ============================================================== the CLIs


def _run(args, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, cwd=REPO, env=env, **kw)


def test_hostlint_cli_green_on_real_surface():
    r = _run(["tools/hostlint.py", "--fail-on", "warn"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "hostlint ok" in r.stdout


def test_hostlint_cli_exit1_on_planted_fixture(tmp_path):
    fx = tmp_path / "fx"
    fx.mkdir()
    (fx / "planted.py").write_text(_BOOKS_LEAK)
    r = _run(["tools/hostlint.py", "--paths", f"fx={fx}",
              "--no-default-allow", "--rules", "books-exactness"])
    # NOTE: default_host_policy's submit_patterns ("*submit*") match the
    # fixture's submit; the leak must fail the gate
    assert r.returncode == 1, r.stdout + r.stderr
    assert "books leak" in r.stdout


def test_hostlint_cli_json_artifact(tmp_path):
    out = tmp_path / "hostlint.json"
    r = _run(["tools/hostlint.py", "--json", str(out)])
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(out.read_text())
    assert "host" in data and data["host"]["backend"] == "host-ast"


def test_hostlint_cli_crash_is_exit3_not_a_verdict(tmp_path):
    fx = tmp_path / "fx"
    fx.mkdir()
    (fx / "broken.py").write_text("def f(:\n")
    r = _run(["tools/hostlint.py", "--paths", f"fx={fx}",
              "--no-default-allow"])
    assert r.returncode == 3, r.stdout + r.stderr
    assert "crashed" in r.stdout


@pytest.mark.parametrize("tool", ["tools/hostlint.py", "tools/graphlint.py"])
def test_unknown_rule_is_usage_error_for_both_linters(tool):
    """The shared lintcli contract: a typo'd --rules name exits 2 and the
    message lists the registered rules for THAT linter."""
    r = _run([tool, "--rules", "no-such-rule"])
    assert r.returncode == 2, r.stdout + r.stderr
    assert "unknown rule(s) no-such-rule" in r.stderr
    assert "registered rules:" in r.stderr
