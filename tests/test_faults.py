"""Preemption-safe training contracts (training/faults.py, docs/robustness.md).

The chaos harness (tools/chaos.py, ``tasks.py chaos``) certifies the same
behaviors end-to-end as a gate; these tests pin each piece — guard, sentinel
ladder, retry/backoff, quarantine, in-graph skip, trainer wiring — so a
regression names the broken part, not just the broken scenario.
"""

import itertools
import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.training import (
    DivergenceHalt,
    DivergenceSentinel,
    FetchRetriesExhausted,
    MetricsLogger,
    PreemptionGuard,
    QuarantineIterator,
    RetryPolicy,
    SentinelConfig,
    TrainState,
    Trainer,
    TrainerConfig,
    call_with_retry,
    make_optimizer,
)
from perceiver_io_tpu.training.loop import make_train_step


# ---------------------------------------------------------------------------
# fixture: trivial linear-regression step (compiles in milliseconds)
# ---------------------------------------------------------------------------


def loss_fn(params, batch, rng):
    pred = batch["x"] @ params["w"]
    loss = jnp.mean((pred - batch["y"]) ** 2)
    return loss, {"loss": loss}


def fresh_state(seed=0):
    tx = make_optimizer(1e-2)
    return TrainState.create(None, {"w": jnp.zeros((3,))}, tx, jax.random.PRNGKey(seed))


def batches(seed=0, n=3, poison_at=()):
    rng = np.random.default_rng(seed)
    for i in itertools.count(1):
        x = rng.normal(size=(4, n)).astype(np.float32)
        y = (x @ np.ones(n)).astype(np.float32)
        if i in poison_at:
            x = x.copy()
            x[0, 0] = np.nan
        yield {"x": x, "y": y}


def make_trainer(tmp_path, max_steps, sentinel=False, **cfg_kw):
    cfg = TrainerConfig(
        max_steps=max_steps,
        log_interval=1,
        checkpoint_dir=str(tmp_path / "ckpt"),
        prefetch_batches=0,
        input_double_buffer=False,
        graphlint=False,
        sentinel=sentinel,
        **cfg_kw,
    )
    logger = MetricsLogger(str(tmp_path / "logs"), use_tensorboard=False)
    return Trainer(loss_fn, config=cfg, logger=logger)


def record_losses(trainer, hook=None):
    losses = []
    orig = trainer._train_step

    def wrapped(state, batch, _orig=orig):
        state, metrics = _orig(state, batch)
        losses.append(float(metrics["loss"]))
        if hook is not None:
            hook(trainer, state)
        return state, metrics

    trainer._train_step = wrapped
    return losses


def events_of(tmp_path, kind):
    path = tmp_path / "logs" / "events.jsonl"
    if not os.path.exists(path):
        return []
    with open(path) as f:
        rows = [json.loads(line) for line in f]
    return [r for r in rows if r["event"] == kind]


# ---------------------------------------------------------------------------
# PreemptionGuard
# ---------------------------------------------------------------------------


def test_preemption_guard_catches_sigterm_and_uninstall_restores():
    guard = PreemptionGuard(signals=(signal.SIGTERM,))
    before = signal.getsignal(signal.SIGTERM)
    assert guard.install()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.requested
        assert guard.signal_count == 1
    finally:
        guard.uninstall()
    assert signal.getsignal(signal.SIGTERM) == before


def test_preemption_guard_second_sigint_escalates():
    guard = PreemptionGuard(signals=(signal.SIGINT,))
    assert guard.install()
    try:
        signal.raise_signal(signal.SIGINT)
        assert guard.requested  # first: cooperative
        with pytest.raises(KeyboardInterrupt):  # second: previous handler
            signal.raise_signal(signal.SIGINT)
    finally:
        guard.uninstall()


def test_preemption_guard_trip_is_programmatic():
    guard = PreemptionGuard()
    assert not guard.requested
    guard.trip()
    assert guard.requested


# ---------------------------------------------------------------------------
# DivergenceSentinel policy ladder
# ---------------------------------------------------------------------------


def test_sentinel_skip_escalates_to_rollback_then_halt():
    s = DivergenceSentinel(SentinelConfig(skip_limit=3, rollback_limit=1))
    assert s.observe(1, float("nan"), skipped=True).action == "skip"
    assert s.observe(2, float("nan"), skipped=True).action == "skip"
    d = s.observe(3, float("nan"), skipped=True)
    assert d.action == "rollback" and d.reason == "persistent-nonfinite"
    # after a rollback the consecutive counter restarts
    assert s.observe(4, float("nan"), skipped=True).action == "skip"
    assert s.observe(5, float("nan"), skipped=True).action == "skip"
    # second trip exceeds rollback_limit=1 -> halt
    assert s.observe(6, float("nan"), skipped=True).action == "halt"


def test_sentinel_nonfinite_without_skip_goes_straight_to_rollback():
    """No in-graph skip held the update (overlap step): the NaN already
    landed in params — waiting out skip_limit would train on garbage."""
    s = DivergenceSentinel(SentinelConfig(skip_limit=3, in_graph_skip=False))
    d = s.observe(1, float("nan"), skipped=False)
    assert d.action == "rollback" and d.reason == "nonfinite-applied"


def test_sentinel_spike_needs_history_and_patience():
    cfg = SentinelConfig(min_history=5, spike_factor=10.0, spike_patience=2, window=10)
    s = DivergenceSentinel(cfg)
    for i in range(5):
        assert s.observe(i, 1.0, skipped=False).action == "ok"
    d1 = s.observe(6, 100.0, skipped=False)  # spike 1: noted, not tripped
    assert d1.action == "ok" and d1.reason == "spike-noted"
    d2 = s.observe(7, 100.0, skipped=False)  # spike 2: patience reached
    assert d2.action == "rollback" and d2.reason == "loss-spike"
    # an isolated spike between normal losses never escalates
    s2 = DivergenceSentinel(cfg)
    for i in range(5):
        s2.observe(i, 1.0, skipped=False)
    assert s2.observe(6, 100.0, skipped=False).reason == "spike-noted"
    assert s2.observe(7, 1.0, skipped=False).action == "ok"
    assert s2.observe(8, 100.0, skipped=False).reason == "spike-noted"


def test_sentinel_rollback_unavailable_escalates():
    s = DivergenceSentinel(SentinelConfig())
    assert s.notify_rollback_unavailable().action == "halt"


# ---------------------------------------------------------------------------
# retry / backoff
# ---------------------------------------------------------------------------


def test_retry_backoff_schedule_and_exhaustion():
    sleeps = []
    calls = []

    def always_fails():
        calls.append(1)
        raise OSError("flaky")

    policy = RetryPolicy(max_retries=3, base_delay=0.1, max_delay=10.0, jitter=0.25)
    with pytest.raises(FetchRetriesExhausted) as ei:
        call_with_retry(always_fails, policy, sleep=sleeps.append)
    assert len(calls) == 4  # initial + 3 retries
    assert isinstance(ei.value.__cause__, OSError)
    # exponential with bounded jitter: delay(k) in base*2^k * [0.75, 1.25]
    assert len(sleeps) == 3
    for k, d in enumerate(sleeps):
        nominal = 0.1 * 2**k
        assert 0.75 * nominal <= d <= 1.25 * nominal
    # deterministic: the same policy reproduces the same schedule
    sleeps2 = []
    with pytest.raises(FetchRetriesExhausted):
        call_with_retry(always_fails, policy, sleep=sleeps2.append)
    assert sleeps == sleeps2


def test_retry_succeeds_midway_and_reports():
    state = {"left": 2}
    seen = []

    def flaky():
        if state["left"] > 0:
            state["left"] -= 1
            raise TimeoutError("transient")
        return "ok"

    policy = RetryPolicy(max_retries=5, base_delay=0.01)
    out = call_with_retry(flaky, policy, on_retry=lambda a, e, d: seen.append(a), sleep=lambda _: None)
    assert out == "ok"
    assert seen == [0, 1]


def test_retry_policy_delay_jitter_bounds():
    """The probe-spacing contract the serving breaker reuses (ISSUE 12):
    delay(k) = min(base * 2^k, max_delay) scaled by exactly [1-j, 1+j),
    deterministic per (seed, attempt), and the max_delay cap applies BEFORE
    the jitter scale (a capped delay still decorrelates)."""
    policy = RetryPolicy(max_retries=9, base_delay=0.1, max_delay=2.0, jitter=0.25)
    for k in range(10):
        nominal = min(0.1 * 2**k, 2.0)
        d = policy.delay(k)
        assert (1 - 0.25) * nominal <= d <= (1 + 0.25) * nominal, (k, d)
        assert d == policy.delay(k)  # deterministic per attempt
    # deep attempts: capped nominal, jitter still spreads them
    deep = {policy.delay(k) for k in range(6, 10)}
    assert len(deep) > 1 and all(1.5 <= d <= 2.5 for d in deep)
    # jitter=0: the exact uncapped/capped schedule, no randomness
    exact = RetryPolicy(base_delay=0.1, max_delay=2.0, jitter=0.0)
    assert [exact.delay(k) for k in range(6)] == [0.1, 0.2, 0.4, 0.8, 1.6, 2.0]
    # different seeds draw different scales at the same attempt
    assert RetryPolicy(jitter=0.25, seed=1).delay(0) != RetryPolicy(jitter=0.25, seed=2).delay(0)


def test_call_with_retry_reraise_original_for_serving_path():
    """The serving-path mode (ISSUE 12): ``reraise=True`` re-raises the
    ORIGINAL exception instance on exhaustion — the front end (and the
    breaker's half-open probes riding it) classify terminal outcomes by the
    real exception type, never a retry wrapper. The loader default is
    unchanged: one stable ``FetchRetriesExhausted`` with the cause chained."""
    boom = OSError("persistent store outage")
    calls, seen = [], []

    def always_fails():
        calls.append(1)
        raise boom

    policy = RetryPolicy(max_retries=2, base_delay=0.01)
    with pytest.raises(OSError) as ei:
        call_with_retry(always_fails, policy, on_retry=lambda a, e, d: seen.append(a),
                        sleep=lambda _: None, reraise=True)
    assert ei.value is boom  # the exact instance, not a wrapper
    assert len(calls) == 3 and seen == [0, 1]
    # default mode still wraps (the Batches/loader contract is untouched)
    with pytest.raises(FetchRetriesExhausted) as ei:
        call_with_retry(always_fails, policy, sleep=lambda _: None)
    assert ei.value.__cause__ is boom


def test_retry_non_transient_propagates_immediately():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("a bug, not flakiness")

    with pytest.raises(ValueError):
        call_with_retry(bad, RetryPolicy(max_retries=5), sleep=lambda _: None)
    assert len(calls) == 1


def test_fetch_retry_emitter_writes_events(tmp_path):
    from perceiver_io_tpu.obs.events import EventLog
    from perceiver_io_tpu.training import fetch_retry_emitter

    log = EventLog(str(tmp_path), main_process=True)
    on_retry = fetch_retry_emitter(log)
    state = {"left": 1}

    def flaky():
        if state["left"] > 0:
            state["left"] -= 1
            raise OSError("blip")
        return 42

    assert call_with_retry(flaky, RetryPolicy(base_delay=0.0), on_retry=on_retry, sleep=lambda _: None) == 42
    with open(tmp_path / "events.jsonl") as f:
        rows = [json.loads(line) for line in f]
    assert len(rows) == 1 and rows[0]["event"] == "fault.fetch_retry"
    assert rows[0]["attempt"] == 0 and "blip" in rows[0]["error"]


def test_batches_retry_absorbs_transient_fetch_errors():
    from perceiver_io_tpu.data.loader import Batches

    class Flaky:
        def __init__(self, fail_index, failures):
            self.fail_index = fail_index
            self.failures = failures

        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == self.fail_index and self.failures > 0:
                self.failures -= 1
                raise OSError("transient")
            return {"x": np.full((2,), i, np.float32)}

    clean = list(Batches(Flaky(5, 0), 2))
    retries = []
    resilient = list(
        Batches(
            Flaky(5, 2), 2,
            retry=RetryPolicy(max_retries=3, base_delay=0.0, jitter=0.0),
            on_retry=lambda a, e, d: retries.append(a),
        )
    )
    assert len(retries) == 2
    assert len(resilient) == len(clean)
    for a, b in zip(clean, resilient):
        np.testing.assert_array_equal(a["x"], b["x"])
    # exhausted retries surface as FetchRetriesExhausted, not silence
    with pytest.raises(FetchRetriesExhausted):
        list(Batches(Flaky(5, 99), 2, retry=RetryPolicy(max_retries=1, base_delay=0.0)))


# ---------------------------------------------------------------------------
# poison-batch quarantine
# ---------------------------------------------------------------------------


def test_quarantine_iterator_drops_poison_and_names_leaf():
    good = {"x": np.ones(3, np.float32), "ids": np.arange(3)}
    poison = {"x": np.array([1.0, np.nan, 2.0], np.float32), "ids": np.arange(3)}
    seen = []
    it = QuarantineIterator(
        iter([good, poison, good]), on_quarantine=lambda path, n: seen.append((path, n))
    )
    out = list(it)
    assert len(out) == 2
    assert it.n_quarantined == 1
    assert seen and "x" in seen[0][0]
    # int leaves can't be "non-finite": an all-int poison candidate passes
    assert QuarantineIterator(iter([{"ids": np.arange(3)}])).__next__() is not None


def test_quarantine_iterator_bounds_consecutive_drops():
    poison = {"x": np.array([np.nan], np.float32)}
    it = QuarantineIterator(itertools.repeat(poison), max_consecutive=4)
    with pytest.raises(RuntimeError, match="consecutive poison"):
        next(it)
    assert it.n_quarantined == 4


# ---------------------------------------------------------------------------
# in-graph sentinel skip (make_train_step(sentinel=True))
# ---------------------------------------------------------------------------


def test_in_graph_skip_holds_params_and_advances_step():
    step = make_train_step(loss_fn, donate=False, sentinel=True)
    state = fresh_state()
    gen = batches()
    clean = next(gen)
    state1, m1 = step(state, clean)
    assert float(m1["sentinel_skipped"]) == 0.0
    assert int(state1.step) == 1
    assert not np.array_equal(np.asarray(state1.params["w"]), np.asarray(state.params["w"]))

    poison = {k: v.copy() for k, v in next(gen).items()}
    poison["x"][0, 0] = np.nan
    state2, m2 = step(state1, poison)
    assert float(m2["sentinel_skipped"]) == 1.0
    assert int(state2.step) == 2  # step advances: the batch schedule holds
    np.testing.assert_array_equal(
        np.asarray(state2.params["w"]), np.asarray(state1.params["w"])
    )
    for a, b in zip(jax.tree.leaves(state2.opt_state), jax.tree.leaves(state1.opt_state)):
        if hasattr(a, "shape") and a.shape:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # rng still advanced (dropout streams must not repeat the skipped draw)
    assert not np.array_equal(np.asarray(state2.rng), np.asarray(state1.rng))

    # and the skipped trajectory continues finitely
    state3, m3 = step(state2, next(gen))
    assert np.isfinite(float(m3["loss"]))


def test_sentinel_rejected_with_overlap():
    with pytest.raises(ValueError, match="overlap"):
        make_train_step(loss_fn, overlap=object(), sentinel=True)


# ---------------------------------------------------------------------------
# trainer wiring: preempt -> auto-resume equivalence, rollback, halt
# ---------------------------------------------------------------------------


def test_trainer_preempt_then_auto_resume_matches_uninterrupted(tmp_path):
    n_steps, kill_at = 10, 4

    ref_tr = make_trainer(tmp_path / "ref", n_steps)
    ref = record_losses(ref_tr)
    ref_tr.fit(fresh_state(), batches())
    ref_tr.close()

    run = tmp_path / "run"
    t1 = make_trainer(run, n_steps)

    def trip(trainer, state):
        if int(state.step) == kill_at:
            trainer._preempt_guard.trip()

    part1 = record_losses(t1, hook=trip)
    out1 = t1.fit(fresh_state(), batches())
    t1.close()
    assert int(out1.step) == kill_at
    assert events_of(run, "fault.preempt")
    fe = events_of(run, "fit_end")
    assert fe[-1]["preempted"] is True and fe[-1]["aborted"] is False

    t2 = make_trainer(run, n_steps)
    part2 = record_losses(t2)
    out2 = t2.fit(fresh_state(), batches(), resume="auto")
    t2.close()
    assert int(out2.step) == n_steps
    ev = events_of(run, "resume")
    assert ev[-1] == {
        **ev[-1],
        "from_step": 0,
        "to_step": kill_at,
        "fast_forward_batches": kill_at,
    }
    combined = part1 + part2
    assert len(combined) == len(ref)
    assert max(abs(a - b) for a, b in zip(ref, combined)) <= 1e-6

    # metrics.csv: truncation + re-logging leaves each step exactly once
    import csv

    with open(run / "logs" / "metrics.csv", newline="") as f:
        steps = [int(float(r["step"])) for r in csv.DictReader(f)]
    assert steps == list(range(1, n_steps + 1))


def test_trainer_auto_resume_without_checkpoint_starts_fresh(tmp_path):
    tr = make_trainer(tmp_path, 3)
    losses = record_losses(tr)
    out = tr.fit(fresh_state(), batches(), resume="auto")
    tr.close()
    assert int(out.step) == 3 and len(losses) == 3
    assert not events_of(tmp_path, "resume")


def test_trainer_sentinel_skip_event_and_recovery(tmp_path):
    tr = make_trainer(tmp_path, 6, sentinel=True)
    losses = record_losses(tr)
    tr.fit(fresh_state(), batches(poison_at=(3,)))
    tr.close()
    skips = events_of(tmp_path, "fault.skip")
    assert len(skips) == 1 and skips[0]["step"] == 3
    assert np.isfinite(losses[3:]).all()


def test_trainer_sentinel_rollback_restores_checkpoint(tmp_path):
    tr = make_trainer(
        tmp_path, 8,
        sentinel=SentinelConfig(skip_limit=2, rollback_limit=2),
        val_interval=3,
    )
    losses = record_losses(tr)
    tr.fit(
        fresh_state(),
        batches(poison_at=(5, 6)),
        val_loader=[next(batches(seed=7))],
    )
    tr.close()
    rb = events_of(tmp_path, "fault.rollback")
    assert len(rb) == 1
    assert rb[0]["from_step"] == 6 and rb[0]["to_step"] == 3
    assert rb[0]["reason"] == "persistent-nonfinite"
    assert np.isfinite(losses[-1])


def test_trainer_rollback_reinits_optimizer_for_weights_only_checkpoints(tmp_path):
    """A weights-only checkpoint cannot restore moments, so rollback must
    REINITIALIZE the optimizer instead of replaying with the (possibly
    poisoned) diverged moments (code-review finding)."""
    tr = make_trainer(
        tmp_path, 8,
        sentinel=SentinelConfig(skip_limit=2, rollback_limit=2),
        val_interval=3,
        save_weights_only=True,
    )
    losses = record_losses(tr)
    tr.fit(
        fresh_state(),
        batches(poison_at=(5, 6)),
        val_loader=[next(batches(seed=7))],
    )
    tr.close()
    rb = events_of(tmp_path, "fault.rollback")
    assert len(rb) == 1 and rb[0]["opt_reinit"] is True
    assert np.isfinite(losses[-1])


def test_trainer_sentinel_halt_raises_and_emits(tmp_path):
    tr = make_trainer(
        tmp_path, 8,
        sentinel=SentinelConfig(skip_limit=1, rollback_limit=0),
        val_interval=2,
    )
    with pytest.raises(DivergenceHalt):
        tr.fit(
            fresh_state(),
            batches(poison_at=tuple(range(3, 100))),
            val_loader=[next(batches(seed=7))],
        )
    tr.close()
    assert events_of(tmp_path, "fault.halt")
    fe = events_of(tmp_path, "fit_end")
    assert fe and fe[-1]["aborted"] is True


def test_trainer_halt_when_no_checkpoint_to_roll_back_to(tmp_path):
    cfg = TrainerConfig(
        max_steps=6, log_interval=1, prefetch_batches=0, input_double_buffer=False,
        graphlint=False, sentinel=SentinelConfig(skip_limit=1),
    )
    tr = Trainer(loss_fn, config=cfg, logger=MetricsLogger(str(tmp_path / "l"), use_tensorboard=False))
    with pytest.raises(DivergenceHalt):
        tr.fit(fresh_state(), batches(poison_at=(2,)))
    tr.close()


def test_trainer_quarantines_poison_batches(tmp_path):
    tr = make_trainer(tmp_path, 5, quarantine_poison_batches=True)
    losses = record_losses(tr)
    tr.fit(fresh_state(), batches(poison_at=(2,)))
    tr.close()
    ev = events_of(tmp_path, "fault.poison_batch")
    assert len(ev) == 1 and "x" in ev[0]["leaf"]
    assert np.isfinite(losses).all()  # the poison batch never reached the step


# ---------------------------------------------------------------------------
# MetricsLogger.truncate_after
# ---------------------------------------------------------------------------


def test_metrics_truncate_after(tmp_path):
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    for step in (1, 2, 3, 4):
        logger.log(step, {"loss": float(step)})
    assert logger.truncate_after(2) == 2
    import csv

    with open(tmp_path / "metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert [int(float(r["step"])) for r in rows] == [1, 2]
    # idempotent + appendable afterwards
    assert logger.truncate_after(2) == 0
    logger.log(3, {"loss": 3.0})
    with open(tmp_path / "metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert [int(float(r["step"])) for r in rows] == [1, 2, 3]
