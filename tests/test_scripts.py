"""L5 CLI layer tests: dataclass auto-flags, YAML defaults, link rules, and
tiny end-to-end `fit` runs per task (reference test strategy category 2/6,
SURVEY §4)."""

import argparse
from pathlib import Path

import numpy as np
import pytest

from perceiver_io_tpu.scripts import cli


# ---------------------------------------------------------------- engine


def test_dataclass_args_roundtrip():
    from perceiver_io_tpu.models.text import TextEncoderConfig

    parser = argparse.ArgumentParser()
    cli.add_dataclass_args(parser, TextEncoderConfig, "model.encoder")
    ns = parser.parse_args(
        [
            "--model.encoder.num_cross_attention_heads=4",
            "--model.encoder.num_cross_attention_qk_channels=None",
            "--model.encoder.freeze=true",
            "--model.encoder.vocab_size=262",
        ]
    )
    config = cli.build_dataclass(TextEncoderConfig, ns, "model.encoder")
    assert config.num_cross_attention_heads == 4
    assert config.num_cross_attention_qk_channels is None
    assert config.freeze is True
    assert config.vocab_size == 262
    # untouched fields keep dataclass defaults
    assert config.num_self_attention_layers_per_block == 8


def test_tuple_field_parsing():
    from perceiver_io_tpu.models.vision.image_classifier import ImageEncoderConfig

    parser = argparse.ArgumentParser()
    cli.add_dataclass_args(parser, ImageEncoderConfig, "enc")
    ns = parser.parse_args(["--enc.image_shape=32,32,3"])
    config = cli.build_dataclass(ImageEncoderConfig, ns, "enc")
    assert config.image_shape == (32, 32, 3)


def test_yaml_defaults_and_override(tmp_path):
    cfg = tmp_path / "defaults.yaml"
    cfg.write_text("trainer:\n  max_steps: 7\noptimizer:\n  lr: 0.5\n")
    parser = cli.make_parser("test")
    ns = cli.parse_args(parser, ["fit", "--config", str(cfg), "--optimizer.lr=0.25"])
    trainer = cli.build_dataclass(cli.TrainerArgs, ns, "trainer")
    opt = cli.build_dataclass(cli.OptimizerArgs, ns, "optimizer")
    assert trainer.max_steps == 7  # from yaml
    assert opt.lr == 0.25  # explicit flag wins over yaml


def test_yaml_unknown_key_rejected(tmp_path):
    cfg = tmp_path / "bad.yaml"
    cfg.write_text("trainer:\n  nonexistent_flag: 1\n")
    parser = cli.make_parser("test")
    with pytest.raises(ValueError, match="unknown keys"):
        cli.parse_args(parser, ["fit", "--config", str(cfg)])


def test_lr_schedule_linked_to_max_steps():
    opt = cli.OptimizerArgs(lr=1.0, lr_scheduler="cosine_with_warmup", warmup_steps=0, training_steps=None)
    schedule = cli.make_lr_schedule(opt, max_steps=100)
    assert float(schedule(100)) == pytest.approx(0.0, abs=1e-6)


def test_make_mesh_for_strategies():
    trainer = cli.TrainerArgs(strategy="dp")
    mesh = cli.make_mesh_for(trainer)
    assert mesh is not None and mesh.shape["data"] == 8
    mesh = cli.make_mesh_for(cli.TrainerArgs(strategy="fsdp"))
    assert mesh.shape["fsdp"] == 8 and mesh.shape["data"] == 1
    mesh = cli.make_mesh_for(cli.TrainerArgs(strategy="tp"))
    assert mesh.shape["tensor"] == 8
    mesh = cli.make_mesh_for(cli.TrainerArgs(strategy="fsdp_tp"))
    assert mesh.shape["tensor"] == 2 and mesh.shape["fsdp"] == 4
    with pytest.raises(ValueError, match="unknown strategy"):
        cli.make_mesh_for(cli.TrainerArgs(strategy="nope"))


# ---------------------------------------------------------- end-to-end fits


def _tiny_trainer_flags(tmp_path, steps=3):
    return [
        "--trainer.devices=1",
        f"--trainer.max_steps={steps}",
        "--trainer.log_interval=1",
        f"--trainer.default_root_dir={tmp_path}",
        "--trainer.checkpoint=false",
        "--optimizer.warmup_steps=1",
    ]


def test_clm_cli_fit(tmp_path):
    from perceiver_io_tpu.scripts.text.clm import main

    train_file = tmp_path / "train.txt"
    train_file.write_text("hello world, this is a tiny corpus. " * 40)
    state, _ = main(
        [
            "fit",
            "--data.dataset=textfile",
            f"--data.train_file={train_file}",
            "--data.max_seq_len=32",
            "--data.batch_size=2",
            f"--data.cache_dir={tmp_path / 'cache'}",
            "--model.max_latents=8",
            "--model.num_channels=32",
            "--model.num_self_attention_layers=1",
            "--model.num_heads=2",
            "--task.sample_prompt=hello",
            "--task.num_sample_tokens=4",
            "--trainer.val_interval=3",
            *_tiny_trainer_flags(tmp_path),
        ]
    )
    assert int(state.step) == 3
    # metrics were written
    metrics_files = list(Path(tmp_path).rglob("metrics.csv"))
    assert metrics_files, "expected a metrics.csv in the run dir"


@pytest.mark.slow  # long-compile; the fast subset keeps one representative of this path
def test_mlm_cli_fit(tmp_path):
    from perceiver_io_tpu.scripts.text.mlm import main as mlm_main
    from perceiver_io_tpu.training.checkpoint import save_pretrained

    (tmp_path / "train.txt").write_text("tiny text corpus for masking " * 50)
    mlm_state, _ = mlm_main(
        [
            "fit",
            "--data.dataset=textfile",
            f"--data.train_file={tmp_path / 'train.txt'}",
            "--data.max_seq_len=16",
            "--data.batch_size=2",
            f"--data.cache_dir={tmp_path / 'cache'}",
            "--model.encoder.num_input_channels=16",
            "--model.encoder.num_self_attention_layers_per_block=1",
            "--model.num_latents=4",
            "--model.num_latent_channels=16",
            *_tiny_trainer_flags(tmp_path, steps=2),
        ]
    )
    assert int(mlm_state.step) == 2
    save_pretrained(str(tmp_path / "mlm_artifact"), mlm_state.params)
    assert (tmp_path / "mlm_artifact" / "params.msgpack").exists() or list(
        (tmp_path / "mlm_artifact").iterdir()
    )


def test_classifier_encoder_warm_start_and_freeze(tmp_path):
    """Encoder params copied from an MLM artifact stay frozen during training
    (reference: text/classifier/lightning.py:28-36, requires_grad=False)."""
    import jax

    from perceiver_io_tpu.core.config import ClassificationDecoderConfig, PerceiverIOConfig
    from perceiver_io_tpu.data.text.datamodule import TextDataModule
    from perceiver_io_tpu.models.text import MaskedLanguageModel, TextClassifier, TextEncoderConfig
    from perceiver_io_tpu.models.text.mlm import TextDecoderConfig
    from perceiver_io_tpu.scripts import cli as cli_mod
    from perceiver_io_tpu.scripts.text.classifier import ENCODER_SUBTREES, make_warm_start
    from perceiver_io_tpu.training.checkpoint import save_pretrained
    from perceiver_io_tpu.training.losses import classification_loss_fn

    encoder_cfg = TextEncoderConfig(
        vocab_size=262,
        max_seq_len=16,
        num_input_channels=16,
        num_self_attention_layers_per_block=1,
        freeze=True,
    )
    mlm = MaskedLanguageModel(
        PerceiverIOConfig(
            encoder=encoder_cfg,
            decoder=TextDecoderConfig(vocab_size=262, max_seq_len=16),
            num_latents=4,
            num_latent_channels=16,
        )
    )
    mlm_params = mlm.init(jax.random.PRNGKey(0), np.zeros((1, 16), np.int32))
    save_pretrained(str(tmp_path / "mlm"), mlm_params)

    clf = TextClassifier(
        PerceiverIOConfig(
            encoder=encoder_cfg,
            decoder=ClassificationDecoderConfig(num_output_query_channels=16, num_classes=2),
            num_latents=4,
            num_latent_channels=16,
        )
    )
    params = clf.init(jax.random.PRNGKey(1), np.zeros((1, 16), np.int32))
    warm = make_warm_start(None, str(tmp_path / "mlm"))
    params = warm(params)

    # encoder subtree equals the MLM artifact's
    for sub in ENCODER_SUBTREES:
        a = jax.tree_util.tree_leaves(params["params"][sub])
        b = jax.tree_util.tree_leaves(mlm_params["params"][sub])
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y))

    # short fit with freeze: encoder unchanged, decoder changed
    data = TextDataModule(
        task="clf",
        max_seq_len=16,
        batch_size=2,
        train_texts=[("good movie", 1), ("bad movie", 0)] * 4,
        valid_texts=[("fine film", 1)] * 2,
    )
    from perceiver_io_tpu.training.optim import freeze_mask, make_optimizer
    from perceiver_io_tpu.training.state import TrainState
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    mask = freeze_mask(params, ENCODER_SUBTREES)
    tx = make_optimizer(1e-2, frozen_mask=mask)
    state = TrainState.create(clf.apply, params, tx, jax.random.PRNGKey(2))
    trainer = Trainer(classification_loss_fn(clf.apply), config=TrainerConfig(max_steps=3, log_interval=10))
    before = jax.device_get(params)
    state = trainer.fit(state, cli_mod.cycle(data.train_batches()))
    after = jax.device_get(state.params)
    for sub in ENCODER_SUBTREES:
        a = jax.tree_util.tree_leaves(before["params"][sub])
        b = jax.tree_util.tree_leaves(after["params"][sub])
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    dec_before = jax.tree_util.tree_leaves(before["params"]["decoder"])
    dec_after = jax.tree_util.tree_leaves(after["params"]["decoder"])
    assert any(not np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(dec_before, dec_after))


@pytest.mark.slow  # long-compile; the fast subset keeps one representative of this path
def test_image_classifier_cli_fit(tmp_path):
    from perceiver_io_tpu.scripts.vision.image_classifier import main

    state, _ = main(
        [
            "fit",
            "--data.synthetic=true",
            "--data.batch_size=4",
            "--model.num_latents=4",
            "--model.num_latent_channels=16",
            "--model.encoder.num_self_attention_layers_per_block=1",
            "--model.encoder.num_frequency_bands=4",
            "--model.encoder.num_cross_attention_heads=1",
            "--model.decoder.num_output_query_channels=16",
            *_tiny_trainer_flags(tmp_path),
        ]
    )
    assert int(state.step) == 3


def test_preproc_cli(tmp_path):
    from perceiver_io_tpu.scripts.text.preproc import main

    train_file = tmp_path / "t.txt"
    train_file.write_text("some text for preprocessing " * 20)
    main(
        [
            "textfile",
            "--task=clm",
            f"--data.train_file={train_file}",
            f"--data.cache_dir={tmp_path / 'cache'}",
            "--data.max_seq_len=16",
        ]
    )
    assert list((tmp_path / "cache").glob("preproc-*.npz"))


@pytest.mark.slow  # long-compile; the fast subset keeps one representative of this path
def test_resume_from_weights_only_checkpoint(tmp_path):
    """Resuming full-state training from a weights-only checkpoint restores
    params and starts the optimizer fresh (Lightning save_weights_only
    semantics) instead of erroring."""
    from perceiver_io_tpu.scripts.text.clm import main

    train_file = tmp_path / "train.txt"
    train_file.write_text("resume me please. " * 60)
    common = [
        "--data.dataset=textfile",
        f"--data.train_file={train_file}",
        "--data.max_seq_len=32",
        "--data.batch_size=2",
        f"--data.cache_dir={tmp_path / 'cache'}",
        "--model.max_latents=8",
        "--model.num_channels=32",
        "--model.num_self_attention_layers=1",
        "--model.num_heads=2",
        "--trainer.devices=1",
        "--trainer.log_interval=10",
        f"--trainer.default_root_dir={tmp_path}",
        "--trainer.name=resume_run",
        "--optimizer.warmup_steps=1",
    ]
    state, _ = main(["fit", "--trainer.max_steps=2", "--trainer.val_interval=2", *common])
    assert int(state.step) == 2
    # second run: save_weights_only defaults true in trainer.yaml; resume anyway
    state2, _ = main(
        [
            "fit",
            "--trainer.max_steps=4",
            "--trainer.val_interval=4",
            "--trainer.resume=true",
            "--trainer.save_weights_only=false",
            *common,
        ]
    )
    assert int(state2.step) == 4


@pytest.mark.slow  # long-compile; the fast subset keeps one representative of this path
def test_validate_restores_checkpoint(tmp_path):
    """`validate` evaluates the checkpointed weights, not the fresh init
    (the Lightning `validate --ckpt_path` analog)."""
    from perceiver_io_tpu.scripts.vision.image_classifier import main

    common = [
        "--data.synthetic=true",
        "--data.batch_size=4",
        "--model.num_latents=4",
        "--model.num_latent_channels=16",
        "--model.encoder.num_self_attention_layers_per_block=1",
        "--model.encoder.num_frequency_bands=4",
        "--model.encoder.num_cross_attention_heads=1",
        "--model.decoder.num_output_query_channels=16",
        "--trainer.devices=1",
        "--trainer.log_interval=10",
        f"--trainer.default_root_dir={tmp_path}",
        "--trainer.name=valrun",
        "--optimizer.warmup_steps=1",
    ]
    state, _ = main(["fit", "--trainer.max_steps=2", "--trainer.val_interval=2", *common])
    state2, metrics = main(["validate", *common])
    assert int(state2.step) == 2  # restored, not fresh
    assert "val_loss" in metrics


def test_validate_command(tmp_path):
    from perceiver_io_tpu.scripts.vision.image_classifier import main

    state, metrics = main(
        [
            "validate",
            "--data.synthetic=true",
            "--data.batch_size=4",
            "--model.num_latents=4",
            "--model.num_latent_channels=16",
            "--model.encoder.num_self_attention_layers_per_block=1",
            "--model.encoder.num_frequency_bands=4",
            "--model.encoder.num_cross_attention_heads=1",
            "--model.decoder.num_output_query_channels=16",
            *_tiny_trainer_flags(tmp_path),
        ]
    )
    assert "val_loss" in metrics and "val_acc" in metrics


def test_img_clf_default_heads_build(tmp_path):
    """The script's DEFAULT attention-head presets must build a valid model:
    the Fourier feature width (131 for MNIST at 32 bands) is the default
    cross-attention qk width and is not divisible by a multi-head split, so
    the paper preset pins 1 cross-attention head
    (reference: perceiver/scripts/vision/image_classifier.py:20-26).
    Regression: runs the real CLI with no head overrides."""
    from perceiver_io_tpu.scripts.vision.image_classifier import main

    state, _ = main(
        [
            "fit",
            "--data.synthetic=true",
            "--data.batch_size=2",
            "--model.num_latents=4",
            "--model.num_latent_channels=16",
            # keep the default 28x28x1 / 32-band adapter (width 131) and the
            # default head counts — the point of the test; layer count is NOT
            # under test, so shrink it (8-layer default costs ~30s of compile)
            "--model.encoder.num_self_attention_layers_per_block=1",
            "--trainer.devices=1",
            "--trainer.max_steps=1",
            "--trainer.log_interval=1",
            f"--trainer.default_root_dir={tmp_path}",
            "--trainer.checkpoint=false",
        ]
    )
    assert int(state.step) == 1


def test_make_mesh_for_ring_strategy():
    import jax

    mesh = cli.make_mesh_for(cli.TrainerArgs(strategy="ring"))
    assert mesh.shape["seq"] == len(jax.devices())


@pytest.mark.slow
def test_clm_cli_fit_ring(tmp_path):
    """--trainer.strategy=ring end-to-end: the CLM CLI trains through the
    explicit shard_map sequence-parallel path (VERDICT r3 item 6)."""
    from perceiver_io_tpu.scripts.text.clm import main

    train_file = tmp_path / "train.txt"
    train_file.write_text("hello world, this is a tiny corpus. " * 40)
    state, _ = main(
        [
            "fit",
            "--data.dataset=textfile",
            f"--data.train_file={train_file}",
            "--data.max_seq_len=40",  # prefix 32 divides the 8-device seq axis
            "--data.batch_size=2",
            f"--data.cache_dir={tmp_path / 'cache'}",
            "--model.max_latents=8",
            "--model.num_channels=32",
            "--model.num_self_attention_layers=1",
            "--model.num_heads=2",
            "--model.cross_attention_dropout=0.0",
            "--task.sample_prompt=hello",
            "--task.num_sample_tokens=4",
            "--trainer.strategy=ring",
            *_tiny_trainer_flags(tmp_path),
        ]
    )
    assert int(state.step) == 3


def test_ring_strategy_rejected_without_builder(tmp_path):
    """Tasks with no sequence-parallel route reject strategy=ring loudly."""
    import numpy as np

    from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
    from perceiver_io_tpu.training.losses import clm_loss_fn

    config = CausalLanguageModelConfig(
        vocab_size=16, max_seq_len=16, max_latents=8, num_channels=32,
        num_heads=2, num_self_attention_layers=1,
    )
    model = CausalLanguageModel(config)
    with pytest.raises(ValueError, match="strategy 'ring'"):
        cli.run_training(
            model,
            config,
            lambda apply_fn: clm_loss_fn(apply_fn, 8),
            {"x": np.zeros((1, 16), np.int32), "prefix_len": 8,
             "pad_mask": np.zeros((1, 16), bool)},
            iter([]),
            [],
            cli.TrainerArgs(strategy="ring", default_root_dir=str(tmp_path)),
            cli.OptimizerArgs(),
        )
