"""The vision encoder's fused split-kv input route must equal the standard
concat route exactly: same parameter tree, same logits, same gradients.

The fused route (CrossAttention.split_kv_projection +
CrossAttentionLayer.call_with_split_kv) folds the constant Fourier features
through the kv LayerNorm algebra into the k/v projections so the (B, M, C)
concatenated input never materializes — ~14 ms/step of input machinery on
the 224x224 image bench (docs/performance.md round-4)."""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.core import modules
from perceiver_io_tpu.core.config import ClassificationDecoderConfig
from perceiver_io_tpu.models.vision.image_classifier import (
    ImageClassifier,
    ImageClassifierConfig,
    ImageEncoderConfig,
)
from perceiver_io_tpu.ops.flash_attention import default_flash


def build(heads=1, dropout=0.0):
    # num_latents/image sizes chosen to PASS flash_supported (nq, nkv >= 128):
    # the split gate must actually engage, or the equivalence checks are vacuous
    config = ImageClassifierConfig(
        encoder=ImageEncoderConfig(
            image_shape=(16, 16, 3),
            num_frequency_bands=8,
            num_cross_attention_heads=heads,
            num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
            num_self_attention_blocks=1,
            dropout=dropout,
        ),
        decoder=ClassificationDecoderConfig(
            num_classes=4, num_output_query_channels=32, num_cross_attention_heads=1
        ),
        num_latents=128,
        num_latent_channels=32,
    )
    model = ImageClassifier(config)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 16, 16, 3)), jnp.float32)
    return model, x


@contextlib.contextmanager
def count_split_calls():
    """Spy on the fused route so tests can assert it actually ran."""
    calls = []
    orig = modules.CrossAttentionLayer.call_with_split_kv

    def spy(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    modules.CrossAttentionLayer.call_with_split_kv = spy
    try:
        yield calls
    finally:
        modules.CrossAttentionLayer.call_with_split_kv = orig


def test_fused_route_matches_standard():
    model, x = build()
    with default_flash(False):  # standard: einsum path, concat input
        params = model.init(jax.random.PRNGKey(0), x)
        logits_std = model.apply(params, x)
    with default_flash(True), count_split_calls() as calls:
        # fused split-kv route (flash interpret on CPU)
        params_fused = model.init(jax.random.PRNGKey(0), x)
        logits_fused = model.apply(params, x)
    assert calls, "split gate did not engage — the comparison is vacuous"

    # identical parameter trees: one checkpoint layout serves both routes
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(params_fused)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params_fused)):
        assert a.shape == b.shape

    np.testing.assert_allclose(
        np.asarray(logits_fused), np.asarray(logits_std), atol=2e-5, rtol=2e-5
    )


def test_fused_route_gradients_match():
    model, x = build()
    y = jnp.asarray([1, 3])

    def loss(params, flash):
        with default_flash(flash):
            logits = model.apply(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    with default_flash(False):
        params = model.init(jax.random.PRNGKey(0), x)
    g_std = jax.grad(loss)(params, False)
    with count_split_calls() as calls:
        g_fused = jax.grad(loss)(params, True)
    assert calls, "split gate did not engage — the comparison is vacuous"
    for (p, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(g_std), jax.tree_util.tree_leaves_with_path(g_fused)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4, err_msg=str(p)
        )


def test_multihead_falls_back_to_standard():
    """heads > 1 cannot use the per-head channel-pad trick — the encoder must
    fall back (and still agree with itself across flash on/off)."""
    model, x = build(heads=2)  # qk 37 not divisible by 2 -> force qk to 32
    config = model.config
    config.encoder.num_cross_attention_qk_channels = 32
    model = ImageClassifier(config)
    with default_flash(False):
        params = model.init(jax.random.PRNGKey(0), x)
        a = model.apply(params, x)
    with default_flash(True):
        b = model.apply(params, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5)


def test_split_adapter_contract():
    from perceiver_io_tpu.models.vision.image_classifier import ImageInputAdapter

    adapter = ImageInputAdapter(image_shape=(8, 8, 3), num_frequency_bands=4)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 8, 3)), jnp.float32)
    params = adapter.init(jax.random.PRNGKey(0), x)
    full = adapter.apply(params, x)
    x_pix, enc = adapter.apply(params, x, method="split")
    rebuilt = jnp.concatenate(
        [x_pix, jnp.broadcast_to(enc[None], x_pix.shape[:2] + (enc.shape[-1],))], axis=-1
    )
    np.testing.assert_allclose(np.asarray(full), np.asarray(rebuilt), atol=0)
