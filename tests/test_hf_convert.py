"""Conversion-equivalence tests against Hugging Face ``transformers``
Perceiver models — the offline analog of the reference's network-dependent
conversion tests (reference: tests/masked_language_model_convert_test.py,
tests/image_classifier_convert_test.py, tests/optical_flow_test.py:28-36).

Small HF models are instantiated locally (random init, no downloads), their
weights converted into our Flax trees, and predictions compared allclose at
the same tolerance the reference uses for its conversions (atol/rtol 1e-4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from transformers import PerceiverConfig  # noqa: E402
from transformers.models.perceiver.modeling_perceiver import (  # noqa: E402
    PerceiverForImageClassificationFourier,
    PerceiverForMaskedLM,
    PerceiverForOpticalFlow,
)

from perceiver_io_tpu.hf import (  # noqa: E402
    convert_image_classifier,
    convert_masked_language_model,
    convert_optical_flow,
)

ATOL = 1e-4
RTOL = 1e-4


def _hf_mlm():
    config = PerceiverConfig(
        num_latents=8,
        d_latents=32,
        d_model=24,
        num_blocks=1,
        num_self_attends_per_block=2,
        num_self_attention_heads=4,
        num_cross_attention_heads=4,
        qk_channels=None,
        v_channels=None,
        vocab_size=262,
        max_position_embeddings=48,
        attention_probs_dropout_prob=0.0,
        # sensitize: encoder widening != the HF decoder's hardcoded 1
        cross_attention_widening_factor=2,
        self_attention_widening_factor=3,
    )
    model = PerceiverForMaskedLM(config)
    model.eval()
    return model


class TestMaskedLanguageModel:
    @pytest.fixture(scope="class")
    def converted(self):
        hf_model = _hf_mlm()
        config, variables = convert_masked_language_model(hf_model)

        from perceiver_io_tpu.models.text.mlm import MaskedLanguageModel

        return hf_model, MaskedLanguageModel(config), variables

    def test_parameter_count(self, converted):
        hf_model, _, variables = converted
        n_src = sum(p.numel() for p in hf_model.parameters())
        n_tgt = sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(variables))
        assert n_tgt == n_src

    def test_prediction_equivalence(self, converted):
        hf_model, model, variables = converted
        rng = np.random.default_rng(0)
        x = rng.integers(0, 262, size=(2, 48))

        with torch.no_grad():
            ref = hf_model(input_ids=torch.tensor(x)).logits.numpy()
        out = np.asarray(model.apply(variables, jnp.asarray(x)))
        np.testing.assert_allclose(out, ref[:, : x.shape[1]], atol=ATOL, rtol=RTOL)

    def test_prediction_equivalence_padded(self, converted):
        hf_model, model, variables = converted
        rng = np.random.default_rng(1)
        x = rng.integers(0, 262, size=(2, 32))
        attention_mask = np.ones((2, 32), dtype=np.int64)
        attention_mask[0, 28:] = 0  # right padding (HF MLM convention)

        with torch.no_grad():
            ref = hf_model(
                input_ids=torch.tensor(x), attention_mask=torch.tensor(attention_mask)
            ).logits.numpy()
        out = np.asarray(model.apply(variables, jnp.asarray(x), pad_mask=jnp.asarray(attention_mask == 0)))
        # compare non-pad rows only (pad-position outputs are unspecified)
        np.testing.assert_allclose(out[1], ref[1, :32], atol=ATOL, rtol=RTOL)
        np.testing.assert_allclose(out[0, :28], ref[0, :28], atol=ATOL, rtol=RTOL)


class TestLanguagePerceiverSize:
    def test_full_size_parameter_parity(self):
        """deepmind/language-perceiver has 201,108,230 parameters (reference:
        tests/masked_language_model_convert_test.py:12). The HF architecture
        with that model's dimensions (PerceiverConfig defaults + qk=256,
        v=1280) must convert into our tree with the exact same count —
        no network access needed."""
        config = PerceiverConfig(qk_channels=256, v_channels=1280)
        hf_model = PerceiverForMaskedLM(config)
        n_src = sum(p.numel() for p in hf_model.parameters())
        assert n_src == 201_108_230

        our_config, variables = convert_masked_language_model(hf_model)
        n_tgt = sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(variables))
        assert n_tgt == 201_108_230
        assert our_config.num_latents == 256
        assert our_config.num_latent_channels == 1280
        assert our_config.encoder.num_self_attention_layers_per_block == 26


class TestImageClassifier:
    @pytest.fixture(scope="class")
    def converted(self):
        config = PerceiverConfig(
            num_latents=4,
            d_latents=16,
            num_blocks=1,
            num_self_attends_per_block=2,
            num_self_attention_heads=2,
            # sensitize: encoder heads/widening != the HF decoder's
            # hardcoded num_heads=1 / widening 1 (qk must divide heads)
            num_cross_attention_heads=2,
            qk_channels=16,
            v_channels=16,
            cross_attention_widening_factor=3,
            num_labels=3,
            attention_probs_dropout_prob=0.0,
        )
        hf_model = PerceiverForImageClassificationFourier(config)
        hf_model.eval()
        cfg, variables = convert_image_classifier(hf_model)

        from perceiver_io_tpu.models.vision.image_classifier import ImageClassifier

        return hf_model, ImageClassifier(cfg), variables

    def test_parameter_count(self, converted):
        hf_model, _, variables = converted
        n_src = sum(p.numel() for p in hf_model.parameters())
        n_tgt = sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(variables))
        assert n_tgt == n_src

    def test_prediction_equivalence(self, converted):
        hf_model, model, variables = converted
        rng = np.random.default_rng(2)
        img = rng.normal(size=(1, 3, 224, 224)).astype(np.float32)

        with torch.no_grad():
            ref = hf_model(inputs=torch.tensor(img)).logits.numpy()
        out = np.asarray(model.apply(variables, jnp.asarray(img.transpose(0, 2, 3, 1))))
        np.testing.assert_allclose(out, ref, atol=ATOL, rtol=RTOL)


class TestOpticalFlow:
    @pytest.fixture(scope="class")
    def converted(self):
        config = PerceiverConfig(
            num_latents=4,
            d_latents=16,
            num_blocks=1,
            num_self_attends_per_block=2,
            num_self_attention_heads=2,
            num_cross_attention_heads=2,
            qk_channels=16,
            v_channels=16,
            cross_attention_widening_factor=2,
            train_size=[16, 24],
            attention_probs_dropout_prob=0.0,
        )
        hf_model = PerceiverForOpticalFlow(config)
        hf_model.eval()
        cfg, variables = convert_optical_flow(hf_model)

        from perceiver_io_tpu.models.vision.optical_flow import OpticalFlow

        return hf_model, OpticalFlow(cfg), variables

    def test_parameter_count(self, converted):
        hf_model, _, variables = converted
        n_src = sum(p.numel() for p in hf_model.parameters())
        n_tgt = sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(variables))
        assert n_tgt == n_src

    def test_prediction_equivalence(self, converted):
        hf_model, model, variables = converted
        rng = np.random.default_rng(3)
        # patched frame-pair features, torch layout (B, 2, 27, H, W)
        patches = rng.normal(size=(1, 2, 27, 16, 24)).astype(np.float32)

        with torch.no_grad():
            ref = hf_model(inputs=torch.tensor(patches)).logits.numpy()
        out = np.asarray(model.apply(variables, jnp.asarray(patches.transpose(0, 1, 3, 4, 2))))
        np.testing.assert_allclose(out, ref.reshape(out.shape), atol=ATOL, rtol=RTOL)
