"""Pins on the committed offline convergence artifacts (VERDICT r3 item 3).

These tests validate the **committed evidence**, not a live run: the flagship
convergence driver (tools/flagship_convergence.py) trains the reference
CLM-small geometry on a deterministic Markov corpus whose entropy rate is
computable, and the MNIST-class classifier on synthetic digits; the curves
and summary land in docs/results/. The pins here fail if a regression ships
worse converged quality (or the artifacts go missing).
"""

import csv
import json
import os

import numpy as np
import pytest

RESULTS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "docs", "results")
SUMMARY = os.path.join(RESULTS, "flagship_convergence.json")

pytestmark = pytest.mark.skipif(
    not os.path.exists(SUMMARY), reason="flagship convergence artifacts not generated yet"
)


def _summary():
    return json.load(open(SUMMARY))


def test_clm_flagship_converged_near_entropy_floor():
    """The 30.7M CLM must close most of the unigram->floor gap on the
    analytic-entropy corpus — the offline stand-in for the reference's
    published val_loss 0.876 on WikiText (training-examples.md:160-161)."""
    s = _summary()["clm"]
    assert s["final_val_loss"] < 1.0, s
    # the corpus's analytic bounds sandwich the result
    assert s["entropy_floor"] < s["final_val_loss"] < s["unigram_baseline"], s
    assert s["gap_closed"] > 0.8, s


def test_clm_flagship_curve_is_monotone_converged():
    path = os.path.join(RESULTS, "clm_flagship.csv")
    vals = [float(r["val_loss"]) for r in csv.DictReader(open(path)) if r.get("val_loss")]
    assert len(vals) >= 5
    assert vals[-1] == min(vals[-3:])  # still at (or tied with) its best at the end
    assert vals[-1] < vals[0] * 0.6  # real descent, not noise
    # plateau: the last quarter moves by < 5% — "to convergence"
    q = max(1, len(vals) // 4)
    assert abs(vals[-1] - vals[-q]) / vals[-q] < 0.05


def test_img_flagship_accuracy():
    """MNIST-class classifier on synthetic digits — offline stand-in for the
    reference's published MNIST val_acc 0.9816 (training-examples.md:143-150)."""
    s = _summary()["img"]
    assert s["final_val_acc"] > 0.95, s


def test_img_flagship_curve_learns():
    path = os.path.join(RESULTS, "img_clf_flagship.csv")
    vals = [float(r["val_acc"]) for r in csv.DictReader(open(path)) if r.get("val_acc")]
    assert len(vals) >= 3
    assert vals[0] < 0.6 < 0.95 < vals[-1]  # chance-ish start, converged end


def test_corpus_entropy_math_self_consistent():
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(RESULTS), "..", "tools"))
    from flagship_convergence import corpus_entropy_rate

    ent = corpus_entropy_rate(vocab=128, fanout=8, seed=7)
    # fanout-8 uniform draws with zipf duplicates: per-word entropy must be
    # positive and below log(8); bytes/word between min and max word length+1
    h_w = ent["nats_per_byte_floor"] * ent["bytes_per_word"]
    assert 0.0 < h_w <= np.log(8) + 1e-9
    assert ent["nats_per_byte_floor"] < ent["nats_per_byte_unigram"]
    assert 3.0 <= ent["bytes_per_word"] <= 6.0
