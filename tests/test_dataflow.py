"""analysis/dataflow.py (the def-use / provenance engine): value threading
through pjit/scan/cond/custom_vjp bodies, reachability and liveness, the
provenance-chain renderer (golden), FLOPs weighting, PRNG key identity, and
the sharding propagator's transfer rules — engine-level coverage; the rules
built on top are covered in tests/test_analysis.py."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from perceiver_io_tpu.analysis import dataflow as D


# ------------------------------------------------------------ def-use basics


def test_def_use_and_io_wiring():
    def f(x, y):
        a = x * 2.0
        return a + y

    df = D.analyze(f, jnp.ones((4,)), jnp.ones((4,)))
    assert len(df.input_vids) == 2
    mul = next(n for n in df.nodes if n.primitive == "mul")
    add = next(n for n in df.nodes if n.primitive == "add")
    # x is consumed by the mul, the mul's output by the add
    assert mul.nid in df.values[df.input_vids[0]].uses
    assert add.nid in df.values[mul.outvals[0]].uses
    assert df.def_node(add.outvals[0]).nid == add.nid
    assert df.output_vids == [add.outvals[0]]


def test_threading_through_pjit_boundary():
    """A value flowing into a jitted sub-call is the SAME dataflow value
    inside the body — the chain crosses the pjit boundary."""

    inner = jax.jit(lambda v: jnp.tanh(v))

    def f(x):
        return inner(x * 2.0).sum()

    df = D.analyze(f, jnp.ones((4,)))
    mul = next(n for n in df.nodes if n.primitive == "mul")
    tanh = next(n for n in df.nodes if n.primitive == "tanh")
    red = next(n for n in df.nodes if n.primitive == "reduce_sum")
    assert tanh.parent is not None and df.nodes[tanh.parent].primitive == "pjit"
    chain = df.find_chain(mul.nid, red.nid)
    assert chain is not None
    assert [n.primitive for n in chain if n.primitive != "pjit"] == [
        "mul", "tanh", "reduce_sum"
    ]


def test_scan_threading_carry_loopback_and_dead_body_op():
    def f(xs, init):
        def body(c, x):
            dead = c * 3.0  # noqa: F841 — feeds nothing
            c2 = c + x
            return c2, c2 * 2.0
        c, ys = lax.scan(body, init, xs)
        return ys

    df = D.analyze(f, jnp.ones((3, 2)), jnp.zeros((2,)))
    assert df.loop_vids, "scan carry binders must be marked loop-carried"
    dead = df.dead_nodes()
    assert [(n.primitive, n.region) for n in dead] == [("mul", ("scan",))]
    # the final-carry output is unused; ys reach the output through the loop
    add = next(n for n in df.nodes if n.primitive == "add")
    assert add.nid in df.live_node_ids()


def test_cond_threading_merges_branches():
    def f(p, x):
        return lax.cond(p, lambda v: v * 2.0, lambda v: v + 1.0, x).sum()

    df = D.analyze(f, jnp.asarray(True), jnp.ones((3,)))
    mul = next(n for n in df.nodes if n.primitive == "mul")
    red = next(n for n in df.nodes if n.primitive == "reduce_sum")
    assert "cond" in mul.region
    assert df.find_chain(mul.nid, red.nid) is not None


def test_custom_vjp_body_is_threaded():
    @jax.custom_vjp
    def g(x):
        return jnp.sin(x)

    g.defvjp(lambda x: (jnp.sin(x), x), lambda x, ct: (ct * jnp.cos(x),))

    def f(x):
        return g(x * 2.0).sum()

    df = D.analyze(f, jnp.ones((4,)))
    sin = next((n for n in df.nodes if n.primitive == "sin"), None)
    assert sin is not None, "custom_vjp body not inlined"
    mul = next(n for n in df.nodes if n.primitive == "mul")
    red = next(n for n in df.nodes if n.primitive == "reduce_sum")
    assert df.find_chain(mul.nid, red.nid) is not None


# --------------------------------------------------------- provenance golden


def test_provenance_chain_rendering_golden():
    """The renderer is part of the rule-message contract: one op per line,
    ``primitive dtype[shape] @ scope``."""

    def f(x, y):
        with jax.named_scope("enc"):
            h = x @ y
        with jax.named_scope("head"):
            return jnp.tanh(h).sum()

    df = D.analyze(f, jnp.ones((4, 4)), jnp.ones((4, 4)))
    src = next(n for n in df.nodes if n.primitive == "dot_general")
    dst = next(n for n in df.nodes if n.primitive == "reduce_sum")
    assert df.provenance(src.nid, dst.nid) == (
        "dot_general float32[4x4] @ enc\n"
        "-> tanh float32[4x4] @ head\n"
        "-> reduce_sum float32[] @ head"
    )


def test_provenance_chain_elides_long_middles():
    def f(x):
        for _ in range(12):
            x = x + 1.0
        return x.sum()

    df = D.analyze(f, jnp.ones((4,)))
    first = next(n for n in df.nodes if n.primitive == "add")
    red = next(n for n in df.nodes if n.primitive == "reduce_sum")
    text = df.provenance(first.nid, red.nid, max_ops=4)
    assert "... (" in text and text.count("\n") == 4  # 4 ops + 1 elision line


# ------------------------------------------------------------ liveness/FLOPs


def test_effectful_op_keeps_feeders_live():
    def f(x):
        s = x.sum()  # feeds only the debug print
        jax.debug.print("s={}", s)
        return x * 2.0

    df = D.analyze(f, jnp.ones((4,)))
    red = next(n for n in df.nodes if n.primitive == "reduce_sum")
    assert red.nid in df.live_node_ids(), "effect sinks must keep feeders live"
    assert all(n.primitive != "reduce_sum" for n in df.dead_nodes())


def test_node_flops_dot_general_exact():
    def f(a, b):
        return a @ b

    df = D.analyze(f, jnp.ones((8, 32)), jnp.ones((32, 16)))
    dot = next(n for n in df.nodes if n.primitive == "dot_general")
    assert D.node_flops(dot, df.values) == 2 * 8 * 16 * 32


# ------------------------------------------------------------- key identity


def test_key_identity_tells_split_rows_apart():
    def f(key):
        k1, k2 = jax.random.split(key)
        return jax.random.uniform(k1, (4,)) + jax.random.normal(k2, (4,))

    assert D.rng_reuse_findings(D.analyze(f, jax.random.PRNGKey(0))) == []

    def g(key):
        k1, _ = jax.random.split(key)
        return jax.random.uniform(k1, (4,)) + jax.random.uniform(k1, (4,))

    findings = D.rng_reuse_findings(D.analyze(g, jax.random.PRNGKey(0)))
    assert [f.kind for f in findings] == ["draw-draw"]
    assert len(findings[0].sink_nids) == 2


def test_draw_then_split_is_a_finding():
    def f(key):
        u = jax.random.uniform(key, (4,))
        k1, _ = jax.random.split(key)  # children correlate with the draw
        return u + jax.random.uniform(k1, (4,))

    kinds = [x.kind for x in D.rng_reuse_findings(D.analyze(f, jax.random.PRNGKey(0)))]
    assert "draw-derive" in kinds


# -------------------------------------------------------- sharding propagator


def test_propagate_shardings_transfer_rules():
    from jax.sharding import PartitionSpec as P

    def f(x, w):
        h = x @ w            # (data, None) @ (None, fsdp) -> (data, fsdp)
        h = jnp.tanh(h)      # elementwise keeps the layout
        return h.sum(axis=1)  # reduce drops the fsdp dim

    df = D.analyze(f, jnp.ones((8, 16)), jnp.ones((16, 4)))
    conflicts, state = D.propagate_shardings(df, [P("data"), P(None, "fsdp")])
    assert conflicts == []
    red = next(n for n in df.nodes if n.primitive == "reduce_sum")
    assert state[red.outvals[0]] == (("data",),)


def test_propagate_shardings_predicts_reshard_points():
    from jax.sharding import PartitionSpec as P

    def f(x, y):
        a = x[0:2]  # slice along the data-sharded dim: permute predicted
        return a, x + y  # dim 0: data vs fsdp — mismatched operands

    df = D.analyze(f, jnp.ones((4, 4)), jnp.ones((4, 4)))
    conflicts, _ = D.propagate_shardings(df, [P("data"), P("fsdp")])
    kinds = sorted(c.kind for c in conflicts)
    assert kinds == ["mismatched-operands", "sliced-sharded-dim"]


def test_propagate_shardings_drops_layouts_across_scan_rank_changes():
    """A scan's stacked xs (rank r+1) alias to per-iteration slices (rank
    r): carrying the stacked layout across would shift mesh axes onto the
    wrong dims and invent phantom conflicts. The layout must become
    unknown at the rank change, not misindexed."""
    from jax.sharding import PartitionSpec as P

    def f(xs, h):
        def body(c, x):
            return c + x, c.sum()  # carry(fsdp@1) joins x — NOT a conflict

        c, ys = lax.scan(body, h, xs)
        return c, ys

    df = D.analyze(f, jnp.ones((3, 4, 8)), jnp.zeros((4, 8)))
    # stacked xs sharded 'data' on dim 1 == the slice's dim 0, carry 'fsdp'
    # on dim 1: same-rank transfer would see a dim-1 data-vs-fsdp clash
    conflicts, _ = D.propagate_shardings(df, [P(None, "data"), P(None, "fsdp")])
    assert conflicts == [], conflicts


def test_propagate_shardings_skips_shard_map_interiors():
    from jax.sharding import Mesh, PartitionSpec as P

    from perceiver_io_tpu.utils.compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(-1), ("data",))

    def f(x):
        def body(x):
            return x[0:1] * 2.0  # a slice of the LOCAL shard: not a reshard

        return shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False
        )(x)

    df = D.analyze(f, jnp.ones((8, 4)))
    conflicts, state = D.propagate_shardings(df, [P("data")])
    assert conflicts == []
    sm = next(n for n in df.nodes if n.primitive == "shard_map")
    # region outputs take their layout from out_names
    assert state[sm.outvals[0]] == (("data",), None)
