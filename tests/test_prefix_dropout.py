"""Prefix cross-attention dropout: gather vs mask mode equivalence, the
host-sampled keep-index path, and the host sampler's law.

The three implementations under test all realize the reference's prefix
dropout (reference: perceiver/model/core/modules.py:809-830 — a uniformly
random static-count keep subset):

- ``prefix_dropout_mode="gather"`` (default): row-gather of the keep set,
  shrinking the CA kv length.
- ``prefix_dropout_mode="mask"``: full-length prefix, dropped positions
  masked out of the CA softmax (SURVEY §7.3).
- ``prefix_keep_idx=...``: the subset drawn on the host
  (training.prefix_dropout) instead of in-graph.

"gather" on statically un-padded input takes the round-5 *compact* route
(selection applied to token ids / position-table rows before embedding —
core/adapter.py ``embed_compact``); ``prefix_dropout_mode="gather_embed"``
pins the round-4 embedded-row gather, and the two must agree bitwise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.training import clm_loss_fn
from perceiver_io_tpu.training.prefix_dropout import (
    prefix_keep_count,
    sample_prefix_keep_idx,
    with_prefix_keep_idx,
)


def _config(**kwargs):
    base = dict(
        vocab_size=50,
        max_seq_len=24,
        max_latents=8,
        num_channels=32,
        num_heads=4,
        num_self_attention_layers=2,
        cross_attention_dropout=0.5,
    )
    base.update(kwargs)
    return CausalLanguageModelConfig(**base)


def _batchish(rng, b=3, n=24, vocab=50):
    return jnp.asarray(rng.integers(0, vocab, size=(b, n)))


def test_gather_and_mask_modes_agree():
    """Same rng draw → the same keep set → identical latent logits, whether
    the dropped positions are gathered away or masked out."""
    rng = np.random.default_rng(0)
    x = _batchish(rng)
    gather = CausalLanguageModel(_config())
    mask = CausalLanguageModel(_config(prefix_dropout_mode="mask"))
    params = gather.init(jax.random.PRNGKey(0), x, prefix_len=16)
    drop_rng = jax.random.PRNGKey(7)

    out_g = gather.apply(
        params, x, prefix_len=16, deterministic=False, rngs={"dropout": drop_rng}
    )
    out_m = mask.apply(
        params, x, prefix_len=16, deterministic=False, rngs={"dropout": drop_rng}
    )
    np.testing.assert_allclose(out_g.logits, out_m.logits, atol=1e-5)


@pytest.mark.parametrize("mode", ["gather", "mask"])
def test_host_keep_idx_matches_in_graph_draw(mode):
    """Feeding the keep set explicitly reproduces the in-graph draw's output
    when the sets coincide (both modes consume ``prefix_keep_idx``)."""
    rng = np.random.default_rng(1)
    x = _batchish(rng)
    model = CausalLanguageModel(_config(prefix_dropout_mode=mode))
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=16)

    drop_rng = jax.random.PRNGKey(3)
    keep = prefix_keep_count(16, 0.5)
    idx = jnp.asarray(
        np.stack([np.sort(np.random.default_rng(s).choice(16, keep, replace=False)) for s in range(3)])
    ).astype(jnp.int32)

    out_idx = model.apply(
        params, x, prefix_len=16, deterministic=False, prefix_keep_idx=idx,
        rngs={"dropout": drop_rng},
    )
    out_idx2 = model.apply(
        params, x, prefix_len=16, deterministic=False, prefix_keep_idx=idx,
        rngs={"dropout": jax.random.PRNGKey(99)},
    )
    # with the keep set supplied, the dropout rng is not consumed for it
    np.testing.assert_allclose(out_idx.logits, out_idx2.logits, atol=1e-6)
    assert np.isfinite(np.asarray(out_idx.logits)).all()


def test_gather_and_mask_agree_on_explicit_idx():
    rng = np.random.default_rng(2)
    x = _batchish(rng)
    gather = CausalLanguageModel(_config())
    mask = CausalLanguageModel(_config(prefix_dropout_mode="mask"))
    params = gather.init(jax.random.PRNGKey(0), x, prefix_len=16)
    keep = prefix_keep_count(16, 0.5)
    idx = sample_prefix_keep_idx(np.random.default_rng(5), 3, 16, 0.5)
    assert idx.shape == (3, keep)
    out_g = gather.apply(
        params, x, prefix_len=16, deterministic=False, prefix_keep_idx=jnp.asarray(idx),
        rngs={"dropout": jax.random.PRNGKey(0)},
    )
    out_m = mask.apply(
        params, x, prefix_len=16, deterministic=False, prefix_keep_idx=jnp.asarray(idx),
        rngs={"dropout": jax.random.PRNGKey(0)},
    )
    np.testing.assert_allclose(out_g.logits, out_m.logits, atol=1e-5)


def test_compact_matches_embedded_gather_bitwise():
    """The compact route (selection before embedding) must reproduce the
    embedded-row gather exactly — gather-then-embed == embed-then-gather is
    pure row selection, so values AND grads agree bitwise."""
    rng = np.random.default_rng(6)
    x = _batchish(rng)
    compact = CausalLanguageModel(_config())  # "gather" → compact (no pad)
    legacy = CausalLanguageModel(_config(prefix_dropout_mode="gather_embed"))
    params = compact.init(jax.random.PRNGKey(0), x, prefix_len=16)
    idx = jnp.asarray(sample_prefix_keep_idx(np.random.default_rng(5), 3, 16, 0.5))

    def loss(model):
        def f(p):
            out = model.apply(
                p, x, prefix_len=16, deterministic=False, prefix_keep_idx=idx,
                rngs={"dropout": jax.random.PRNGKey(7)},
            )
            return (out.logits.astype(jnp.float32) ** 2).mean()

        return f

    l_c, g_c = jax.value_and_grad(loss(compact))(params)
    l_l, g_l = jax.value_and_grad(loss(legacy))(params)
    assert l_c == l_l
    for a, b in zip(jax.tree.leaves(g_c), jax.tree.leaves(g_l)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # in-graph draw: same rng → same keep set → identical logits across routes
    out_c = compact.apply(
        params, x, prefix_len=16, deterministic=False, rngs={"dropout": jax.random.PRNGKey(9)}
    )
    out_l = legacy.apply(
        params, x, prefix_len=16, deterministic=False, rngs={"dropout": jax.random.PRNGKey(9)}
    )
    np.testing.assert_array_equal(np.asarray(out_c.logits), np.asarray(out_l.logits))


def test_gather_with_pad_mask_falls_back_and_agrees_with_mask_mode():
    """With a pad mask the compact route does not apply (positions are not
    statically arange); "gather" must fall back to the embedded-row gather
    and still agree with mask mode on the same keep set."""
    rng = np.random.default_rng(8)
    x = _batchish(rng)
    pad = np.zeros((3, 24), bool)
    pad[0, :3] = True  # left padding
    pad[1, :1] = True
    pad_mask = jnp.asarray(pad)
    gather = CausalLanguageModel(_config())
    mask = CausalLanguageModel(_config(prefix_dropout_mode="mask"))
    params = gather.init(jax.random.PRNGKey(0), x, prefix_len=16)
    idx = jnp.asarray(sample_prefix_keep_idx(np.random.default_rng(5), 3, 16, 0.5))
    out_g = gather.apply(
        params, x, prefix_len=16, pad_mask=pad_mask, deterministic=False,
        prefix_keep_idx=idx, rngs={"dropout": jax.random.PRNGKey(0)},
    )
    out_m = mask.apply(
        params, x, prefix_len=16, pad_mask=pad_mask, deterministic=False,
        prefix_keep_idx=idx, rngs={"dropout": jax.random.PRNGKey(0)},
    )
    np.testing.assert_allclose(
        np.asarray(out_g.logits), np.asarray(out_m.logits), atol=1e-5
    )


def test_keep_idx_wrong_count_raises():
    rng = np.random.default_rng(3)
    x = _batchish(rng)
    model = CausalLanguageModel(_config())
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=16)
    bad = jnp.zeros((3, 3), jnp.int32)  # keeps 8, not 3
    with pytest.raises(ValueError, match="keeps 8 of 16"):
        model.apply(
            params, x, prefix_len=16, deterministic=False, prefix_keep_idx=bad,
            rngs={"dropout": jax.random.PRNGKey(0)},
        )


def test_unknown_mode_rejected():
    model = CausalLanguageModel(_config(prefix_dropout_mode="bogus"))
    with pytest.raises(ValueError, match="prefix_dropout_mode"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 24), jnp.int32), prefix_len=16)


def test_clm_loss_fn_forwards_batch_keep_idx():
    rng = np.random.default_rng(4)
    t = rng.integers(0, 50, size=(3, 25))
    model = CausalLanguageModel(_config())
    x = jnp.asarray(t[:, :-1])
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=16)
    loss = clm_loss_fn(model.apply, max_latents=8)
    idx = jnp.asarray(sample_prefix_keep_idx(np.random.default_rng(6), 3, 16, 0.5))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": x,
        "pad_mask": None,
        "prefix_keep_idx": idx,
    }
    l1, _ = loss(params, batch, jax.random.PRNGKey(1))
    l2, _ = loss(params, batch, jax.random.PRNGKey(2))  # rng no longer drives the subset
    assert float(l1) == pytest.approx(float(l2), abs=1e-6)
    # and without the key, different rngs draw different subsets
    batch.pop("prefix_keep_idx")
    l3, _ = loss(params, batch, jax.random.PRNGKey(1))
    l4, _ = loss(params, batch, jax.random.PRNGKey(2))
    assert float(l3) != pytest.approx(float(l4), abs=1e-9)


def test_sampler_law():
    rng = np.random.default_rng(0)
    idx = sample_prefix_keep_idx(rng, 64, 40, 0.5)
    keep = prefix_keep_count(40, 0.5)
    assert idx.shape == (64, keep) and idx.dtype == np.int32
    for row in idx:
        assert len(set(row.tolist())) == keep  # unique
        assert (np.sort(row) == row).all()  # sorted
        assert row.min() >= 0 and row.max() < 40
    # marginal inclusion probability ~ keep/n for every position
    freq = np.zeros(40)
    big = sample_prefix_keep_idx(rng, 2000, 40, 0.5)
    for row in big:
        freq[row] += 1
    freq /= 2000
    np.testing.assert_allclose(freq, keep / 40, atol=0.05)


def test_iterator_wrapper():
    batches = [{"input_ids": np.zeros((2, 24)), "pad_mask": None} for _ in range(3)]
    out = list(with_prefix_keep_idx(iter(batches), prefix_len=16, dropout=0.5, seed=1))
    keep = prefix_keep_count(16, 0.5)
    assert all(b["prefix_keep_idx"].shape == (2, keep) for b in out)
    # fresh draw per batch
    assert not np.array_equal(out[0]["prefix_keep_idx"], out[1]["prefix_keep_idx"])
    # original dicts untouched
    assert "prefix_keep_idx" not in batches[0]
