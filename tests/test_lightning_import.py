"""Reference training-checkpoint import (Lightning ``.ckpt`` → Flax).

The torch state_dicts here are synthesized with the reference backends' exact
parameter names (transcribed from the reference module structure:
perceiver/model/core/modules.py — nn.Sequential layer indices + ``Residual``
``module`` attributes; adapter.py — ``txt_embedding``/``pos_embedding``/
``_query``; the published checkpoints are listed in examples/convert.py:38-66).
Each import asserts: every checkpoint parameter is consumed, the derived
config rebuilds a model whose ``init`` tree matches the imported tree
exactly, and the model runs. The importer itself fails loudly on unconsumed
parameters, so these tests pin the naming contract."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from perceiver_io_tpu.hf.lightning_ckpt import (  # noqa: E402
    export_causal_sequence_model_state_dict,
    import_clm_checkpoint,
    import_image_classifier_checkpoint,
    import_mlm_checkpoint,
    import_symbolic_audio_checkpoint,
    import_text_classifier_checkpoint,
    load_lightning_checkpoint,
    save_lightning_checkpoint,
)

C, V, SEQ, LAT = 16, 32, 24, 4  # tiny geometry
rng = np.random.default_rng(0)


def t(*shape):
    return torch.from_numpy(rng.normal(scale=0.02, size=shape).astype(np.float32))


def _linear(prefix, n_in, n_out, bias=True):
    sd = {f"{prefix}.weight": t(n_out, n_in)}
    if bias:
        sd[f"{prefix}.bias"] = t(n_out)
    return sd


def _layernorm(prefix, n):
    return {f"{prefix}.weight": t(n), f"{prefix}.bias": t(n)}


def _attention(prefix, n, qkv_bias, out_bias):
    sd = {}
    for name in ("q_proj", "k_proj", "v_proj"):
        sd.update(_linear(f"{prefix}.{name}", n, n, bias=qkv_bias))
    sd.update(_linear(f"{prefix}.o_proj", n, n, bias=out_bias))
    return sd


def _mlp(prefix, n, widening, bias):
    sd = _layernorm(f"{prefix}.0", n)
    sd.update(_linear(f"{prefix}.1", n, widening * n, bias=bias))
    sd.update(_linear(f"{prefix}.3", widening * n, n, bias=bias))
    return sd


def _cross_attn_layer(prefix, n, widening=1, qkv_bias=True, out_bias=True, mlp_bias=True):
    sd = _layernorm(f"{prefix}.0.module.q_norm", n)
    sd.update(_layernorm(f"{prefix}.0.module.kv_norm", n))
    sd.update(_attention(f"{prefix}.0.module.attention", n, qkv_bias, out_bias))
    sd.update(_mlp(f"{prefix}.1.module", n, widening, mlp_bias))
    return sd


def _self_attn_layer(prefix, n, widening=1, qkv_bias=True, out_bias=True, mlp_bias=True):
    sd = _layernorm(f"{prefix}.0.module.norm", n)
    sd.update(_attention(f"{prefix}.0.module.attention", n, qkv_bias, out_bias))
    sd.update(_mlp(f"{prefix}.1.module", n, widening, mlp_bias))
    return sd


def clm_backend_state_dict(num_layers=2):
    """Reference CausalSequenceModel naming (modules.py:874-930; qkv_bias
    False / out_bias True for CA, all-False for SA, mlp_bias False)."""
    sd = {
        "input_adapter.frq_pos_encoding.inv_freq": t(4),  # buffer, ignored
        "input_adapter.txt_embedding.weight": t(V, C),
        "input_adapter.pos_embedding.weight": t(SEQ, C),
        "output_adapter.bias": t(V),
    }
    sd.update(_layernorm("out_norm", C))
    sd.update(
        _cross_attn_layer("cross_attention", C, widening=4, qkv_bias=False, out_bias=True, mlp_bias=False)
    )
    for i in range(num_layers):
        sd.update(
            _self_attn_layer(f"self_attention.{i}", C, widening=4, qkv_bias=False, out_bias=False, mlp_bias=False)
        )
    return sd


def clm_hparams():
    return {
        "vocab_size": V, "max_seq_len": SEQ, "max_latents": LAT, "num_channels": C,
        "num_heads": 2, "num_self_attention_layers": 2,
        "num_self_attention_rotary_layers": 1,
        "cross_attention_dropout": 0.5, "output_norm": True, "output_bias": True,
        "abs_pos_emb": True, "init_scale": 0.02,
        "validation_sample_record": None, "params": None,  # wrapper extras, ignored
    }


def as_ckpt(backend_sd, hparams):
    return {
        "state_dict": {f"model.{k}": v for k, v in backend_sd.items()},
        "hyper_parameters": hparams,
    }


def assert_trees_match(imported, model_init):
    """Same structure and shapes as a fresh init of the derived config."""
    ref_paths = jax.tree_util.tree_flatten_with_path(model_init)[0]
    got_paths = jax.tree_util.tree_flatten_with_path(imported)[0]
    ref = {jax.tree_util.keystr(p): leaf.shape for p, leaf in ref_paths}
    got = {jax.tree_util.keystr(p): np.asarray(leaf).shape for p, leaf in got_paths}
    assert ref == got


# -------------------------------------------------------------------------------------------


@pytest.mark.slow
def test_import_clm_checkpoint(tmp_path):
    from perceiver_io_tpu.models.text import CausalLanguageModel

    path = tmp_path / "clm.ckpt"
    torch.save(as_ckpt(clm_backend_state_dict(), clm_hparams()), path)

    config, variables = import_clm_checkpoint(str(path))
    assert config.vocab_size == V and config.max_latents == LAT
    assert config.num_heads == 2 and config.cross_attention_dropout == 0.5
    assert config.output_norm and config.output_bias
    assert config.cross_attention_widening_factor == 4

    model = CausalLanguageModel(config)
    x = jnp.asarray(rng.integers(0, V, size=(2, SEQ)))
    init = model.init(jax.random.PRNGKey(0), x, prefix_len=SEQ - LAT)
    assert_trees_match(variables, init)
    logits = model.apply(variables, x, prefix_len=SEQ - LAT).logits
    assert logits.shape == (2, LAT, V)
    # imported weights actually land (not re-initialized)
    np.testing.assert_array_equal(
        np.asarray(variables["params"]["output_adapter"]["bias"]),
        np.asarray(torch.load(path, weights_only=True)["state_dict"]["model.output_adapter.bias"]),
    )


def test_import_rejects_unconsumed_parameters(tmp_path):
    sd = clm_backend_state_dict()
    sd["self_attention.0.0.module.attention.extra_proj.weight"] = t(C, C)
    path = tmp_path / "bad.ckpt"
    torch.save(as_ckpt(sd, clm_hparams()), path)
    with pytest.raises(ValueError, match="not mapped"):
        import_clm_checkpoint(str(path))


@pytest.mark.slow
def test_clm_export_import_round_trip(tmp_path):
    """Our trained params → reference-named .ckpt → re-import: identical."""
    from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig

    config = CausalLanguageModelConfig(
        vocab_size=V, max_seq_len=SEQ, max_latents=LAT, num_channels=C,
        num_heads=2, num_self_attention_layers=2, output_norm=True,
    )
    model = CausalLanguageModel(config)
    x = jnp.asarray(rng.integers(0, V, size=(1, SEQ)))
    variables = model.init(jax.random.PRNGKey(1), x, prefix_len=SEQ - LAT)

    path = tmp_path / "exported.ckpt"
    save_lightning_checkpoint(str(path), variables, config)
    config2, variables2 = import_clm_checkpoint(str(path))
    assert dataclasses.asdict(config2) == dataclasses.asdict(config)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(variables)[0],
        jax.tree_util.tree_flatten_with_path(variables2)[0],
    ):
        assert jax.tree_util.keystr(pa) == jax.tree_util.keystr(pb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # exported names are exactly the reference backend's key set
    exported = set(export_causal_sequence_model_state_dict(variables))
    expected = {k for k in clm_backend_state_dict() if not k.endswith(".inv_freq")}
    assert exported == expected


def test_import_symbolic_audio_checkpoint(tmp_path):
    from perceiver_io_tpu.models.audio.symbolic import SymbolicAudioModel

    path = tmp_path / "sam.ckpt"
    torch.save(as_ckpt(clm_backend_state_dict(), clm_hparams()), path)
    config, variables = import_symbolic_audio_checkpoint(str(path))
    model = SymbolicAudioModel(config)
    x = jnp.asarray(rng.integers(0, V, size=(1, SEQ)))
    init = model.init(jax.random.PRNGKey(0), x, prefix_len=SEQ - LAT)
    assert_trees_match(variables, init)


# -------------------------------------------------------------------------------------------


def encoder_state_dict(num_layers=2, prefix="0"):
    """Reference TextEncoder naming: PerceiverIO is nn.Sequential(encoder,
    decoder) → children '0'/'1' (modules.py:678-688)."""
    sd = {
        f"{prefix}.latent_provider._query": t(LAT, C),
        f"{prefix}.input_adapter.txt_embedding.weight": t(V, C),
        f"{prefix}.input_adapter.pos_embedding.weight": t(SEQ, C),
    }
    sd.update(_cross_attn_layer(f"{prefix}.cross_attn_1", C))
    for i in range(num_layers):
        sd.update(_self_attn_layer(f"{prefix}.self_attn_1.{i}", C))
    return sd


def perceiver_io_hparams(decoder_extra=None):
    return {
        "encoder": {
            "vocab_size": V, "max_seq_len": SEQ, "num_input_channels": C,
            "num_cross_attention_heads": 2, "num_self_attention_heads": 2,
            "num_self_attention_layers_per_block": 2, "num_self_attention_blocks": 1,
        },
        "decoder": {"num_cross_attention_heads": 2, **(decoder_extra or {})},
        "num_latents": LAT, "num_latent_channels": C,
        "activation_checkpointing": False, "activation_offloading": False, "params": None,
    }


@pytest.mark.slow
def test_import_mlm_checkpoint_tied(tmp_path):
    from perceiver_io_tpu.models.text.mlm import MaskedLanguageModel

    sd = encoder_state_dict()
    sd.update(_cross_attn_layer("1.cross_attn", C))
    sd["1.output_query_provider._query"] = t(SEQ, C)
    sd["1.output_adapter.bias"] = t(V)
    path = tmp_path / "mlm.ckpt"
    torch.save(as_ckpt(sd, perceiver_io_hparams({"vocab_size": V, "max_seq_len": SEQ})), path)

    config, variables = import_mlm_checkpoint(str(path))
    model = MaskedLanguageModel(config)
    x = jnp.asarray(rng.integers(0, V, size=(2, 8)))
    init = model.init(jax.random.PRNGKey(0), x)
    assert_trees_match(variables, init)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 8, V)


@pytest.mark.slow
def test_import_mlm_checkpoint_untied(tmp_path):
    from perceiver_io_tpu.models.text.mlm import MaskedLanguageModel

    qc = C  # independent (untied) output head; query width == latent width
    sd = encoder_state_dict()
    sd.update(_cross_attn_layer("1.cross_attn", C))
    sd["1.output_query_provider._query"] = t(SEQ, qc)
    sd.update(_linear("1.output_adapter.linear", qc, V))
    path = tmp_path / "mlm_untied.ckpt"
    torch.save(
        as_ckpt(
            sd,
            perceiver_io_hparams(
                {"vocab_size": V, "max_seq_len": SEQ, "num_output_query_channels": qc}
            ),
        ),
        path,
    )

    config, variables = import_mlm_checkpoint(str(path))
    assert config.decoder.num_output_query_channels == qc
    model = MaskedLanguageModel(config)
    x = jnp.asarray(rng.integers(0, V, size=(2, 8)))
    init = model.init(jax.random.PRNGKey(0), x)
    assert_trees_match(variables, init)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 8, V)


def test_import_text_classifier_checkpoint(tmp_path):
    from perceiver_io_tpu.models.text.classifier import TextClassifier

    sd = encoder_state_dict()
    sd.update(_cross_attn_layer("1.cross_attn", C))
    sd["1.output_query_provider._query"] = t(1, C)
    sd.update(_linear("1.output_adapter.linear", C, 2))
    path = tmp_path / "clf.ckpt"
    torch.save(as_ckpt(sd, perceiver_io_hparams({"num_classes": 2, "num_output_query_channels": C})), path)

    config, variables = import_text_classifier_checkpoint(str(path))
    assert config.decoder.num_classes == 2
    model = TextClassifier(config)
    x = jnp.asarray(rng.integers(0, V, size=(2, 8)))
    init = model.init(jax.random.PRNGKey(0), x)
    assert_trees_match(variables, init)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 2)


@pytest.mark.slow
def test_import_image_classifier_checkpoint(tmp_path):
    from perceiver_io_tpu.models.vision.image_classifier import ImageClassifier

    image_shape, bands = (8, 8, 1), 4
    in_ch = 1 + 2 * (2 * bands + 1)  # pixels + 2-D fourier features
    sd = {
        "0.latent_provider._query": t(LAT, C),
        "0.input_adapter.position_encoding.position_encoding": t(64, in_ch - 1),  # buffer
    }
    # image encoder CA: qk defaults to the adapter width (backend.py:59-61)
    sd.update(_layernorm("0.cross_attn_1.0.module.q_norm", C))
    sd.update(_layernorm("0.cross_attn_1.0.module.kv_norm", in_ch))
    sd.update(_linear("0.cross_attn_1.0.module.attention.q_proj", C, in_ch))
    sd.update(_linear("0.cross_attn_1.0.module.attention.k_proj", in_ch, in_ch))
    sd.update(_linear("0.cross_attn_1.0.module.attention.v_proj", in_ch, in_ch))
    sd.update(_linear("0.cross_attn_1.0.module.attention.o_proj", in_ch, C))
    sd.update(_mlp("0.cross_attn_1.1.module", C, 1, True))
    for i in range(2):
        sd.update(_self_attn_layer(f"0.self_attn_1.{i}", C))
    sd.update(_cross_attn_layer("1.cross_attn", C))
    sd["1.output_query_provider._query"] = t(1, C)
    sd.update(_linear("1.output_adapter.linear", C, 10))

    hp = {
        "encoder": {
            "image_shape": list(image_shape), "num_frequency_bands": bands,
            "num_cross_attention_heads": 1, "num_self_attention_heads": 2,
            "num_self_attention_layers_per_block": 2, "num_self_attention_blocks": 1,
            "num_cross_attention_qk_channels": in_ch,
        },
        "decoder": {"num_classes": 10, "num_output_query_channels": C, "num_cross_attention_heads": 2},
        "num_latents": LAT, "num_latent_channels": C,
    }
    path = tmp_path / "img.ckpt"
    torch.save(as_ckpt(sd, hp), path)

    config, variables = import_image_classifier_checkpoint(str(path))
    model = ImageClassifier(config)
    x = jnp.asarray(rng.normal(size=(2,) + image_shape), jnp.float32)
    init = model.init(jax.random.PRNGKey(0), x)
    assert_trees_match(variables, init)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 10)


# -------------------------------------------------------------------------------------------


def test_lenient_unpickler_survives_missing_reference_package(tmp_path):
    """hyper_parameters pickled as reference-package dataclasses (module not
    installed here) must still load: unknown classes become attribute stubs."""

    import sys
    import types

    # a throwaway module registered only for the save: pickle-by-reference
    # writes its dotted path; after deregistration, unpickling fails the
    # import -> the lenient unpickler must stub the class
    mod_name = "perceiver_ref_fake.backend"
    mod = types.ModuleType(mod_name)

    class TextEncoderConfig:
        pass

    TextEncoderConfig.__module__ = mod_name
    TextEncoderConfig.__qualname__ = "TextEncoderConfig"
    mod.TextEncoderConfig = TextEncoderConfig
    sys.modules["perceiver_ref_fake"] = types.ModuleType("perceiver_ref_fake")
    sys.modules[mod_name] = mod
    try:
        cfg = TextEncoderConfig()
        cfg.vocab_size = V
        cfg.num_input_channels = C
        path = tmp_path / "stub.ckpt"
        torch.save(
            {"state_dict": {}, "hyper_parameters": {"encoder": cfg, "num_latents": LAT}}, path
        )
    finally:
        del sys.modules[mod_name]
        del sys.modules["perceiver_ref_fake"]

    ckpt = load_lightning_checkpoint(str(path))
    enc = ckpt["hyper_parameters"]["encoder"]
    assert enc.vocab_size == V and enc.num_input_channels == C
    assert ckpt["hyper_parameters"]["num_latents"] == LAT


def test_import_timeseries_checkpoint(tmp_path):
    """Naming contract for the root-app MultivariatePerceiver importer —
    unlike the task models the state dict has NO ``model.`` prefix and the
    hyper-parameters are flat (reference: model.py:47-75)."""
    from perceiver_io_tpu.hf.lightning_ckpt import import_timeseries_checkpoint
    from perceiver_io_tpu.models.timeseries import TimeSeriesPerceiver

    in_ch, in_len, out_len, bands = 3, 12, 8, 4
    pos_ch = 1 + 2 * bands
    sd = {
        "encoder.latent_provider._query": t(LAT, C),
        "encoder.input_adapter.position_encoding.position_encoding": t(in_len, pos_ch),  # buffer
        "encoder.input_adapter.pos_proj.weight": t(C, pos_ch),  # bias-free (model.py:20)
    }
    sd.update(_linear("encoder.input_adapter.linear", in_ch, C))
    sd.update(_cross_attn_layer("encoder.cross_attn_1", C))
    for i in range(1):
        sd.update(_self_attn_layer(f"encoder.self_attn_1.{i}", C))
    sd.update(_cross_attn_layer("decoder.cross_attn", C))
    sd["decoder.output_query_provider._query"] = t(out_len, C)
    sd.update(_linear("decoder.output_adapter.linear", C, in_ch))

    hp = {
        "num_input_channels": in_ch, "in_len": in_len, "out_len": out_len,
        "num_latents": LAT, "latent_channels": C, "num_layers": 2,
        "learning_rate": 1e-4,
        "num_cross_attention_heads": 1, "num_self_attention_heads": 1,
    }
    path = tmp_path / "ts.ckpt"
    torch.save({"state_dict": sd, "hyper_parameters": hp}, path)

    config, variables = import_timeseries_checkpoint(str(path))
    assert config.encoder.num_frequency_bands == bands
    assert config.encoder.num_self_attention_blocks == 2
    assert config.decoder.out_len == out_len
    model = TimeSeriesPerceiver(config)
    x = jnp.asarray(rng.normal(size=(2, in_len, in_ch)), jnp.float32)
    init = model.init(jax.random.PRNGKey(0), x)
    assert_trees_match(variables, init)
    out = model.apply(variables, x)
    assert out.shape == (2, out_len, in_ch)
