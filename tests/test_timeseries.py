"""Time-series app tests (reference: root-level model.py/datamodule.py/cli.py,
SURVEY §2.9) — model shapes, sliding-window data module, CLI fit, and the
auto-model registry round trip."""

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.core.config import PerceiverIOConfig
from perceiver_io_tpu.models.timeseries import (
    TimeSeriesDecoderConfig,
    TimeSeriesEncoderConfig,
    TimeSeriesPerceiver,
)


def tiny_config(in_len=32, out_len=16, channels=3):
    enc = TimeSeriesEncoderConfig(
        num_input_channels=channels,
        in_len=in_len,
        num_frequency_bands=4,
        num_cross_attention_heads=1,
        num_self_attention_heads=1,
        num_self_attention_blocks=2,
        num_self_attention_layers_per_block=1,
    )
    dec = TimeSeriesDecoderConfig(
        out_len=out_len, num_output_channels=channels, num_cross_attention_heads=1
    )
    return PerceiverIOConfig(encoder=enc, decoder=dec, num_latents=8, num_latent_channels=16)


class TestModel:
    @pytest.mark.slow
    def test_forward_shape(self):
        config = tiny_config()
        model = TimeSeriesPerceiver(config)
        x = jnp.zeros((2, 32, 3))
        params = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(params, x)
        assert out.shape == (2, 16, 3)

    def test_input_shape_validated(self):
        config = tiny_config()
        model = TimeSeriesPerceiver(config)
        with pytest.raises(ValueError, match="incompatible"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((1, 20, 3)))

    @pytest.mark.slow
    def test_auto_registry_roundtrip(self, tmp_path):
        from perceiver_io_tpu.hf import from_pretrained
        from perceiver_io_tpu.training.checkpoint import save_pretrained

        config = tiny_config()
        model = TimeSeriesPerceiver(config)
        x = jnp.ones((1, 32, 3))
        params = model.init(jax.random.PRNGKey(0), x)
        save_pretrained(str(tmp_path), params, config=config)

        loaded_model, loaded_params = from_pretrained(str(tmp_path))
        assert isinstance(loaded_model, TimeSeriesPerceiver)
        np.testing.assert_allclose(
            np.asarray(loaded_model.apply(loaded_params, x)),
            np.asarray(model.apply(params, x)),
            atol=1e-6,
        )


def write_csv(path: Path, rows: int = 200, channels: int = 3, seed: int = 0):
    rng = np.random.default_rng(seed)
    data = rng.normal(size=(rows, channels)).astype(np.float32)
    header = "date," + ",".join(f"c{i}" for i in range(channels))
    lines = [header] + [f"{i}," + ",".join(f"{v:.6f}" for v in row) for i, row in enumerate(data)]
    path.write_text("\n".join(lines))
    return data


class TestDataModule:
    def test_sliding_windows(self, tmp_path):
        from perceiver_io_tpu.data.timeseries import CSVDataModule

        data = write_csv(tmp_path / "train.csv", rows=100, channels=3)
        dm = CSVDataModule(
            train_path=tmp_path / "train.csv",
            in_len=32,
            out_len=16,
            stride=10,
            batch_size=2,
            usecols=(1, 2, 3),
        )
        ds = dm.dataset("train")
        # windows at starts 0,10,...,50 -> (100 - 48) // 10 + 1
        assert len(ds) == (100 - 48) // 10 + 1
        ex = ds[1]
        np.testing.assert_allclose(ex["x"], data[10:42], atol=1e-6)
        np.testing.assert_allclose(ex["y"], data[42:58], atol=1e-6)

        batch = next(iter(dm.train_batches()))
        assert batch["x"].shape == (2, 32, 3)
        assert batch["y"].shape == (2, 16, 3)

    def test_too_short_series_rejected(self, tmp_path):
        from perceiver_io_tpu.data.timeseries import CSVDataModule

        write_csv(tmp_path / "train.csv", rows=30, channels=3)
        dm = CSVDataModule(
            train_path=tmp_path / "train.csv", in_len=32, out_len=16, usecols=(1, 2, 3)
        )
        with pytest.raises(ValueError, match="too short"):
            dm.dataset("train")


class TestCLI:
    @pytest.mark.slow  # long-compile; the fast subset keeps one representative of this path
    def test_fit(self, tmp_path):
        from perceiver_io_tpu.scripts.timeseries import main

        write_csv(tmp_path / "train.csv", rows=120, channels=3)
        state, _ = main(
            [
                "fit",
                f"--data.train_path={tmp_path / 'train.csv'}",
                "--data.in_len=32",
                "--data.out_len=16",
                "--data.stride=10",
                "--data.batch_size=2",
                "--data.usecols=1,2,3",
                "--model.encoder.num_frequency_bands=4",
                "--model.num_latents=8",
                "--model.num_latent_channels=16",
                "--trainer.devices=1",
                "--trainer.max_steps=2",
                "--trainer.log_interval=1",
                f"--trainer.default_root_dir={tmp_path}",
                "--trainer.checkpoint=false",
                "--optimizer.warmup_steps=1",
            ]
        )
        assert int(state.step) == 2
