"""Overlap-scheduled distributed train step (parallel/overlap.py): loss/grad
equivalence against the GSPMD path and the unsharded step on the 8-virtual-
device CPU mesh, bucketing boundary cases, and the graphlint surface of the
scheduling claim (`collective-overlap` must PASS on the overlap step and
FAIL on a deliberately dependency-serialized schedule — the rule has to
discriminate, not rubber-stamp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from perceiver_io_tpu import analysis
from perceiver_io_tpu.analysis.rules import LintPolicy
from perceiver_io_tpu.parallel import make_mesh, shard_batch
from perceiver_io_tpu.parallel.overlap import (
    OverlapConfig,
    _leaf_plan,
    _plan_buckets,
    expected_collectives,
    make_overlap_train_step,
    parse_mesh_spec,
)
from perceiver_io_tpu.training import TrainState, make_optimizer
from perceiver_io_tpu.training.loop import make_train_step, shard_train_state
from perceiver_io_tpu.utils.compat import shard_map


# --------------------------------------------------------------- toy harness
# A parameter tree covering every bucketing boundary case, with an analytic
# uniform-weighting loss so gradient sync is verifiable to the digit:
#   big      — alone >= bucket_bytes: its own single-leaf bucket (fast path)
#   exact    — exactly bucket_bytes: closes its bucket at the boundary
#   small_*  — coalesce into one multi-leaf bucket
#   odd      — no dim divisible by fsdp: replicated fallback
#   tiny     — below min_weight_size: replicated
BUCKET_BYTES = 64 * 64 * 4  # 16 KiB


def toy_params():
    rng = np.random.default_rng(0)

    def t(*shape):
        return jnp.asarray(rng.standard_normal(shape), jnp.float32)

    return {
        "big": t(128, 64),      # 32 KiB > bucket -> own bucket
        "exact": t(64, 64),     # exactly bucket_bytes
        "small_a": t(16, 8),
        "small_b": t(8, 16),
        "odd": t(7, 3),         # 7 and 3 not divisible by fsdp -> replicated
        "tiny": t(4,),
    }


def toy_loss(params, batch, rng):
    # per-sample weight = x_i.sum(); loss = mean_i(w_i) * sum(all params)
    w = jnp.mean(jnp.sum(batch["x"], axis=-1))
    total = sum(jnp.sum(v) for v in jax.tree.leaves(params))
    loss = w * total
    return loss, {"loss": loss}


toy_loss.uniform_weighting = True


def toy_state(params):
    tx = make_optimizer(1e-2, optimizer="sgd")
    return TrainState.create(lambda *a, **k: None, params, tx, jax.random.PRNGKey(1))


def toy_batch(batch_size=16):
    rng = np.random.default_rng(3)
    return {"x": jnp.asarray(rng.standard_normal((batch_size, 8)), jnp.float32)}


MESHES = [dict(data=8), dict(data=2, fsdp=4), dict(data=4, fsdp=2)]


# ------------------------------------------------------------- bucket plans


def test_plan_buckets_boundary_cases():
    params = toy_params()
    flat = jax.tree_util.tree_leaves(params)
    leaves = _leaf_plan([(p.shape, p.dtype) for p in flat], fsdp_size=4, min_weight_size=32)
    sharded, replicated = _plan_buckets(leaves, BUCKET_BYTES)

    by_index = {lf.index: lf for lf in leaves}
    names = sorted(params)  # dict pytrees flatten in sorted-key order
    dims = {names[i]: lf.dim for i, lf in by_index.items()}
    # non-divisible leaf falls back to replicated, below-threshold leaf too
    assert dims["odd"] is None and dims["tiny"] is None
    assert dims["big"] is not None and dims["exact"] is not None

    def bucket_names(buckets):
        return [[names[lf.index] for lf in b] for b in buckets]

    sh = bucket_names(sharded)
    # big exceeds the bucket size -> closes its own (single-leaf fast path);
    # exact closes at the boundary; the smalls coalesce
    assert ["big"] in sh and ["exact"] in sh
    assert any(set(b) == {"small_a", "small_b"} for b in sh)
    assert any(set(b) == {"odd", "tiny"} for b in bucket_names(replicated))


def test_plan_buckets_splits_dtypes():
    leaves = _leaf_plan(
        [((8, 8), jnp.float32), ((8, 8), jnp.bfloat16), ((8, 8), jnp.float32)],
        fsdp_size=4,
        min_weight_size=0,
    )
    sharded, _ = _plan_buckets(leaves, bucket_bytes=1 << 20)
    # coalescing concatenates flattened leaves — one dtype per bucket
    assert all(len({lf.dtype for lf in b}) == 1 for b in sharded)
    assert len(sharded) == 3  # f32 / bf16 / f32: a dtype change closes the bucket


def test_parse_mesh_spec():
    assert parse_mesh_spec("data=2,fsdp=4") == {"data": 2, "fsdp": 4}
    assert parse_mesh_spec("data=8") == {"data": 8}
    with pytest.raises(ValueError):
        parse_mesh_spec("data=2,tensor=4")
    with pytest.raises(ValueError):
        parse_mesh_spec("8x2")


def test_expected_collectives_counts():
    params = toy_params()
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    exp = expected_collectives(
        params, mesh, microbatch=2, bucket_bytes=BUCKET_BYTES, min_weight_size=32
    )
    # 3 sharded buckets (big / exact / smalls), 1 replicated bucket
    assert exp["all-gather"] == 3
    assert exp["reduce-scatter"] == 2 * 3
    assert exp["all-reduce"] == 2 * (3 + 1) + 1


def test_shard_batch_reports_indivisible_leaf():
    mesh = make_mesh(data=2, fsdp=2, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match=r"\['labels'\].*leading dim 6.*4 shards"):
        shard_batch({"x": np.zeros((8, 4)), "labels": np.zeros((6,))}, mesh)


# --------------------------------------------------- step equivalence (toy)


@pytest.mark.parametrize("shape", MESHES, ids=str)
@pytest.mark.parametrize("microbatch", [1, 2])
def test_overlap_toy_step_matches_gspmd_and_unsharded(shape, microbatch):
    params = toy_params()
    batch = toy_batch()
    mesh = make_mesh(devices=jax.devices()[:8], **shape)
    cfg = OverlapConfig(mesh=mesh, bucket_bytes=BUCKET_BYTES, min_weight_size=32)

    ref_state, ref_m = make_train_step(toy_loss, donate=False, microbatch=microbatch)(
        toy_state(params), batch
    )
    gspmd_state, gspmd_m = make_train_step(toy_loss, donate=False, microbatch=microbatch)(
        shard_train_state(toy_state(params), mesh, min_weight_size=32),
        shard_batch(dict(batch), mesh),
    )
    ov_state, ov_m = make_overlap_train_step(
        toy_loss, cfg, microbatch=microbatch, donate=False
    )(
        shard_train_state(toy_state(params), mesh, min_weight_size=32),
        shard_batch(dict(batch), mesh),
    )

    np.testing.assert_allclose(float(ov_m["loss"]), float(gspmd_m["loss"]), atol=1e-5)
    np.testing.assert_allclose(float(ov_m["loss"]), float(ref_m["loss"]), atol=1e-5)
    for name, a, b, c in zip(
        params,
        jax.tree.leaves(ov_state.params),
        jax.tree.leaves(gspmd_state.params),
        jax.tree.leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, err_msg=name)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=1e-5, err_msg=name)

    # the sync math is verifiable analytically: grad of every leaf is the
    # GLOBAL batch mean of per-sample weights (sgd lr 1e-2)
    w = float(jnp.mean(jnp.sum(batch["x"], axis=-1)))
    before = params["big"]
    after = np.asarray(jax.tree.leaves(ov_state.params)[0])  # 'big' is first
    np.testing.assert_allclose(after, np.asarray(before) - 1e-2 * w, atol=1e-5)


def test_overlap_rejects_padded_batches_and_bad_meshes():
    params = toy_params()
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    step = make_overlap_train_step(
        # undeclared loss: the pad sniff must fire (per-shard means reweight)
        lambda p, b, r: toy_loss(p, b, r),
        OverlapConfig(mesh=mesh, min_weight_size=32),
        donate=False,
        jit=False,
    )
    batch = dict(toy_batch(), pad_mask=np.zeros((16, 8), bool))
    with pytest.raises(ValueError, match="uniform"):
        step(toy_state(params), batch)

    with pytest.raises(ValueError, match="tensor/sequence"):
        make_overlap_train_step(
            toy_loss, OverlapConfig(mesh=make_mesh(data=2, tensor=4, devices=jax.devices()[:8]))
        )


# ------------------------------------------------- graphlint: the scheduling


def _overlap_report(microbatch=2, rules=("collective-budget", "collective-overlap")):
    params = toy_params()
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    cfg = OverlapConfig(mesh=mesh, bucket_bytes=BUCKET_BYTES, min_weight_size=32)
    step = make_overlap_train_step(toy_loss, cfg, microbatch=microbatch, donate=False, jit=False)
    state = shard_train_state(toy_state(params), mesh, min_weight_size=32)
    batch = shard_batch(toy_batch(), mesh)
    exp = expected_collectives(
        params, mesh, microbatch=microbatch, bucket_bytes=BUCKET_BYTES, min_weight_size=32
    )
    budget = dict(exp)
    # the GSPMD optimizer update outside the shard_map region adds per-leaf
    # global-norm partials; only all-reduce needs that headroom
    budget["all-reduce"] += len(jax.tree_util.tree_leaves(params)) + 8
    return analysis.check(
        step,
        (state, batch),
        rules=rules,
        policy=LintPolicy(expect_overlap=True, collective_budget=budget),
        name="toy_overlap_step",
    )


def test_collective_kind_and_count_within_budget():
    """analysis.check pins the overlap step's collective kinds/counts: the
    explicit all-gather/reduce-scatter structure is exactly the bucket plan
    (XLA may combine, never add)."""
    report = _overlap_report()
    assert "collective-budget" in report.rules_run
    assert report.ok(), report.format()


def test_collective_overlap_rule_passes_on_overlap_step():
    report = _overlap_report(rules=("collective-overlap",))
    assert "collective-overlap" in report.rules_run
    assert report.clean, report.format()


def test_collective_overlap_rule_fails_on_serialized_schedule():
    """The discriminator: a chain where every compute op is upstream or
    downstream of every collective — no schedule can overlap it, and the
    rule must say so rather than rubber-stamp."""
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])

    def serialized(x):
        for _ in range(2):
            x = jax.lax.psum_scatter(x, "fsdp", scatter_dimension=0, tiled=True)
            x = jnp.tanh(x @ jnp.ones((x.shape[-1], x.shape[-1]), x.dtype))
            x = jax.lax.all_gather(x, "fsdp", axis=0, tiled=True)
        return x

    fn = shard_map(serialized, mesh=mesh, in_specs=P("fsdp"), out_specs=P("fsdp"))
    report = analysis.check(
        fn,
        (jnp.ones((16, 64)),),
        rules=("collective-overlap",),
        policy=LintPolicy(expect_overlap=True),
        name="serialized_chain",
    )
    assert not report.ok()
    kinds = {v.op for v in report.violations}
    assert kinds == {"all-gather", "reduce-scatter"}
    assert all("serialized" in v.message for v in report.violations)


def test_collective_overlap_rule_inert_without_declaration():
    report = _overlap_report(rules=("collective-overlap",))
    undeclared = analysis.check(
        lambda x: x + 1, (jnp.ones(4),), rules=("collective-overlap",), policy=LintPolicy()
    )
    assert "collective-overlap" in undeclared.rules_skipped
    assert report.rules_run  # sanity: the declared path did run


# --------------------------------------------- trainer integration + events


def test_trainer_overlap_fit_logs_input_wait(tmp_path):
    """Trainer with overlap=True: fits on a data x fsdp mesh through the
    shard_map step, and the per-window log rows carry input_wait_ms (the
    device-side double-buffer satellite)."""
    from perceiver_io_tpu.training.metrics import MetricsLogger
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    mesh = make_mesh(data=2, fsdp=2, devices=jax.devices()[:4])
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(
        toy_loss,
        mesh=mesh,
        logger=logger,
        config=TrainerConfig(
            max_steps=3, log_interval=1, overlap=True, overlap_bucket_mb=0.01,
            fsdp_min_weight_size=32, prefetch_batches=0,
        ),
    )
    batches = [toy_batch(8) for _ in range(3)]
    state = trainer.fit(toy_state(toy_params()), iter(batches))
    logger.close()
    assert int(state.step) == 3

    import csv

    rows = list(csv.DictReader((tmp_path / "metrics.csv").open()))
    waits = [float(r["input_wait_ms"]) for r in rows if r.get("input_wait_ms")]
    assert waits and all(w >= 0.0 for w in waits)


def test_trainer_overlap_requires_mesh():
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    with pytest.raises(ValueError, match="mesh"):
        Trainer(toy_loss, config=TrainerConfig(overlap=True))


def test_overlap_rng_differs_per_shard():
    """The step key must be folded with the device's mesh index: a
    replicated key would draw identical dropout masks on every batch shard.
    Observable via the variance of a per-device uniform draw: E[r^2] >
    E[r]^2 across devices iff the draws differ."""

    def rng_loss(params, batch, rng):
        u = jax.random.uniform(rng, ())
        loss = jnp.mean(batch["x"]) * sum(jnp.sum(v) for v in jax.tree.leaves(params)) * 0.0
        return loss, {"loss": loss, "r": u, "r2": u * u}

    rng_loss.uniform_weighting = True
    mesh = make_mesh(data=4, fsdp=2, devices=jax.devices()[:8])
    cfg = OverlapConfig(mesh=mesh, bucket_bytes=BUCKET_BYTES, min_weight_size=32)
    _, metrics = make_overlap_train_step(rng_loss, cfg, microbatch=1, donate=False)(
        shard_train_state(toy_state(toy_params()), mesh, min_weight_size=32),
        shard_batch(toy_batch(8), mesh),
    )
    variance = float(metrics["r2"]) - float(metrics["r"]) ** 2
    assert variance > 1e-4, f"per-device rng draws are identical (var={variance:.2e})"


def test_trainer_double_buffer_defers_pipeline_errors(tmp_path):
    """A pipeline error hit during the overlapped prefetch must surface at
    the NEXT iteration's fetch — after the completed step's log row — not
    abort the step that already ran."""
    from perceiver_io_tpu.training.metrics import MetricsLogger
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    def batches():
        yield toy_batch(8)
        yield toy_batch(8)
        raise RuntimeError("pipe burst")

    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(
        toy_loss,
        logger=logger,
        config=TrainerConfig(max_steps=5, log_interval=1, prefetch_batches=0,
                             input_double_buffer=True),
    )
    with pytest.raises(RuntimeError, match="pipe burst"):
        trainer.fit(toy_state(toy_params()), batches())
    trainer.close()
    logger.close()

    import csv

    rows = list(csv.DictReader((tmp_path / "metrics.csv").open()))
    # both completed steps logged before the deferred error surfaced
    assert [r["step"] for r in rows if r.get("train_loss")] == ["1", "2"]


def test_trainer_double_buffer_consumes_exactly_max_steps():
    """The double buffer must not steal a batch past the last step: 3 steps
    consume exactly 3 batches (prefetch skipped on the final iteration)."""
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    trainer = Trainer(
        toy_loss,
        config=TrainerConfig(max_steps=3, log_interval=10, prefetch_batches=0,
                             input_double_buffer=True),
    )
    it = iter([toy_batch(8) for _ in range(5)])
    state = trainer.fit(toy_state(toy_params()), it)
    assert int(state.step) == 3
    assert len(list(it)) == 2  # two batches untouched


# --------------------------------------------------- real-model equivalence


@pytest.mark.slow
def test_overlap_clm_step_matches_gspmd_all_meshes():
    """The dryrun bar as a pytest: the tiny Perceiver AR CLM train step,
    overlap-on vs overlap-off (GSPMD) vs unsharded, across the three
    data/fsdp mesh shapes — loss and post-update params within 1e-5."""
    from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
    from perceiver_io_tpu.training import clm_loss_fn

    config = CausalLanguageModelConfig(
        vocab_size=64, max_seq_len=64, max_latents=16, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.0,
    )
    model = CausalLanguageModel(config)
    rng = np.random.default_rng(0)
    t = rng.integers(0, 64, size=(16, 65))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=48)
    loss = clm_loss_fn(model.apply, max_latents=16, deterministic=True)

    def fresh():
        tx = make_optimizer(1e-3, gradient_clip=1.0)
        return TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))

    ref_state, ref_m = make_train_step(loss, donate=False, microbatch=2)(fresh(), batch)

    for shape in MESHES:
        mesh = make_mesh(devices=jax.devices()[:8], **shape)
        cfg = OverlapConfig(mesh=mesh, bucket_bytes=32 * 1024, min_weight_size=0)
        sb = shard_batch(dict(batch), mesh)
        gspmd_state, gspmd_m = make_train_step(loss, donate=False, microbatch=2)(
            shard_train_state(fresh(), mesh, min_weight_size=0), sb
        )
        ov_state, ov_m = make_overlap_train_step(loss, cfg, microbatch=2, donate=False)(
            shard_train_state(fresh(), mesh, min_weight_size=0), sb
        )
        np.testing.assert_allclose(
            float(ov_m["loss"]), float(gspmd_m["loss"]), atol=1e-5, err_msg=str(shape)
        )
        np.testing.assert_allclose(
            float(ov_m["loss"]), float(ref_m["loss"]), atol=1e-5, err_msg=str(shape)
        )
        for a, b, c in zip(
            jax.tree.leaves(ov_state.params),
            jax.tree.leaves(gspmd_state.params),
            jax.tree.leaves(ref_state.params),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
            np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=1e-5)
