"""Shedline (ISSUE 12): the hardened serving front end — deadline-aware
admission with first-class shedding, mid-decode cancellation through the
``on_token`` seam, the error-rate/sentinel-fed circuit breaker with
RetryPolicy-spaced probes, bounded pre-decode retry, graceful drain, and
the clean-books invariant under every injected failure."""

import json
import os
import urllib.request

import jax
import numpy as np
import pytest

from perceiver_io_tpu.generation import GenerationAborted, GenerationDeadlineExceeded
from perceiver_io_tpu.obs.events import EventLog, merged_events, validate_events
from perceiver_io_tpu.obs.flightrec import FlightRecorder, SLOBounds
from perceiver_io_tpu.obs.loadgen import WorkloadSpec, run_load
from perceiver_io_tpu.serving import (
    BreakerConfig,
    CircuitBreaker,
    FaultInjector,
    FrontEndConfig,
    ManualClock,
    RequestFrontEnd,
    poison_params,
)
from perceiver_io_tpu.training.faults import RetryPolicy

# one compiled geometry for the whole module (prompt 10, 4 new tokens)
SPEC = WorkloadSpec(seed=7, prompt_lens=(10,), max_new_tokens=(4,))


@pytest.fixture(scope="module")
def tiny_model():
    from perceiver_io_tpu.models.text import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )

    config = CausalLanguageModelConfig(
        vocab_size=50, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    ids = np.random.default_rng(0).integers(0, 50, size=(1, 12))
    import jax.numpy as jnp

    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids), prefix_len=8)
    return model, params


def make_frontend(tiny_model, tmp_path, *, recorder=False, slo=None, clock=None,
                  injector=None, config=None, **kw):
    model, params = tiny_model
    events = EventLog(str(tmp_path), main_process=True)
    if recorder:
        events = FlightRecorder(events, out_dir=str(tmp_path),
                                slo=slo if slo is not None else SLOBounds())
    clock = clock or ManualClock()
    fe = RequestFrontEnd(
        model, params, num_latents=4, config=config, events=events,
        clock=clock, sleep=clock.sleep, injector=injector, **kw,
    )
    return fe, events, clock


# ------------------------------------------------------------ manual clock


def test_manual_clock_semantics():
    c = ManualClock(1.0)
    assert c() == 1.0
    c.advance(0.5)
    c.advance_to(1.2)  # never backwards
    assert c() == 1.5
    c.sleep(0.5)
    assert c() == 2.0
    with pytest.raises(ValueError):
        c.advance(-1.0)


# ---------------------------------------------------------------- breaker


def test_breaker_opens_on_error_rate_and_probe_cycle():
    clock = ManualClock()
    transitions = []
    br = CircuitBreaker(
        BreakerConfig(window=4, min_requests=3, error_rate_to_open=0.5,
                      probe_backoff=RetryPolicy(base_delay=1.0, max_delay=8.0, jitter=0.0)),
        clock=clock,
        on_transition=lambda p, n, r, d: transitions.append((p, n, r)),
    )
    assert br.allow() == "admit"
    br.record(True)
    br.record(False)
    assert br.state == "closed"  # min_requests guards the tiny sample
    br.record(False)
    assert br.state == "open" and br.error_rate() == pytest.approx(2 / 3)
    assert transitions == [("closed", "open", "error-rate")]
    assert br.allow() == "shed" and br.shed_total == 1
    # probe spacing = RetryPolicy.delay(n_opens-1): 1.0s for the first open
    clock.advance(0.99)
    assert br.allow() == "shed"
    clock.advance(0.02)
    assert br.allow() == "probe"
    assert br.allow() == "shed"  # one probe in flight, others keep shedding
    br.record(False, probe=True)  # probe fails: reopen at the NEXT rung (2.0s)
    assert br.state == "open" and br.n_opens == 2
    clock.advance(1.5)
    assert br.allow() == "shed"  # 2.0s rung not elapsed yet
    clock.advance(0.6)
    assert br.allow() == "probe"
    br.record(True, probe=True)
    assert br.state == "closed" and br.n_opens == 0
    # the failure window was reset: one old-regime error cannot re-trip
    br.record(False)
    assert br.state == "closed"
    assert [t[1] for t in transitions] == ["open", "half_open", "open", "half_open", "closed"]


def test_breaker_unjudged_probe_releases_without_closing():
    """A probe that never exercised the backend (deadline expired queued,
    caller cancelled) must NOT close the breaker — release_probe frees the
    slot so the next arrival probes again, state unchanged."""
    clock = ManualClock()
    br = CircuitBreaker(
        BreakerConfig(window=4, min_requests=2, error_rate_to_open=0.5,
                      probe_backoff=RetryPolicy(base_delay=1.0, jitter=0.0)),
        clock=clock,
    )
    br.record(False)
    br.record(False)
    assert br.state == "open"
    clock.advance(1.1)
    assert br.allow() == "probe"
    br.release_probe()  # the probe timed out / was cancelled: unjudged
    assert br.state == "half_open"  # still not closed
    assert br.allow() == "probe"  # slot free again: next arrival probes
    br.record(True, probe=True)
    assert br.state == "closed"


def test_breaker_stale_probe_cannot_judge_a_newer_cycle():
    """A probe verdict arriving after ANOTHER open happened (a sentinel
    re-opened the breaker while the probe was queued) is stale: it must not
    close, re-open, or release the new cycle's probe slot."""
    clock = ManualClock()
    br = CircuitBreaker(
        BreakerConfig(window=4, min_requests=2, error_rate_to_open=0.5,
                      probe_backoff=RetryPolicy(base_delay=1.0, jitter=0.0)),
        clock=clock,
    )
    br.record(False)
    br.record(False)
    clock.advance(1.1)
    assert br.allow() == "probe"
    stale_cycle = br.cycle
    br.record_sentinel("nonfinite-logits")  # re-opens mid-probe: cycle moves on
    assert br.state == "open" and br.cycle == stale_cycle + 1
    # the stale probe finishing ok must NOT close the sentinel-opened breaker
    br.record(True, probe=True, cycle=stale_cycle)
    assert br.state == "open"
    clock.advance(2.1)  # the new cycle's backoff rung
    assert br.allow() == "probe"
    # a stale release must not free the NEW cycle's in-flight probe slot
    br.release_probe(cycle=stale_cycle)
    assert br.allow() == "shed"  # new probe still in flight
    br.record(True, probe=True, cycle=br.cycle)
    assert br.state == "closed"


def test_breaker_sentinel_opens_immediately_and_health():
    clock = ManualClock()
    br = CircuitBreaker(BreakerConfig(), clock=clock)
    br.record_sentinel("nonfinite-logits")
    assert br.state == "open" and br.opens_total == 1
    h = br.health()
    assert h["state"] == "open" and h["probe_in_s"] > 0
    br.record_sentinel()  # already open: no double-count
    assert br.opens_total == 1


# ----------------------------------------------------------- fault injector


def test_fault_injector_plan_and_audit():
    clock = ManualClock()
    inj = FaultInjector(clock=clock)
    inj.stall_at(None, 1, 0.2).stall_at(3, 2, 1.0).kill_at(3, 3)
    inj.on_token(0, 0)  # no plan at token 0: nothing
    inj.on_token(0, 1)
    assert clock() == pytest.approx(0.2)  # wildcard stall
    inj.on_token(3, 2)
    assert clock() == pytest.approx(1.2)  # per-request stall
    from perceiver_io_tpu.serving import InjectedFault

    with pytest.raises(InjectedFault):
        inj.on_token(3, 3)
    inj.on_token(3, 3)  # kills fire once
    assert [i["kind"] for i in inj.injected] == ["stall", "stall", "kill"]

    inj2 = FaultInjector().fail_prefill(1, times=2, exc_type=TimeoutError)
    with pytest.raises(TimeoutError):
        inj2.before_attempt(1)
    with pytest.raises(TimeoutError):
        inj2.before_attempt(1)
    inj2.before_attempt(1)  # exhausted: clean
    inj2.before_attempt(0)  # unplanned request: clean

    # seeded kills are deterministic per seed
    a = FaultInjector().seeded_kills(50, 0.2, seed=3)
    b = FaultInjector().seeded_kills(50, 0.2, seed=3)
    assert a._kills.keys() == b._kills.keys() and len(a._kills) > 0
    assert a._kills.keys() != FaultInjector().seeded_kills(50, 0.2, seed=4)._kills.keys()


def test_poison_params_plants_one_nan():
    params = {"a": {"w": np.ones((2, 2), np.float32)}, "ids": np.arange(3)}
    poisoned = poison_params(params)
    assert np.isnan(np.asarray(poisoned["a"]["w"])).sum() == 1
    assert np.isnan(np.asarray(params["a"]["w"])).sum() == 0  # original untouched
    with pytest.raises(ValueError):
        poison_params({"ids": np.arange(3)})  # nothing poisonable


# ----------------------------------------------------- admission / shedding


def test_admission_sheds_are_first_class(tiny_model, tmp_path):
    """queue_full / deadline_unmeetable / draining sheds: never served,
    never silent — each books as terminal `shed` with a reasoned request
    event, and the books identities hold throughout."""
    fe, events, clock = make_frontend(
        tiny_model, tmp_path,
        config=FrontEndConfig(max_queue=3, est_service_s=1.0, breaker=None),
    )
    specs = SPEC.draw(8, 50)
    fe.submit(specs[0])
    fe.submit(specs[1])
    # projection: 2 queued * 1.0s estimate > 1.5s deadline
    late = fe.submit(specs[3], deadline_s=1.5)
    assert late.outcome == "shed" and late.shed_reason == "deadline_unmeetable"
    # a roomy deadline admits fine (queue now at its 3-deep cap)
    ok = fe.submit(specs[4], deadline_s=60.0)
    assert ok.outcome is None
    full = fe.submit(specs[2])
    assert full.outcome == "shed" and full.shed_reason == "queue_full"
    b = fe.books()
    assert b["submitted"] == 5 and b["admitted"] == 3 and b["shed"] == 2
    assert b["balanced"] and b["queued"] == 3
    fe.pump()
    fe._draining = True
    drained = fe.submit(specs[5])
    assert drained.outcome == "shed" and drained.shed_reason == "draining"
    assert fe.audit() == []
    rows = [e for e in merged_events(str(tmp_path))
            if e.get("event") == "request" and e.get("outcome") == "shed"]
    assert [e["shed_reason"] for e in rows] == [
        "deadline_unmeetable", "queue_full", "draining",
    ]
    # shed rows carry their own spans (flight dumps can name them)
    assert all(e.get("span_id") for e in rows)
    assert validate_events(str(tmp_path), warnings_out=[]) == []
    assert fe.registry.counter("serve_shed_total").value == 3


def test_closed_loop_clean_path_books_and_metrics(tiny_model, tmp_path):
    fe, events, clock = make_frontend(tiny_model, tmp_path)
    recs = fe.run_closed(SPEC.draw(5, 50), concurrency=2)
    assert [r.outcome for r in recs] == ["ok"] * 5
    assert all(r.tokens_out == 4 for r in recs)
    b = fe.books()
    assert b["balanced"] and b["ok"] == 5 and b["terminal"] == 5
    assert b["max_queue_depth"] == 2  # closed loop pins the depth
    assert fe.audit() == []
    assert fe.registry.counter("serve_submitted_total").value == 5
    assert fe.registry.counter("serve_admitted_total").value == 5
    assert fe.registry.gauge("serve_queue_depth").value == 0
    # queue-wait flowed into the shared admission histogram
    assert fe.registry.histogram("generate_queue_wait_s").n == 5
    assert validate_events(str(tmp_path), warnings_out=[]) == []


# ------------------------------------------------- mid-decode cancellation


def test_deadline_mid_decode_times_out_with_partial_stats(tiny_model, tmp_path):
    clock = ManualClock()
    inj = FaultInjector(clock=clock).stall_at(1, 1, 9.0)
    fe, events, clock = make_frontend(tiny_model, tmp_path, recorder=True,
                                      clock=clock, injector=inj)
    recs = fe.run_closed(SPEC.draw(3, 50), concurrency=1, deadline_s=2.0)
    assert [r.outcome for r in recs] == ["ok", "timeout", "ok"]
    dead = recs[1]
    assert 0 < dead.tokens_out < 4 and dead.service_s >= 9.0
    assert fe.audit() == []
    row = next(e for e in merged_events(str(tmp_path))
               if e.get("event") == "request" and e.get("outcome") == "timeout")
    assert row["tokens_out"] == dead.tokens_out and row["ttft_s"] > 0
    assert row.get("tpot_hist"), "partial TPOT distribution missing"
    # the timeout triggered exactly one dump naming the span
    dumps = events.dumps
    assert len(dumps) == 1 and "flight-timeout" in os.path.basename(dumps[0])
    assert json.load(open(dumps[0]))["trigger_span_id"] == row["span_id"]


def test_queue_expired_deadline_times_out_without_serving(tiny_model, tmp_path):
    clock = ManualClock()
    inj = FaultInjector(clock=clock).stall_at(0, 1, 5.0)  # head hogs the worker
    fe, events, clock = make_frontend(
        tiny_model, tmp_path, clock=clock, injector=inj,
        # projection off: the doomed request must be ADMITTED to expire queued
        config=FrontEndConfig(admission_projection=False, breaker=None),
    )
    recs = fe.run_closed(SPEC.draw(2, 50), concurrency=2, deadline_s=1.0)
    assert recs[0].outcome == "timeout" or recs[1].outcome == "timeout"
    expired = recs[1]
    assert expired.outcome == "timeout" and expired.tokens_out == 0
    assert expired.queue_wait_s >= 5.0  # sat behind the stalled head
    assert fe.registry.counter("serve_queue_expired_total").value == 1
    assert fe.audit() == []
    rows = [e for e in merged_events(str(tmp_path))
            if e.get("event") == "request" and e.get("queue_expired")]
    assert len(rows) == 1 and rows[0]["outcome"] == "timeout"
    assert validate_events(str(tmp_path), warnings_out=[]) == []


def test_cancel_queued_and_mid_decode(tiny_model, tmp_path):
    inj = FaultInjector()
    inj.kill_at(0, 1, exc=lambda: GenerationAborted("client went away"))
    fe, events, clock = make_frontend(tiny_model, tmp_path, injector=inj)
    specs = SPEC.draw(3, 50)
    for s in specs:
        fe.submit(s)
    assert fe.cancel(2) is True
    assert fe.cancel(99) is False
    fe.pump()
    outcomes = {r.index: r.outcome for r in fe.records}
    assert outcomes == {0: "cancelled", 1: "ok", 2: "cancelled"}
    mid = next(r for r in fe.records if r.index == 0)
    assert mid.tokens_out > 0  # aborted MID-decode, partial stream accounted
    queued = next(r for r in fe.records if r.index == 2)
    assert queued.tokens_out == 0  # never served
    assert fe.audit() == []
    assert validate_events(str(tmp_path), warnings_out=[]) == []


def test_generation_aborted_outcomes_pinned():
    assert GenerationAborted.outcome == "cancelled"
    assert GenerationDeadlineExceeded.outcome == "timeout"
    assert issubclass(GenerationDeadlineExceeded, GenerationAborted)


# --------------------------------------------------------- pre-decode retry


def test_transient_predecode_failures_retried_with_events(tiny_model, tmp_path):
    inj = FaultInjector().fail_prefill(1, times=2)
    fe, events, clock = make_frontend(
        tiny_model, tmp_path, injector=inj,
        config=FrontEndConfig(retry=RetryPolicy(max_retries=3, base_delay=0.01)),
    )
    recs = fe.run_closed(SPEC.draw(3, 50), concurrency=1)
    assert [r.outcome for r in recs] == ["ok", "ok", "ok"]
    assert next(r for r in recs if r.index == 1).attempts == 3
    retries = [e for e in merged_events(str(tmp_path)) if e.get("event") == "serve.retry"]
    assert [e["attempt"] for e in retries] == [0, 1]
    assert all(e["request_index"] == 1 for e in retries)
    assert fe.registry.counter("serve_retries_total").value == 2
    # the injected sleeps advanced the manual clock (RetryPolicy schedule)
    assert clock() > 0
    assert fe.audit() == []
    # ONE terminal request row per submitted request, retries or not —
    # books and stream agree exactly
    rows = [e for e in merged_events(str(tmp_path)) if e.get("event") == "request"]
    assert len(rows) == 3 and [r["outcome"] for r in rows] == ["ok"] * 3


def test_predecode_retry_exhaustion_books_original_error(tiny_model, tmp_path):
    inj = FaultInjector().fail_prefill(0, times=9)
    fe, events, clock = make_frontend(
        tiny_model, tmp_path, injector=inj,
        config=FrontEndConfig(retry=RetryPolicy(max_retries=1, base_delay=0.01)),
    )
    recs = fe.run_closed(SPEC.draw(2, 50), concurrency=1)
    assert [r.outcome for r in recs] == ["error", "ok"]
    # reraise=True: the books carry the ORIGINAL exception type, no wrapper
    assert "OSError" in recs[0].error and "FetchRetriesExhausted" not in recs[0].error
    assert recs[0].attempts == 2
    assert fe.audit() == []
    # the failure never reached the decode path, so the FRONT END emitted
    # the terminal row: stream and books still agree 1:1
    rows = [e for e in merged_events(str(tmp_path)) if e.get("event") == "request"]
    assert [r["outcome"] for r in rows] == ["error", "ok"]
    assert "OSError" in rows[0]["error"] and rows[0].get("span_id")
    assert validate_events(str(tmp_path), warnings_out=[]) == []


def test_decode_path_transient_never_retried(tiny_model, tmp_path):
    """A transient-typed failure from INSIDE the decode path books as one
    error with one attempt — the instrumented wrapper already emitted that
    attempt's request event (a retry would double-count the request in the
    stream), and any streamed tokens are gone (a replay would double-serve
    them). The DecodePathFailure wrap keeps call_with_retry's hands off."""
    inj = FaultInjector().kill_at(0, 2, exc=lambda: OSError("nic died mid-stream"))
    fe, events, clock = make_frontend(
        tiny_model, tmp_path, injector=inj,
        config=FrontEndConfig(retry=RetryPolicy(max_retries=3, base_delay=0.01)),
    )
    recs = fe.run_closed(SPEC.draw(2, 50), concurrency=1)
    assert recs[0].outcome == "error" and recs[0].attempts == 1
    assert "nic died" in recs[0].error
    assert recs[0].tokens_out > 0
    assert recs[1].outcome == "ok"
    assert fe.audit() == []
    rows = [e for e in merged_events(str(tmp_path)) if e.get("event") == "request"]
    assert [r["outcome"] for r in rows] == ["error", "ok"]  # exactly one row each


def test_prologue_failure_still_gets_its_one_stream_row(tiny_model, tmp_path):
    """A failure in the instrumented wrapper's PRE-emit prologue (here: a
    1-D prompt that blows up before the wrapper's emit path arms) carries
    no stats marker — the front end must emit the terminal row itself, so
    the stream stays 1:1 with the books instead of silently dropping a
    booked request."""
    from perceiver_io_tpu.obs.loadgen import RequestSpec

    fe, events, clock = make_frontend(tiny_model, tmp_path)
    bad = RequestSpec(index=0, prompt_len=10, max_new_tokens=4,
                      input_ids=np.zeros((10,), np.int32), rng_seed=1)  # 1-D!
    fe.submit(bad)
    fe.submit(SPEC.draw(2, 50)[1])
    fe.pump()
    assert [r.outcome for r in fe.records] == ["error", "ok"]
    assert fe.audit() == []
    rows = [e for e in merged_events(str(tmp_path)) if e.get("event") == "request"]
    assert [r["outcome"] for r in rows] == ["error", "ok"]  # exactly one row each
    assert "error" in rows[0] and rows[0].get("span_id")
    assert validate_events(str(tmp_path), warnings_out=[]) == []


# ------------------------------------------------------- breaker, end to end


def test_breaker_trips_sheds_and_recovers_end_to_end(tiny_model, tmp_path):
    clock = ManualClock()
    inj = FaultInjector(clock=clock)
    for i in (1, 2, 3):
        inj.kill_at(i, 1)
    cfg = FrontEndConfig(breaker=BreakerConfig(
        window=4, min_requests=3, error_rate_to_open=0.5,
        probe_backoff=RetryPolicy(base_delay=2.0, max_delay=10.0, jitter=0.0),
    ))
    fe, events, clock = make_frontend(tiny_model, tmp_path, recorder=True,
                                      clock=clock, injector=inj, config=cfg)
    specs = SPEC.draw(10, 50)
    recs = fe.run_closed(specs[:8], concurrency=1)
    assert fe.breaker.state == "open"
    assert any(r.shed_reason == "breaker_open" for r in recs)
    assert fe.registry.gauge("serve_breaker_state").value == 2  # open
    clock.advance(2.0)
    probe = fe.submit(specs[8])
    fe.pump()
    assert probe.probe and probe.outcome == "ok" and fe.breaker.state == "closed"
    assert fe.registry.gauge("serve_breaker_state").value == 0
    assert fe.audit() == []
    assert any("flight-breaker" in os.path.basename(p) for p in events.dumps)
    assert validate_events(str(tmp_path), warnings_out=[]) == []


def test_timed_out_probe_does_not_close_breaker(tiny_model, tmp_path):
    """End-to-end version of the unjudged-probe rule: the half-open probe's
    deadline expires while queued, so the backend is never exercised — the
    breaker must stay half-open (not close), and the NEXT admission probes."""
    clock = ManualClock()
    inj = FaultInjector(clock=clock)
    for i in (0, 1):
        inj.kill_at(i, 1)
    cfg = FrontEndConfig(
        admission_projection=False,  # the doomed probe must be ADMITTED
        breaker=BreakerConfig(window=4, min_requests=2, error_rate_to_open=0.5,
                              probe_backoff=RetryPolicy(base_delay=1.0, jitter=0.0)),
    )
    fe, events, clock = make_frontend(tiny_model, tmp_path, clock=clock,
                                      injector=inj, config=cfg)
    specs = SPEC.draw(5, 50)
    fe.run_closed(specs[:2], concurrency=1)  # two errors open the breaker
    assert fe.breaker.state == "open"
    clock.advance(1.1)
    probe = fe.submit(specs[2], deadline_s=0.5)  # admitted as THE probe
    assert probe.probe is True
    clock.advance(2.0)  # its deadline expires before the worker gets to it
    fe.pump()
    assert probe.outcome == "timeout"
    assert fe.breaker.state == "half_open"  # unjudged: NOT closed
    nxt = fe.submit(specs[3])
    fe.pump()
    assert nxt.probe is True and nxt.outcome == "ok"
    assert fe.breaker.state == "closed"  # a SERVED ok probe closes it
    assert fe.audit() == []


def test_nonfinite_logits_feed_breaker_sentinel(tiny_model, tmp_path):
    """The Probeline gauge loop closed: poisoned params -> real NaN logits
    through the compiled decode -> nonfinite_logit_frac on the stats ->
    sentinel-opened breaker -> subsequent admissions shed."""
    inj = FaultInjector().poison_at(1)
    fe, events, clock = make_frontend(tiny_model, tmp_path, injector=inj,
                                      config=FrontEndConfig(probes=True))
    recs = fe.run_closed(SPEC.draw(4, 50), concurrency=1)
    assert fe.breaker.state == "open"
    assert [r.outcome for r in recs] == ["ok", "ok", "shed", "shed"]
    assert all(r.shed_reason == "breaker_open" for r in recs[2:])
    trans = [e for e in merged_events(str(tmp_path)) if e.get("event") == "serve.breaker"]
    assert trans and trans[0]["reason"] == "nonfinite-logits"
    poisoned_row = [e for e in merged_events(str(tmp_path))
                    if e.get("event") == "request"][1]
    assert poisoned_row["nonfinite_logit_frac"] == 1.0
    assert fe.audit() == []


# ------------------------------------------------------------------- drain


def test_guard_trip_drains_and_books_balance(tiny_model, tmp_path):
    from perceiver_io_tpu.training.faults import PreemptionGuard

    fe, events, clock = make_frontend(tiny_model, tmp_path)
    guard = PreemptionGuard()
    fe._guard = guard  # trip programmatically (no real signal in pytest workers)
    specs = SPEC.draw(6, 50)
    for s in specs[:4]:
        fe.submit(s)
    fe.pump(max_requests=1)
    guard.trip()
    fe.pump()  # guard noticed; queued work still finishes
    late = [fe.submit(s) for s in specs[4:]]
    books = fe.drain()
    assert all(r.outcome == "shed" and r.shed_reason == "draining" for r in late)
    assert books["ok"] == 4 and books["shed"] == 2 and books["balanced"]
    assert fe.audit() == []
    stream = merged_events(str(tmp_path))
    assert any(e.get("event") == "serve.preempt" for e in stream)
    drains = [e for e in stream if e.get("event") == "serve.drain"]
    assert len(drains) == 1 and drains[0]["books"]["balanced"] is True
    assert validate_events(str(tmp_path), warnings_out=[]) == []
    assert fe.health()["status"] == "draining"


# ----------------------------------------------------- /healthz exposition


def test_obs_server_health_provider_merges_and_degrades(tiny_model, tmp_path):
    from perceiver_io_tpu.obs.server import ObsServer

    fe, events, clock = make_frontend(tiny_model, tmp_path)
    fe.run_closed(SPEC.draw(2, 50), concurrency=1)

    def get(url):
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.loads(r.read())

    with ObsServer(registry=fe.registry, run_dir=str(tmp_path), health=fe.health) as srv:
        h = get(srv.url + "/healthz")
        assert h["status"] == "ok" and h["books_balanced"] is True
        assert h["breaker"]["state"] == "closed"
        assert h["outcomes"]["ok"] == 2
        # breaker open -> the provider overrides status for the LB
        fe.breaker.record_sentinel("nonfinite-logits")
        assert get(srv.url + "/healthz")["status"] == "shedding"

    def broken():
        raise RuntimeError("health backend down")

    with ObsServer(registry=fe.registry, health=broken) as srv:
        h = get(srv.url + "/healthz")
        assert h["status"] == "ok" and "health backend down" in h["health_error"]


# ------------------------------------------- slo taxonomy / loadgen clock


def test_slo_report_accounts_shed_and_timeout(tiny_model, tmp_path):
    from perceiver_io_tpu.obs.slo import build_slo_report

    clock = ManualClock()
    inj = FaultInjector(clock=clock).stall_at(None, 1, 0.1)
    fe, events, clock = make_frontend(
        tiny_model, tmp_path, clock=clock, injector=inj,
        config=FrontEndConfig(max_queue=32, est_service_s=0.1),
    )
    fe.run_open(SPEC.draw(20, 50), rate_rps=50.0, deadline_s=0.5, seed=11)
    report = build_slo_report(merged_events(str(tmp_path)))
    b = fe.books()
    assert report["outcomes"].get("shed") == b["shed"] > 0
    assert report["n_admitted"] == b["admitted"]
    # shed_rate is a share of ALL traffic; served-path rates (error/
    # timeout/cancelled) are over ADMITTED requests only — shedding must
    # not dilute them
    assert report["shed_rate"] == pytest.approx(b["shed"] / 20, abs=1e-6)
    if b["timeout"]:
        assert report["timeout_rate"] == pytest.approx(
            b["timeout"] / b["admitted"], abs=1e-6
        )
    # latency pools stay admitted-ok-only: shed rows carry no latency
    assert report["n_latency_requests"] <= b["ok"]
    assert fe.audit() == []


def test_run_load_open_loop_with_injected_clock_is_wall_clock_free(tiny_model, tmp_path):
    """Satellite: `run_load(..., sleep=, clock=)` — open-loop pacing off a
    ManualClock never sleeps for real, and queue waits/duration come off
    the manual timeline (deterministic: the sleeps exactly chase the seeded
    schedule, so measured queue wait is 0 and duration == last offset)."""
    model, params = tiny_model
    events = EventLog(str(tmp_path), main_process=True)
    clock = ManualClock()
    report = run_load(
        model, params, SPEC, mode="open", n_requests=4, rate_rps=20.0,
        num_latents=4, events=events, sleep=clock.sleep, clock=clock,
    )
    from perceiver_io_tpu.obs.loadgen import arrival_schedule

    offsets = arrival_schedule(4, 20.0, seed=SPEC.seed + 1)
    # the worker slept up to each arrival on the manual clock: zero lag
    assert [r.queue_wait_s for r in report.records] == [0.0] * 4
    assert report.summary["duration_s"] == pytest.approx(offsets[-1], abs=1e-6)
    assert clock() == pytest.approx(offsets[-1], abs=1e-6)


# ------------------------------------------- hostlint true-positive pins


def test_books_snapshot_is_consistent_under_scrape_hammer(tiny_model, tmp_path):
    """Hostlint fix pin (shared-state-race:RequestFrontEnd._n): a scrape
    thread hammering books() while the serving thread books outcomes must
    always see a CONSISTENT terminal decomposition — the per-outcome counts
    and their sum come from one _books_lock'd snapshot, never a torn read
    taken mid-booking."""
    import threading

    from perceiver_io_tpu.serving.frontend import TERMINAL_OUTCOMES

    fe, events, clock = make_frontend(tiny_model, tmp_path)
    stop = threading.Event()
    torn = []

    def scrape():
        while not stop.is_set():
            b = fe.books()
            if b["terminal"] != sum(b[o] for o in TERMINAL_OUTCOMES):
                torn.append(b)

    t = threading.Thread(target=scrape)
    t.start()
    try:
        fe.run_closed(SPEC.draw(6, 50), concurrency=2)
    finally:
        stop.set()
        t.join()
    assert torn == [], f"torn books snapshot(s): {torn[:3]}"
    assert fe.books()["balanced"] and fe.audit() == []


def test_default_registry_shares_the_injected_clock(tiny_model, tmp_path):
    """Hostlint fix pin (clock-discipline:MetricsRegistry): when the front
    end builds its default registry, the registry's rate-limit clock IS the
    front end's injected clock — a ManualClock run rate-limits metrics
    emission in virtual time, not off the wall."""
    fe, events, clock = make_frontend(tiny_model, tmp_path)
    assert fe.registry._clock is clock


def test_flightrec_dumps_list_consistent_under_concurrent_emit(tmp_path):
    """Hostlint fix pin (shared-state-race:FlightRecorder.dumps): dump()
    appends to the dumps list under the ring's lock, so dumps triggered
    from the serving thread and the signal frame interleave without losing
    entries; every returned path is recorded, in order."""
    import threading

    rec = FlightRecorder(None, out_dir=str(tmp_path), max_dumps=64)
    stop = threading.Event()

    def chatter():
        while not stop.is_set():
            rec.emit("probe", step=1)

    t = threading.Thread(target=chatter)
    t.start()
    try:
        paths = [rec.dump("sigusr1") for _ in range(16)]
    finally:
        stop.set()
        t.join()
    paths = [p for p in paths if p is not None]
    assert paths and rec.dumps == paths
