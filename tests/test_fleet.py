"""Fleetline tests (ISSUE 20): the replicated-engine router
(``serving/router.py``) — least-outstanding dispatch with deterministic
ties, bounded re-dispatch of ADMISSION sheds only, graceful drain with
zero attributable sheds, journal-backed failover through the
``EngineFrontEnd.recover`` handoff seam (fleet books identity: nothing
lost, nothing served twice), heartbeat-timeout death on the injected
clock, brownout degradation (EWMA vs the fleet floor) steering dispatch
off a slow replica, and the fleet health/books/metrics surfaces.

No jax computation runs anywhere in this file: every replica is a
``SimEngineFrontEnd`` (sampled service times over the REAL host control
plane) on a shared ``ManualClock``, which is the wall-clock-free property
the chaos scenarios (``tools/chaos.py serve_fleet_*``) certify at scale.
Token-exact failover on the compiled engine is pinned there and in
``tests/test_evictline.py``; this file pins the ROUTER's laws.
"""

import os

import pytest

from perceiver_io_tpu.obs.events import EventLog, merged_events, validate_events
from perceiver_io_tpu.obs.loadgen import WorkloadSpec
from perceiver_io_tpu.obs.metrics import MetricsRegistry
from perceiver_io_tpu.serving import (
    EngineConfig,
    FaultInjector,
    FleetConfig,
    FleetRouter,
    FrontEndConfig,
    ManualClock,
    RequestJournal,
)
from perceiver_io_tpu.serving.sim import ServiceTimeModel, SimEngineFrontEnd

VOCAB = 64

MODEL = ServiceTimeModel(
    prefill_p50_s=0.002, prefill_p99_s=0.004,
    tpot_p50_s=0.0005, tpot_p99_s=0.001, source="test_synthetic",
)


def _specs(n, seed=13):
    return WorkloadSpec(seed=seed, prompt_lens=(8, 12),
                        max_new_tokens=(3, 4)).draw(n, VOCAB)


def _fleet(n=2, *, clock=None, events=None, registry=None, config=None,
           injector=None, journal_dir=None, max_queue=64):
    """A fleet of ``n`` sim replicas on ONE shared ManualClock.

    Breaker and admission projection are off so the tests steer admission
    with ``max_queue`` alone; journals only where a test fails over."""
    clock = clock if clock is not None else ManualClock()
    router = FleetRouter(clock=clock, events=events, registry=registry,
                        config=config, injector=injector)
    fes = {}
    for i in range(n):
        rid = f"r{i}"
        fe = SimEngineFrontEnd(
            service_model=MODEL,
            engine_config=EngineConfig(slots=4, page_size=8,
                                       max_ca_tokens=24, max_sa_tokens=8),
            clock=clock, seed=7 + i, replica_id=rid,
            config=FrontEndConfig(max_queue=max_queue,
                                  admission_projection=False, breaker=None),
            events=events, registry=registry, injector=injector,
            journal=(os.path.join(journal_dir, f"journal-{rid}.jsonl")
                     if journal_dir else None),
        )
        router.add_replica(rid, fe)
        fes[rid] = fe
    return router, fes, clock


# ---------------------------------------------------------------- dispatch


def test_least_outstanding_dispatch_alternates_and_drains_clean():
    """An idle fleet alternates submissions (least-outstanding with the
    deterministic replica-id tie-break), the labeled ``router_dispatch``
    children decompose the unlabeled total, and a full pump drains to
    balanced fleet books with an empty audit."""
    registry = MetricsRegistry()
    router, fes, clock = _fleet(3, registry=registry)
    recs = [router.submit(s) for s in _specs(6)]
    with router._lock:
        assigned = dict(router._assigned)
    # 6 submissions over 3 idle replicas: r0 r1 r2 r0 r1 r2
    assert [assigned[i] for i in range(6)] == ["r0", "r1", "r2"] * 2
    assert router.books()["dispatched"] == 6
    done = router.pump()
    assert done == 6 and all(r.outcome == "ok" for r in recs)
    books = router.books()
    assert books["balanced"] and books["outcomes"]["ok"] == 6, books
    assert books["requeued"] == 0 and books["failovers"] == 0
    assert router.audit() == []
    # metrics: per-replica children sum to the family total
    disp = registry.counter("router_dispatch_total")
    assert disp.value == 6
    assert sum(disp.labels(replica=r).value for r in fes) == 6
    text = registry.to_prometheus()
    assert 'router_dispatch_total{replica="r0"}' in text
    assert "router_replicas_active" in text


def test_redispatch_bounded_to_admission_sheds():
    """An admission shed (queue full, ZERO tokens served) is retried on the
    other replica — counted in ``requeued`` — and when every replica sheds,
    the LAST verdict comes back instead of an unbounded spin. The fleet
    books stay balanced with every dispatch accounted."""
    router, fes, clock = _fleet(2, max_queue=2)
    specs = _specs(6)
    for s in specs[:4]:
        router.submit(s)  # fills both 2-deep queues, nothing stepped yet
    assert all(router._outstanding(fe) == 2 for fe in fes.values())
    rec = router.submit(specs[4])  # shed on r0, re-dispatched, shed on r1
    assert rec.outcome == "shed"
    books = router.books()
    assert books["requeued"] == 1 and books["dispatched"] == 6, books
    assert books["outcomes"]["shed"] == 2  # one verdict per replica tried
    assert books["balanced"], books
    router.pump()
    books = router.books()
    assert books["outcomes"]["ok"] == 4 and books["balanced"], books
    assert router.audit() == []


def test_submit_with_no_dispatchable_replica_raises():
    router, fes, clock = _fleet(1)
    router.drain_replica("r0")  # idle: drains immediately
    with router._lock:
        assert router._replicas["r0"].state == "drained"
    with pytest.raises(RuntimeError, match="no dispatchable replica"):
        router.submit(_specs(1)[0])
    # and a duplicate join is refused loudly
    with pytest.raises(ValueError, match="already in the fleet"):
        router.add_replica("r0", fes["r0"])


# ------------------------------------------------------------- drain / join


def test_drain_sheds_nothing_and_routes_around(tmp_path):
    """The SIGTERM path: draining a replica stops NEW dispatch immediately
    while the drive loop finishes its outstanding work — zero sheds, the
    late arrivals all land on the survivor, and the drained replica's
    lifecycle reads join -> drain -> drained on the event stream."""
    events = EventLog(str(tmp_path), main_process=True)
    router, fes, clock = _fleet(2, events=events)
    specs = _specs(6)
    for s in specs[:4]:
        router.submit(s)
    router.step()
    assert router._outstanding(fes["r0"]) >= 1  # still owes work
    r0_submitted = fes["r0"].books()["submitted"]
    router.drain_replica("r0")
    late = [router.submit(s) for s in specs[4:]]
    router.pump()
    books = router.books()
    assert books["outcomes"]["shed"] == 0 and books["outcomes"]["ok"] == 6, books
    assert books["balanced"], books
    with router._lock:
        assert router._replicas["r0"].state == "drained"
        for s in specs[4:]:
            assert router._assigned[s.index] == "r1"
    assert fes["r0"].books()["submitted"] == r0_submitted  # no post-drain dispatch
    assert all(r.outcome == "ok" for r in late)
    transitions = [e["transition"] for e in merged_events(str(tmp_path))
                   if e.get("event") == "serve.replica"
                   and e.get("replica_id") == "r0"]
    assert transitions == ["join", "drain", "drained"]
    assert router.audit() == []


# ----------------------------------------------------------------- failover


def test_failover_replays_journal_onto_survivor(tmp_path):
    """An injected replica kill mid-drive: the dead replica's journal
    replays onto the survivor (handoff mode — the dead ledger closes with
    handoff markers, pending drops to zero), every orphan re-lands exactly
    once, the span-attributed ``serve.failover`` row carries the replay
    accounting, and a second failover of the same replica is a no-op."""
    events = EventLog(str(tmp_path), main_process=True)
    injector = FaultInjector().kill_replica_at("r0", 2)
    router, fes, clock = _fleet(2, events=events, injector=injector,
                                journal_dir=str(tmp_path))
    specs = _specs(6)
    recs = router.run_closed(specs, concurrency=6)
    assert len(recs) == 6
    # NOTE: an orphaned request's ORIGINAL record froze with the dead
    # replica — its terminal outcome lives on the survivor's replay
    # record, which is why the assertions below read the fleet books
    books = router.books()
    assert books["failovers"] == 1 and books["balanced"], books
    assert books["orphaned"] >= 1
    assert books["orphaned"] == books["readmitted"] + books["readmit_skipped"]
    assert books["outcomes"]["ok"] == 6 and books["outcomes"]["shed"] == 0
    with router._lock:
        assert router._replicas["r0"].state == "dead"
        assert router._replicas["r1"].state == "active"
        # every index the dead replica owned re-points at the survivor
        assert set(router._assigned.values()) == {"r1"}
    # the dead ledger closed by handoff: nothing pends, books balance
    dead_j = RequestJournal(os.path.join(str(tmp_path), "journal-r0.jsonl"))
    jb = dead_j.books()
    assert jb["balanced"] and jb["pending"] == 0, jb
    assert jb.get("handed_off", 0) >= 1, jb
    assert dead_j.pending() == [] and dead_j.audit() == []
    rows = [e for e in merged_events(str(tmp_path))
            if e.get("event") == "serve.failover"]
    assert len(rows) == 1
    row = rows[0]
    assert row["dead_replica"] == "r0" and row["survivor"] == "r1"
    assert row["n_replayed"] == books["readmitted"]
    assert row.get("span_id"), "failover row lost its span attribution"
    assert validate_events(str(tmp_path), strict_spans=False) == []
    # idempotence at the fleet level: the replica is already dead
    assert router.failover("r0") is None
    assert router.books()["failovers"] == 1
    assert router.audit() == []


def test_heartbeat_timeout_declares_death_and_fails_over(tmp_path):
    """A stale heartbeat on the injected clock first EXCLUDES the replica
    from dispatch, then ``check_replicas`` declares it dead (reason
    ``heartbeat_timeout``) and replays its journal onto the fresh
    survivor — the fleet finishes every accepted request."""
    events = EventLog(str(tmp_path), main_process=True)
    router, fes, clock = _fleet(
        2, events=events, journal_dir=str(tmp_path),
        config=FleetConfig(heartbeat_timeout_s=1.0),
    )
    specs = _specs(5)
    for s in specs[:4]:
        router.submit(s)  # alternates: r0 owns 2, r1 owns 2
    assert router._outstanding(fes["r0"]) == 2
    clock.advance(2.0)  # both heartbeats stale now
    router.heartbeat("r1")  # an external prober keeps r1 fresh
    rec = router.submit(specs[4])  # r0 is stale: excluded from dispatch
    with router._lock:
        assert router._assigned[specs[4].index] == "r1"
    assert router.check_replicas() == ["r0"]
    books = router.books()
    assert books["failovers"] == 1 and books["readmitted"] == 2, books
    router.pump()
    books = router.books()
    assert books["balanced"] and books["outcomes"]["ok"] == 5, books
    assert books["outcomes"]["shed"] == 0
    dead_rows = [e for e in merged_events(str(tmp_path))
                 if e.get("event") == "serve.replica"
                 and e.get("transition") == "dead"]
    assert len(dead_rows) == 1 and dead_rows[0]["replica_id"] == "r0"
    assert dead_rows[0]["reason"] == "heartbeat_timeout"
    assert rec.outcome == "ok"
    assert router.audit() == []


# ----------------------------------------------------------------- brownout


def test_brownout_degrades_then_restores(tmp_path):
    """A browned-out replica (injected latency factor) crosses the EWMA
    threshold and flips ``degraded`` — dispatch sorts it last even when it
    is the least loaded — and clearing the brownout decays the EWMA back
    under the threshold, flipping it ``restored``. Both flips land on the
    event stream naming the replica."""
    events = EventLog(str(tmp_path), main_process=True)
    injector = FaultInjector().brownout_replica("r1", 10.0)
    router, fes, clock = _fleet(
        2, events=events, injector=injector,
        config=FleetConfig(brownout_factor=3.0),
    )
    specs = _specs(40, seed=5)
    pending = list(specs)

    def top_up():
        # keep BOTH replicas busy so each drive step updates both EWMAs
        for rid, fe in fes.items():
            while pending and router._outstanding(fe) < 2:
                rec = pending.pop(0)
                fe.submit(rec)  # direct: pin EWMA behavior, not routing
                with router._lock:
                    router._dispatched += 1
                    router._assigned[int(rec.index)] = rid

    def degraded(rid):
        with router._lock:
            return router._replicas[rid].degraded

    for _ in range(200):
        top_up()
        router.step()
        if degraded("r1"):
            break
    assert degraded("r1") and not degraded("r0")
    # degraded sorts LAST: r1 idle-er than r0 still loses the pick
    while router._outstanding(fes["r1"]) > 0 and pending:
        top_up()
        router.step()
    assert router._pick().replica_id == "r0"
    injector.clear_brownout("r1")
    for _ in range(200):
        top_up()
        router.step()
        if not degraded("r1"):
            break
    assert not degraded("r1")
    router.pump()
    books = router.books()
    assert books["balanced"] and books["outcomes"]["shed"] == 0, books
    flips = [(e["replica_id"], e["transition"])
             for e in merged_events(str(tmp_path))
             if e.get("event") == "serve.replica"
             and e.get("transition") in ("degraded", "restored")]
    assert ("r1", "degraded") in flips and ("r1", "restored") in flips
    assert all(rid == "r1" for rid, _ in flips)
    assert router.audit() == []


# -------------------------------------------------------- health and books


def test_health_and_books_shapes():
    """The scrape surfaces: ``health()`` is the /healthz provider (fleet
    status over per-replica rows, each embedding the replica's own engine
    health), ``books()`` is the fleet accounting identity — both read
    clean on a fresh fleet and stay coherent across a drain."""
    router, fes, clock = _fleet(2)
    h = router.health()
    assert h["status"] == "ok"
    assert h["n_replicas"] == 2 and h["n_dispatchable"] == 2
    assert h["dispatched"] == 0 and h["failovers"] == 0
    for rid in ("r0", "r1"):
        row = h["replicas"][rid]
        assert row["state"] == "active" and row["dispatchable"]
        assert row["degraded"] is False and row["outstanding"] == 0
        assert row["heartbeat_age_s"] is not None
        assert isinstance(row["engine"], dict) and "status" in row["engine"]
    books = router.books()
    assert books["balanced"]
    assert set(books) == {
        "submitted", "terminal", "live", "orphaned", "dispatched",
        "requeued", "failovers", "readmitted", "readmit_skipped",
        "outcomes", "replicas", "balanced",
    }
    for s in _specs(2):
        router.submit(s)
    router.drain_replica("r1")
    h = router.health()
    assert h["status"] == "ok"  # r0 still dispatchable
    assert h["n_dispatchable"] == 1
    router.pump()
    h = router.health()
    assert h["replicas"]["r1"]["state"] == "drained"
    assert router.books()["balanced"] and router.audit() == []
