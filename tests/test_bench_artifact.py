"""Pin on the committed bench artifact (the latest round's
BENCH_extra_r<k>.json present) — its own module (not
test_results_artifacts.py) so its skip condition is this artifact's
presence, not flagship_convergence.json's."""

import json
import os

import pytest


def test_bench_extra_artifact_shape_and_int8_wins():
    """The committed bench artifact (latest round present) must keep its row
    set and the two int8 headline wins (decode b=8 int8 cache and decode
    b=1 int8 weights both beat the analytic baseline) — a bad regeneration
    (stalled chip, wrong flags) would otherwise ship silently."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name in ("BENCH_extra_r5.json", "BENCH_extra_r4.json"):
        path = os.path.join(root, name)
        if os.path.exists(path):
            break
    else:
        pytest.skip("no BENCH_extra artifact generated yet")
    d = json.load(open(path))
    expected = {
        "decode_b1",
        "decode_b8",
        "decode_b8_int8",
        "decode_b1_int8w",
        "decode_b8_int8_full",
        "image_b16",
    }
    assert expected <= set(d), sorted(d)
    for k in expected:
        assert d[k]["value"] > 0, k
    assert d["decode_b8_int8"]["vs_baseline"] > 1.0, d["decode_b8_int8"]
    assert d["decode_b1_int8w"]["vs_baseline"] > 1.0, d["decode_b1_int8w"]
    # decode rows self-describe their bandwidth ceilings (VERDICT r3 item 4)
    for k in expected - {"image_b16"}:
        assert "ceiling_fraction" in d[k] and "vs_baseline_cap" in d[k], k
    # ADVICE r4 asked for ceiling_fraction asserts as a clock-proof backstop,
    # but within one regeneration ceiling_fraction and vs_baseline share the
    # measured denominator (cf = vs / vs_baseline_cap), so threshold pins on
    # cf would only TIGHTEN the clock-sensitive pin above, not complement it.
    # What IS invariant is the triplet's internal consistency — a corrupt or
    # hand-edited regeneration (mismatched flags, partial rewrite) breaks it
    # while any uniform clock state preserves it:
    for k in expected - {"image_b16"}:
        cf, vs, cap = d[k]["ceiling_fraction"], d[k]["vs_baseline"], d[k]["vs_baseline_cap"]
        assert abs(cf - vs / cap) < 0.02, (k, cf, vs, cap)
    # telemetry rides along from the first regeneration after the obs/ PR;
    # when present it must be internally consistent (older artifacts skip)
    for k, row in d.items():
        t = row.get("telemetry")
        if t is None:
            continue
        assert t["device_kind"], k
        if "mfu" in t and t["mfu"] is not None:
            assert t["mfu"] == pytest.approx(
                t["model_flops_per_sec"] / t["peak_flops_per_device"], rel=0.01
            ), k


def test_bench_telemetry_fields_shape():
    """The telemetry block every bench result carries (ISSUE 1 satellite):
    MFU against the obs.mfu peak table plus the StepTimer percentile
    summary — validated on synthetic numbers so no device work runs."""
    import bench
    from perceiver_io_tpu.obs.mfu import device_peak_flops

    t = bench.telemetry_fields(1e12, 0.5, step_times_s=[0.4, 0.5, 0.6])["telemetry"]
    assert t["model_flops_per_sec"] == pytest.approx(2e12)
    peak = device_peak_flops()
    assert t["peak_flops_per_device"] == peak
    assert t["mfu"] == pytest.approx(2e12 / peak, rel=0.01)
    assert t["step_ms"]["p50"] == pytest.approx(500.0)
    assert t["step_ms"]["p50"] <= t["step_ms"]["p90"] <= t["step_ms"]["p99"]

    # decode rows: no FLOPs model (bandwidth-bound), per-token latency only
    td = bench.telemetry_fields(None, 0.01, step_times_s=[0.01], times_key="token_ms")[
        "telemetry"
    ]
    assert "mfu" not in td and "model_flops_per_sec" not in td
    assert td["token_ms"]["p99"] == pytest.approx(10.0)
    assert td["device_kind"]


def test_bench_telemetry_records_kernel_features_and_smoke_status():
    """Committed results must self-describe the A/B state that produced
    them (ISSUE 2 satellites): the active trace-time kernel feature set,
    and the kernel_smoke gate's pass/fail/skipped status once main()
    resolves it (a --skip-smoke run is visible in the artifact)."""
    import bench
    from perceiver_io_tpu.ops.flash_attention import fast_kernels

    t = bench.telemetry_fields(None, 0.01)["telemetry"]
    assert t["kernel_features"] == []
    assert "kernel_smoke" not in t  # unresolved outside main()

    with fast_kernels({"twoseg"}):
        t = bench.telemetry_fields(None, 0.01)["telemetry"]
    assert t["kernel_features"] == ["twoseg"]

    old = bench._SMOKE_STATUS
    try:
        bench._SMOKE_STATUS = "skipped"
        t = bench.telemetry_fields(None, 0.01)["telemetry"]
        assert t["kernel_smoke"] == "skipped"
    finally:
        bench._SMOKE_STATUS = old
