"""Telemetry subsystem (obs/): a CPU-backed Trainer.fit run must produce
events.jsonl + run_manifest.json with non-null MFU/throughput fields and a
compile event; the xplane per-scope rollup must reproduce the raw per-op
totals on a hand-built varint-encoded golden; MetricsLogger must survive a
resume without corrupting its CSV; StepTimer delivers the percentile
summary its docstring promises; obs_report renders it all."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.obs import (
    EventLog,
    RecompileTracker,
    clm_train_telemetry,
    config_hash,
    device_peak_flops,
)
from perceiver_io_tpu.obs.mfu import GoodputTracker
from perceiver_io_tpu.training import (
    MetricsLogger,
    TrainState,
    Trainer,
    TrainerConfig,
    clm_loss_fn,
    make_optimizer,
)


def tiny_clm():
    config = CausalLanguageModelConfig(
        vocab_size=50, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    return CausalLanguageModel(config), config


def clm_batch(config, batch=4, seed=0):
    rng = np.random.default_rng(seed)
    t = rng.integers(0, config.vocab_size, size=(batch, config.max_seq_len + 1))
    return {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }


def run_tiny_fit(tmp_path, max_steps=4, log_interval=2):
    """A short CPU-backed training run with full telemetry (the ISSUE's
    acceptance workload)."""
    model, config = tiny_clm()
    batch = clm_batch(config)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=16)
    state = TrainState.create(model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1))
    tokens_per_sample, flops_per_sample = clm_train_telemetry(config)
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(
        clm_loss_fn(model.apply, max_latents=config.max_latents),
        logger=logger,
        config=TrainerConfig(
            max_steps=max_steps,
            log_interval=log_interval,
            prefetch_batches=0,
            tokens_per_sample=tokens_per_sample,
            flops_per_sample=flops_per_sample,
        ),
    )
    state = trainer.fit(state, iter([batch] * max_steps), model_config=config)
    trainer.close()
    logger.close()
    return state


def read_events(run_dir):
    with open(os.path.join(str(run_dir), "events.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------- trainer


def test_trainer_emits_events_manifest_and_mfu(tmp_path):
    run_tiny_fit(tmp_path)
    events = read_events(tmp_path)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "fit_start"
    assert kinds[-1] == "fit_end"
    assert "compile" in kinds  # the train step's first trace+compile surfaced

    # every log row carries non-null throughput/MFU accounting
    logs = [e for e in events if e["event"] == "log"]
    assert len(logs) == 2  # steps 2 and 4 at log_interval=2
    for row in logs:
        assert row["tokens_per_sec"] > 0
        assert row["model_flops_per_sec"] > 0
        assert row["mfu"] > 0
        assert 0.0 <= row["goodput"] <= 1.0
        assert "train_loss" in row

    # the same fields land in metrics.csv (the human-facing mirror)
    import csv

    with open(os.path.join(str(tmp_path), "metrics.csv"), newline="") as f:
        rows = list(csv.DictReader(f))
    assert rows and float(rows[-1]["mfu"]) > 0
    assert float(rows[-1]["tokens_per_sec"]) > 0

    # fit_end carries the goodput breakdown and the recompile audit
    end = events[-1]
    assert end["recompiles"]["train_step"] == 1
    assert end["total_s"] > 0 and end["compile_s"] > 0
    assert 0.0 <= end["goodput"] <= 1.0

    manifest = json.load(open(os.path.join(str(tmp_path), "run_manifest.json")))
    assert manifest["jax_version"] == jax.__version__
    assert manifest["device_kind"]
    assert manifest["device_count"] >= 1
    assert manifest["mesh"] is None  # no mesh in this run
    assert len(manifest["config_hash"]) == 12
    # the hash is stable across identical configs
    _, config = tiny_clm()
    assert config_hash(config, None) == config_hash(config, None)


def test_trainer_aborted_run_still_emits_fit_end(tmp_path):
    """A run killed mid-loop must still leave the goodput/recompile audit —
    it is exactly the run that needs diagnosing."""
    model, config = tiny_clm()
    batch = clm_batch(config)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=16)
    state = TrainState.create(model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1))
    tokens_per_sample, flops_per_sample = clm_train_telemetry(config)
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(
        clm_loss_fn(model.apply, max_latents=config.max_latents),
        logger=logger,
        config=TrainerConfig(
            max_steps=10, log_interval=2, prefetch_batches=0,
            tokens_per_sample=tokens_per_sample, flops_per_sample=flops_per_sample,
        ),
    )
    def dying_loader():
        yield batch
        yield batch
        raise RuntimeError("data source died")

    with pytest.raises(RuntimeError, match="data source died"):
        trainer.fit(state, dying_loader(), model_config=config)
    trainer.close()
    logger.close()
    end = [e for e in read_events(tmp_path) if e["event"] == "fit_end"]
    assert len(end) == 1 and end[0]["aborted"] is True
    assert end[0]["recompiles"]["train_step"] == 1
    assert end[0]["compile_s"] > 0


def test_trainer_telemetry_off_without_logger(tmp_path):
    model, config = tiny_clm()
    batch = clm_batch(config)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=16)
    state = TrainState.create(model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1))
    trainer = Trainer(
        clm_loss_fn(model.apply, max_latents=config.max_latents),
        config=TrainerConfig(max_steps=1, log_interval=1, prefetch_batches=0),
    )
    trainer.fit(state, iter([batch]), model_config=config)
    trainer.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "events.jsonl"))


def test_clm_train_telemetry_matches_bench_cost_model():
    """The trainer's MFU numerator and bench.py's telemetry block must share
    ONE cost model, or the two surfaces report incomparable MFU for the
    same config on the same chip."""
    _, config = tiny_clm()
    tokens, flops = clm_train_telemetry(config)
    assert tokens == config.max_latents
    from perceiver_io_tpu.utils.flops import train_step_flops

    keep = 1.0 - config.cross_attention_dropout
    assert flops == pytest.approx(train_step_flops(config, 1, prefix_dropout_keep=keep))
    import bench

    assert bench.train_step_flops is train_step_flops  # bench re-exports, not forks
    # non-CLM configs have no analytic model: None, not a bogus number
    assert clm_train_telemetry(object()) is None


# ------------------------------------------------------------- recompiles


def test_recompile_tracker_counts_shape_driven_recompiles(tmp_path):
    events = EventLog(str(tmp_path), main_process=True)
    tracker = RecompileTracker(events=events, goodput=GoodputTracker())
    f = tracker.wrap(jax.jit(lambda x: x * 2), "f")
    f(jnp.ones((2,)))
    f(jnp.ones((2,)))  # cache hit: no event
    f(jnp.ones((3,)))  # new shape: silent recompile surfaces
    assert tracker.counts()["f"] == 2
    compiles = [e for e in read_events(tmp_path) if e["event"] == "compile"]
    assert len(compiles) == 2
    # the shape signatures differ — that's what identifies the leak
    assert compiles[0]["arg_shapes"] != compiles[1]["arg_shapes"]
    assert all(c["wall_s"] >= 0 for c in compiles)
    assert tracker.total_compile_s >= 0


# ---------------------------------------------------------- xplane golden


def _vint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _varint_field(fnum: int, n: int) -> bytes:
    return _vint(fnum << 3) + _vint(n)


def _len_field(fnum: int, payload: bytes) -> bytes:
    return _vint((fnum << 3) | 2) + _vint(len(payload)) + payload


def golden_xplane() -> (bytes, dict):
    """A hand-encoded XSpace: one device plane, one "XLA Ops" line, six ops —
    two with scope paths in their display names, one raw HLO op, one with
    the path in an XEventMetadata ``tf_op`` stat (str_value), one with an
    interned per-event stat (ref_value), one unscoped. Field numbers match
    the parser's contract (obs/xplane.py wire-format notes)."""
    ops = {
        1: ("jit(train_step)/perceiver_ar/cross_attend/fusion.1", 3000),
        2: ("jit(train_step)/perceiver_ar/cross_attend/dot.7", 1500),
        3: ("jit(train_step)/perceiver_ar/self_attend/fusion.2", 2000),
        4: ("copy.3", 500),
        5: ("fusion.9", 1000),  # scope via metadata tf_op stat
        6: ("dot.11", 250),  # scope via per-event interned ref stat
    }
    # stat_metadata: 50 = the "tf_op" stat key; 60 = an interned path string
    ref_path = "jit(train_step)/decode/sample/dot.11"
    stat_metadata = b"".join(
        _len_field(5, _varint_field(1, sid) + _len_field(2, _varint_field(1, sid) + _len_field(2, sname.encode())))
        for sid, sname in ((50, "tf_op"), (60, ref_path))
    )

    def event(mid, dur, stats=b""):
        return _len_field(4, _varint_field(1, mid) + _varint_field(3, dur) + stats)

    ref_stat = _len_field(4, _varint_field(1, 50) + _varint_field(7, 60))  # XEvent.stats
    events = b"".join(
        event(mid, dur, stats=ref_stat if mid == 6 else b"")
        for mid, (_, dur) in ops.items()
    )
    line = _len_field(2, b"XLA Ops") + events

    tf_op_stat = _len_field(
        5, _varint_field(1, 50) + _len_field(5, b"jit(train_step)/perceiver_ar/mlp/fusion.9")
    )  # XEventMetadata.stats

    def meta(mid, name):
        payload = _varint_field(1, mid) + _len_field(2, name.encode())
        if mid == 5:
            payload += tf_op_stat
        return _len_field(4, _varint_field(1, mid) + _len_field(2, payload))

    metadata = b"".join(meta(mid, name) for mid, (name, _) in ops.items())
    plane = _len_field(2, b"/device:TPU:0") + _len_field(3, line) + metadata + stat_metadata
    return _len_field(1, plane), ops


def test_xplane_golden_parse_and_scope_rollup(tmp_path):
    from perceiver_io_tpu.obs import xplane as ox

    buf, ops = golden_xplane()
    path = os.path.join(str(tmp_path), "golden.xplane.pb")
    with open(path, "wb") as f:
        f.write(buf)

    # raw per-op totals (the tools/xplane.py view)
    planes = list(ox.iter_planes(path))
    assert len(planes) == 1
    plane = planes[0]
    assert plane.name == "/device:TPU:0"
    total = sum(dur for _, dur in ops.values())
    assert plane.total_ps == total == 8250
    assert plane.per_op[ops[1][0]] == 3000
    assert plane.per_line == {"XLA Ops": total}
    # the stat-carried paths were resolved (metadata stat + interned event stat)
    assert plane.op_scopes["fusion.9"] == "jit(train_step)/perceiver_ar/mlp/fusion.9"
    assert plane.op_scopes["dot.11"] == "jit(train_step)/decode/sample/dot.11"

    # per-scope rollup: aggregates by module path, reproduces the totals
    rolls = ox.rollup(path)
    assert len(rolls) == 1
    scopes = rolls[0].scopes
    assert scopes["perceiver_ar/cross_attend"] == (4500, 2)  # fusion.1 + dot.7
    assert scopes["perceiver_ar/self_attend"] == (2000, 1)
    assert scopes["perceiver_ar/mlp"] == (1000, 1)  # via XEventMetadata tf_op stat
    assert scopes["decode/sample"] == (250, 1)  # via per-event ref stat
    assert scopes[ox.UNSCOPED] == (500, 1)
    assert rolls[0].total_ps == plane.total_ps  # acceptance: same totals

    # depth truncation merges sibling scopes
    deep = ox.rollup(path, depth=1)[0].scopes
    assert deep["perceiver_ar"] == (7500, 4)

    # the tools/xplane.py CLI entry resolves to the same numbers
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "tools_xplane", os.path.join(root, "tools", "xplane.py")
    )
    tools_xplane = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tools_xplane)
    out = []
    cli_planes = tools_xplane.summarize(path, top=10, print_fn=out.append)
    assert cli_planes[0].total_ps == rolls[0].total_ps
    assert any("XLA Ops" in line for line in out)  # the CLI rendering ran


def test_scope_of_rules():
    from perceiver_io_tpu.obs.xplane import UNSCOPED, scope_of

    assert scope_of("jit(f)/jit(main)/a/b/op") == "a/b"
    assert scope_of("transpose(jit(f))/a/op") == "a"
    assert scope_of("jit(f)/a/b/op", depth=1) == "a"
    assert scope_of("fusion.12") == UNSCOPED
    assert scope_of("jit(f)/op") == UNSCOPED


# ------------------------------------------------------- metrics resume


def test_metrics_logger_resume_keeps_single_header(tmp_path):
    d = str(tmp_path)
    l1 = MetricsLogger(d, use_tensorboard=False, main_process=True)
    l1.log(1, {"a": 1.0})
    l1.close()

    # restart: a new logger against the same metrics.csv, with a widening key
    l2 = MetricsLogger(d, use_tensorboard=False, main_process=True)
    l2.log(2, {"a": 2.0, "b": 3.0})
    l2.log(3, {"a": 4.0})
    l2.close()

    import csv

    with open(os.path.join(d, "metrics.csv"), newline="") as f:
        raw = f.read().splitlines()
    # exactly one header row, first line, widened to include b
    assert sum(1 for line in raw if line.startswith("step,")) == 1
    header = raw[0].split(",")
    assert "a" in header and "b" in header
    with open(os.path.join(d, "metrics.csv"), newline="") as f:
        rows = list(csv.DictReader(f))
    assert [int(float(r["step"])) for r in rows] == [1, 2, 3]
    assert rows[0]["b"] == ""  # pre-widening row backfilled empty
    assert float(rows[1]["b"]) == 3.0


def test_metrics_logger_resume_foreign_header_rewritten(tmp_path):
    """A metrics.csv whose header lacks the step/time contract keys must be
    rewritten on resume — appending to _keys alone would misalign rows."""
    import csv

    d = str(tmp_path)
    path = os.path.join(d, "metrics.csv")
    with open(path, "w", newline="") as f:
        f.write("loss\n0.9\n")
    logger = MetricsLogger(d, use_tensorboard=False, main_process=True)
    logger.log(1, {"loss": 0.4})
    logger.close()
    with open(path, newline="") as f:
        raw = f.read().splitlines()
    header = raw[0].split(",")
    assert header[0] == "loss" and "step" in header and "time" in header
    assert len(raw) == 3  # one header + the old row + the new row
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    assert float(rows[0]["loss"]) == 0.9 and rows[0]["step"] == ""
    assert float(rows[1]["loss"]) == 0.4 and int(float(rows[1]["step"])) == 1


# -------------------------------------------------------------- profiling


def test_steptimer_percentile_summary():
    from perceiver_io_tpu.utils.profiling import StepTimer, percentile

    timer = StepTimer(warmup=1)
    timer._times = [99.0] + [float(i) for i in range(1, 11)]  # warmup discarded
    assert timer.percentile(50) == pytest.approx(5.5)
    assert timer.percentile(0) == 1.0 and timer.percentile(100) == 10.0
    s = timer.summary()
    assert s["p50"] == pytest.approx(5.5)
    assert s["p90"] == pytest.approx(9.1)
    assert s["p99"] == pytest.approx(9.91)
    assert s["mean"] == pytest.approx(5.5)
    assert s["n"] == 10
    with pytest.raises(ValueError):
        StepTimer().percentile(50)
    with pytest.raises(ValueError):
        percentile([1.0], 150)


def test_steptimer_summary_low_n_uses_exact_order_statistics():
    """Satellite fix: under 5 samples the summary must report exact order
    statistics (nearest rank — the p99 of 3 samples IS the max) and mark
    the row low_n, instead of interpolating a fake tail."""
    from perceiver_io_tpu.utils.profiling import StepTimer, exact_percentile

    timer = StepTimer(warmup=1)
    timer._times = [99.0, 1.0, 10.0, 2.0]  # 3 retained samples
    s = timer.summary()
    assert s["low_n"] is True and s["n"] == 3
    assert s["p50"] == 2.0  # the middle observation, not an interpolation
    assert s["p90"] == 10.0 and s["p99"] == 10.0  # the max — no fake tail
    assert s["mean"] == pytest.approx(13.0 / 3)
    # ≥5 samples: interpolated percentiles, no low_n mark
    timer._times = [99.0] + [float(i) for i in range(1, 6)]
    s5 = timer.summary()
    assert "low_n" not in s5 and s5["p99"] == pytest.approx(4.96)
    assert exact_percentile([3.0, 1.0, 2.0], 0) == 1.0
    with pytest.raises(ValueError):
        exact_percentile([], 50)
    # bench telemetry blocks apply the same rule
    import bench

    t = bench.telemetry_fields(None, 1.0, [0.1, 0.2, 0.3])["telemetry"]
    assert t["step_ms"]["low_n"] is True
    assert t["step_ms"]["p99"] == pytest.approx(300.0)  # exact max, in ms
    t5 = bench.telemetry_fields(None, 1.0, [0.1] * 5)["telemetry"]
    assert "low_n" not in t5["step_ms"]


# -------------------------------------------------------------- goodput


def test_goodput_tracker_buckets():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    g = GoodputTracker(clock=clock)
    t[0] = 10.0
    with g.measure("compile"):
        t[0] = 12.0
    with g.measure("eval"):
        t[0] = 13.0
    s = g.summary()
    assert s["total_s"] == pytest.approx(13.0)
    assert s["compile_s"] == pytest.approx(2.0)
    assert s["eval_s"] == pytest.approx(1.0)
    assert s["productive_s"] == pytest.approx(10.0)
    assert s["goodput"] == pytest.approx(10.0 / 13.0, abs=1e-3)


def test_device_peak_flops_table():
    # the current (CPU) device resolves to the nominal placeholder entry
    assert device_peak_flops() == 100e9

    class Fake:
        def __init__(self, kind, platform="tpu"):
            self.device_kind = kind
            self.platform = platform

    assert device_peak_flops(Fake("TPU v5 lite")) == 197e12
    assert device_peak_flops(Fake("TPU v4")) == 275e12
    assert device_peak_flops(Fake("NVIDIA A100-SXM4-40GB", "gpu")) == 312e12
    assert device_peak_flops(Fake("warp drive", "quantum")) is None


# ------------------------------------------------------------ obs_report


def test_obs_report_renders_run_summary(tmp_path):
    run_tiny_fit(tmp_path)
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(root, "tools", "obs_report.py")
    )
    obs_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_report)
    text = obs_report.render(str(tmp_path))
    assert "== manifest ==" in text
    assert "jax_version" in text
    assert "== compiles ==" in text and "train_step" in text
    assert "mfu" in text and "tokens_per_sec" in text
    assert "== goodput (fit_end) ==" in text
    # no spurious recompile warning on a clean single-shape run
    assert "WARNING: recompiles" not in text

    # a RESUMED run appends a second legitimate first-compile (fresh process,
    # n_compiles resets to 1) — still no leak warning; a genuine same-process
    # recompile (n_compiles=2) must warn
    with open(os.path.join(str(tmp_path), "events.jsonl"), "a") as f:
        f.write(json.dumps({"ts": 0, "event": "compile", "fn": "train_step",
                            "wall_s": 1.0, "n_compiles": 1}) + "\n")
    assert "WARNING: recompiles" not in obs_report.render(str(tmp_path))
    with open(os.path.join(str(tmp_path), "events.jsonl"), "a") as f:
        f.write(json.dumps({"ts": 0, "event": "compile", "fn": "train_step",
                            "wall_s": 1.0, "n_compiles": 2}) + "\n")
    assert "WARNING: recompiles after the first on: train_step" in obs_report.render(str(tmp_path))


# ------------------------------------------------------------ generation


def test_instrumented_generation_stats_and_request_events(tmp_path):
    """Acceptance pin: one `request` event per request, carrying TTFT and
    histogram-derived TPOT p50/p99 (not means), tokens in/out, cache
    geometry and outcome; spans + compile events attributed per request."""
    from perceiver_io_tpu.generation import GenerationConfig, make_instrumented_generate_fn

    model, config = tiny_clm()
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, config.vocab_size, size=(2, 12)))
    params = model.init(jax.random.PRNGKey(0), prompt, prefix_len=8)
    events = EventLog(str(tmp_path), main_process=True)
    fn = make_instrumented_generate_fn(
        model, num_latents=4, config=GenerationConfig(max_new_tokens=6), events=events
    )
    out, stats = fn(params, prompt)
    assert out.shape == (2, 18)
    assert stats.compiled  # first call pays the compiles
    assert stats.prefill_s > 0 and stats.decode_s >= 0
    assert stats.ttft_s == stats.prefill_s
    assert stats.tokens_per_sec > 0
    assert stats.batch == 2 and stats.prompt_len == 12 and stats.new_tokens == 6
    assert stats.tokens_out == 6 and stats.outcome == "ok"

    out2, stats2 = fn(params, prompt)
    assert not stats2.compiled  # warm call: no recompile
    assert np.array_equal(np.asarray(out), np.asarray(out2))  # same rng default
    # TPOT percentiles are histogram-derived and ordered
    assert stats2.tpot_p50_s > 0
    assert stats2.tpot_p50_s <= stats2.tpot_p90_s <= stats2.tpot_p99_s

    evs = read_events(tmp_path)
    reqs = [e for e in evs if e["event"] == "request"]
    assert len(reqs) == 2  # one request event per request
    for r in reqs:
        assert r["ttft_s"] > 0
        assert r["tpot_p50_s"] > 0 and r["tpot_p99_s"] >= r["tpot_p50_s"]
        assert sum(r["tpot_hist"].values()) == 5  # 5 decode steps recorded
        assert r["outcome"] == "ok" and r["tokens_out"] == 6
        assert r["ca_capacity"] == 18 and r["sa_capacity"] == 10
        assert r["schema_version"] == 1
    # the cross-request registry records WARM samples only (a dashboard
    # histogram never resets, so one compile sample would poison its tail
    # forever): request 1's compiling prefill + first decode step are out
    assert fn.registry.counter("generate_cold_requests_total").value == 1
    assert fn.registry.histogram("generate_ttft_s").n == 1
    assert fn.registry.histogram("generate_tpot_s").n == 9  # 4 warm + 5 warm
    # both compiled programs surfaced as compile events on the first call,
    # attributed to the request span that paid them
    compiles = [e for e in evs if e["event"] == "compile"]
    assert {e["fn"] for e in compiles} == {"generate_prefill", "generate_decode_step"}
    span_ids = {e["span_id"] for e in evs if e["event"] == "span"}
    assert reqs[0]["span_id"] in span_ids
    assert all(c["span_id"] == reqs[0]["span_id"] for c in compiles)
    # the stream validates (schema_version + required fields + span refs)
    from perceiver_io_tpu.obs.events import validate_events

    assert validate_events(str(tmp_path)) == []


def test_streamed_decode_matches_compiled_scan():
    """make_decode_fns' host-driven loop must be token-exact equal to
    generate()'s compiled scan — same body, same rng chain — including
    sampling and EOS freezing."""
    from perceiver_io_tpu.generation import GenerationConfig, generate, make_decode_fns

    model, config = tiny_clm()
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, config.vocab_size, size=(2, 12)))
    params = model.init(jax.random.PRNGKey(0), prompt, prefix_len=8)
    for gc in (
        GenerationConfig(max_new_tokens=6, do_sample=True, temperature=0.8, top_k=10),
        GenerationConfig(max_new_tokens=5, eos_token_id=3),
    ):
        ref = generate(model, params, prompt, num_latents=4, config=gc, rng=jax.random.PRNGKey(7))
        prefill_fn, step_fn = make_decode_fns(model, num_latents=4, config=gc)
        token, state = prefill_fn(params, prompt, None, jax.random.PRNGKey(7))
        toks = [token]
        for _ in range(1, gc.max_new_tokens):
            state, token = step_fn(state)
            toks.append(token)
        streamed = jnp.concatenate([prompt] + [t[:, None] for t in toks], axis=1)
        assert np.array_equal(np.asarray(ref), np.asarray(streamed))


def test_instrumented_generation_abort_emits_error_request(tmp_path):
    """A request that dies mid-decode must still emit its `request` event
    with outcome="error" and the partial TPOT data, then re-raise (the
    fit_end except-and-reraise guarantee, request-level)."""
    from perceiver_io_tpu.generation import GenerationConfig, make_instrumented_generate_fn

    model, config = tiny_clm()
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, config.vocab_size, size=(2, 12)))
    params = model.init(jax.random.PRNGKey(0), prompt, prefix_len=8)
    events = EventLog(str(tmp_path), main_process=True)

    def die_at_3(i, token):
        if i == 3:
            raise RuntimeError("consumer died mid-decode")

    fn = make_instrumented_generate_fn(
        model, num_latents=4, config=GenerationConfig(max_new_tokens=8),
        events=events, on_token=die_at_3,
    )
    with pytest.raises(RuntimeError, match="consumer died mid-decode"):
        fn(params, prompt)
    reqs = [e for e in read_events(tmp_path) if e["event"] == "request"]
    assert len(reqs) == 1
    r = reqs[0]
    assert r["outcome"] == "error"
    assert "consumer died mid-decode" in r["error"]
    assert r["tokens_out"] == 4  # tokens 0..3 were produced before the abort
    assert sum(r["tpot_hist"].values()) == 3  # partial TPOT samples survive
    assert r["ttft_s"] > 0
    # the error outcome rides the span and the registry error counter
    spans = [e for e in read_events(tmp_path) if e["event"] == "span"]
    assert any(s["attrs"].get("outcome") == "error" for s in spans)
    assert fn.registry.counter("generate_request_errors_total").value == 1


# ------------------------------------------------------------------ spans


def test_tracer_span_nesting_ids_and_ambient(tmp_path):
    from perceiver_io_tpu.obs.trace import Tracer, current_span_id

    events = EventLog(str(tmp_path), main_process=True)
    tracer = Tracer(events)
    assert current_span_id() is None
    with tracer.span("outer", kind="test") as outer:
        assert current_span_id() == outer.span_id
        with tracer.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert current_span_id() == inner.span_id
            inner.set("k", 7)
        assert current_span_id() == outer.span_id
    assert current_span_id() is None
    tracer.flush()
    rows = [e for e in read_events(tmp_path) if e["event"] == "span"]
    by_name = {r["name"]: r for r in rows}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["inner"]["attrs"] == {"k": 7}
    assert by_name["outer"]["attrs"] == {"kind": "test"}
    assert by_name["outer"]["parent_id"] is None
    for r in rows:
        assert r["dur_ms"] >= 0 and r["t_end"] >= r["t_start"]
        assert r["process_index"] == 0

    # ambient fallback: a FOREIGN thread's emit attaches to the ambient span
    import threading

    seen = {}
    with tracer.span("fit", ambient=True) as fit:
        t = threading.Thread(target=lambda: seen.update(sid=current_span_id()))
        t.start()
        t.join()
    assert seen["sid"] == fit.span_id

    # decorator form
    @tracer.traced("worker")
    def work():
        return current_span_id()

    sid = work()
    tracer.flush()
    names = [e["name"] for e in read_events(tmp_path) if e["event"] == "span"]
    assert "worker" in names and sid is not None


def test_event_rows_carry_schema_version_and_current_span(tmp_path):
    from perceiver_io_tpu.obs.events import EVENT_SCHEMA_VERSION
    from perceiver_io_tpu.obs.trace import Tracer

    events = EventLog(str(tmp_path), main_process=True)
    tracer = Tracer(events)
    events.emit("custom", a=1)
    with tracer.span("step") as sp:
        events.emit("fault.skip", step=3, reason="nonfinite", skips=1)
    tracer.flush()
    rows = read_events(tmp_path)
    assert all(r["schema_version"] == EVENT_SCHEMA_VERSION for r in rows)
    assert "span_id" not in rows[0]  # no open span at emit time
    fault = [r for r in rows if r["event"] == "fault.skip"][0]
    assert fault["span_id"] == sp.span_id  # stamped by the open span


def test_trainer_emits_step_spans_with_phases(tmp_path):
    run_tiny_fit(tmp_path)
    events = read_events(tmp_path)
    spans = [e for e in events if e["event"] == "span"]
    steps = [s for s in spans if s["name"] == "step"]
    fits = [s for s in spans if s["name"] == "fit"]
    assert len(fits) == 1 and len(steps) == 4  # one span per step
    for s in steps:
        assert s["parent_id"] == fits[0]["span_id"]
        assert "input_wait_ms" in s["attrs"] and "dispatch_ms" in s["attrs"]
        assert "step" in s["attrs"]
    assert [s["attrs"]["step"] for s in steps] == [1, 2, 3, 4]
    # fit_start and log rows are attributed (fit / step span respectively)
    by_event = {e["event"]: e for e in events}
    assert by_event["fit_start"]["span_id"] == fits[0]["span_id"]
    assert by_event["log"]["span_id"] in {s["span_id"] for s in steps}
    # the whole stream validates, span references included
    from perceiver_io_tpu.obs.events import validate_events

    assert validate_events(str(tmp_path)) == []


def test_host_device_breakdown_joins_spans_and_rollups(tmp_path):
    """The correlation hook: step spans (host) + golden-xplane named-scope
    rollups (device) produce the per-step breakdown obs_report renders."""
    from perceiver_io_tpu.obs import xplane as ox
    from perceiver_io_tpu.obs.trace import host_device_breakdown

    buf, _ops = golden_xplane()
    path = os.path.join(str(tmp_path), "golden.xplane.pb")
    with open(path, "wb") as f:
        f.write(buf)
    rollups = ox.rollup(path)
    span_rows = [
        {"event": "span", "name": "step", "dur_ms": float(d),
         "attrs": {"input_wait_ms": 0.5, "dispatch_ms": 2.0}}
        for d in (10.0, 12.0, 11.0, 50.0, 13.0)
    ] + [{"event": "span", "name": "checkpoint", "dur_ms": 30.0, "attrs": {}}]
    bd = host_device_breakdown(span_rows, rollups)
    assert bd["steps"] == 5
    assert bd["step_ms"]["p50"] == 12.0 and "low_n" not in bd["step_ms"]
    assert bd["input_wait_ms"] == pytest.approx(0.5)
    assert bd["dispatch_ms"] == pytest.approx(2.0)
    assert bd["checkpoint"] == {"count": 1, "total_ms": 30.0}
    # device totals: golden plane is 8250 ps, 5 steps
    assert bd["device"]["total_ms"] == pytest.approx(8250 / 1e9, abs=1e-9)
    assert bd["device"]["per_step_ms"] == pytest.approx(8250 / 5 / 1e9, abs=1e-9)
    scopes = {s["scope"] for s in bd["device"]["top_scopes"]}
    assert "perceiver_ar/cross_attend" in scopes


def test_fault_and_resume_events_carry_resolvable_span_ids(tmp_path):
    """Acceptance pin (chaos-scenario span attribution): every fault.* and
    resume event of a preempt + sentinel-rollback + auto-resume run carries
    a span_id whose span row is present in the same stream."""
    from perceiver_io_tpu.training import (
        MetricsLogger,
        SentinelConfig,
        TrainState,
        Trainer,
        TrainerConfig,
        make_optimizer,
    )

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"]
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, {"loss": loss}

    def fresh_state():
        return TrainState.create(
            None, {"w": jnp.zeros((3,))}, make_optimizer(1e-2), jax.random.PRNGKey(0)
        )

    def batches(poison_at=()):
        rng = np.random.default_rng(0)
        import itertools

        for i in itertools.count(1):
            x = rng.normal(size=(4, 3)).astype(np.float32)
            y = (x @ np.ones(3)).astype(np.float32)
            if i in poison_at:
                x = x.copy()
                x[0, 0] = np.nan
            yield {"x": x, "y": y}

    cfg = dict(
        log_interval=1, checkpoint_dir=str(tmp_path / "ckpt"), prefetch_batches=0,
        input_double_buffer=False, graphlint=False, graphcheck=False,
    )
    logger = MetricsLogger(str(tmp_path / "logs"), use_tensorboard=False)
    # phase 1: checkpoint at step 3 (val), sentinel skips at the poison
    # steps 5-6 then rolls back to it, programmatic preemption at step 7
    tr = Trainer(
        loss_fn,
        config=TrainerConfig(
            max_steps=9, val_interval=3,
            sentinel=SentinelConfig(skip_limit=2, rollback_limit=2), **cfg
        ),
        logger=logger,
    )
    orig = tr._train_step

    def tripping(state, batch, _orig=orig):
        out = _orig(state, batch)
        if int(out[0].step) == 7:
            tr._preempt_guard.trip()
        return out

    tr._train_step = tripping
    val_batch = next(batches())
    tr.fit(
        fresh_state(), batches(poison_at=(5, 6)), val_loader=[val_batch], model_config=None
    )
    tr.close()
    # phase 2: auto-resume appends a resume event to the same stream
    tr2 = Trainer(loss_fn, config=TrainerConfig(max_steps=8, **cfg), logger=logger)
    tr2.fit(fresh_state(), batches(), resume="auto")
    tr2.close()
    logger.close()

    events = []
    with open(tmp_path / "logs" / "events.jsonl") as f:
        events = [json.loads(line) for line in f if line.strip()]
    span_ids = {e["span_id"] for e in events if e["event"] == "span"}
    audited = [
        e for e in events if e["event"].startswith("fault.") or e["event"] == "resume"
    ]
    kinds = {e["event"] for e in audited}
    assert "fault.skip" in kinds and "fault.rollback" in kinds
    assert "fault.preempt" in kinds and "resume" in kinds
    for e in audited:
        assert e.get("span_id") in span_ids, f"{e['event']} not span-attributed: {e}"
    from perceiver_io_tpu.obs.events import validate_events

    assert validate_events(str(tmp_path / "logs")) == []


# --------------------------------------------------- events: shards, schema


def test_eventlog_shards_per_process_and_merge(tmp_path):
    from perceiver_io_tpu.obs.events import EventLog, merged_events

    d = str(tmp_path)
    # synthetic two-process program: each process writes its own shard
    e0 = EventLog(d, process_index=0, process_count=2)
    e1 = EventLog(d, process_index=1, process_count=2)
    assert os.path.basename(e0.path) == "events-p0.jsonl"
    assert os.path.basename(e1.path) == "events-p1.jsonl"
    assert e1._active  # non-zero processes WRITE in sharded mode
    e0.emit("a", seq=0)
    e1.emit("b", seq=0)
    e0.emit("c", seq=1)
    merged = merged_events(d)
    assert [e["event"] for e in merged] in (["a", "b", "c"], ["b", "a", "c"])

    # clock-skew tolerance: a shard whose wall clock stepped BACKWARDS keeps
    # its own file order (per-process history is authoritative)
    import json as _json

    with open(os.path.join(d, "events-p1.jsonl"), "a") as f:
        f.write(_json.dumps({"ts": 1.0, "event": "late", "schema_version": 1}) + "\n")
    merged = merged_events(d)
    names = [e["event"] for e in merged]
    assert names.index("late") > names.index("b")  # never reordered before b


def test_validate_events_catches_drift(tmp_path):
    from perceiver_io_tpu.obs.events import EventLog, validate_events

    d = str(tmp_path)
    events = EventLog(d, main_process=True)
    events.emit("fit_start", start_step=0, max_steps=2)
    events.emit("fit_end", step=2, aborted=False)
    assert validate_events(d) == []

    # a torn TAIL line is tolerated (killed runs are expected)...
    with open(events.path) as f:
        clean = f.read()
    with open(events.path, "a") as f:
        f.write('{"ts": 1, "event": "log", "step"')
    assert validate_events(d) == []
    # ...but planted drift is not: missing schema_version, missing required
    # field, unresolvable span reference
    with open(events.path, "w") as f:
        f.write(clean)
    with open(events.path, "a") as f:
        f.write(json.dumps({"ts": 1.0, "event": "log", "step": 1}) + "\n")  # no version
        f.write(json.dumps({"ts": 1.0, "event": "compile", "schema_version": 1}) + "\n")
        f.write(
            json.dumps(
                {"ts": 1.0, "event": "fault.skip", "schema_version": 1, "span_id": "dead"}
            )
            + "\n"
        )
    problems = validate_events(d)
    assert any("schema_version" in p for p in problems)
    assert any("compile" in p and "fn" in p for p in problems)
    assert any("dead" in p for p in problems)


# ------------------------------------------------------------------ metrics


def test_metrics_registry_counters_gauges_histograms():
    from perceiver_io_tpu.obs.metrics import MetricsRegistry, bucket_index

    reg = MetricsRegistry()
    c = reg.counter("requests_total", help="total requests")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("queue_depth")
    g.set(5)
    g.add(-2)
    assert g.value == 3
    h = reg.histogram("latency_s")
    for v in (0.001, 0.002, 0.002, 0.004, 0.1, 0.1, 0.1, 0.1, 0.1, 0.1):
        h.record(v)
    assert h.n == 10 and h.min == 0.001 and h.max == 0.1
    # bucket-derived percentiles: within one bucket width of the truth
    assert h.percentile(50) == pytest.approx(0.1, rel=0.25)
    assert h.percentile(99) == pytest.approx(0.1, rel=0.25)
    assert h.percentile(10) == pytest.approx(0.001, rel=0.25)  # nearest rank: 1st of 10
    # same name returns the same metric; wrong type raises
    assert reg.counter("requests_total") is c
    with pytest.raises(TypeError):
        reg.gauge("requests_total")
    # snapshot carries everything, histogram percentiles included
    snap = reg.snapshot()
    assert snap["counters"]["requests_total"] == 3
    assert snap["gauges"]["queue_depth"] == 3
    assert snap["histograms"]["latency_s"]["n"] == 10
    assert "p99" in snap["histograms"]["latency_s"]
    assert "low_n" not in snap["histograms"]["latency_s"]
    # low-sample histograms say so
    h2 = reg.histogram("rare_s")
    h2.record(1.0)
    assert reg.snapshot()["histograms"]["rare_s"]["low_n"] is True
    # one-sample percentile clamps to the observation, not the bucket mid
    assert h2.percentile(99) == 1.0
    assert bucket_index(0.0) == bucket_index(-1.0)  # clamped, no crash


def test_metrics_prometheus_and_event_snapshot(tmp_path):
    from perceiver_io_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("gen_requests", help="requests served").inc(4)
    reg.gauge("inflight").set(2)
    h = reg.histogram("ttft_seconds")
    h.record(0.5)
    h.record(1.5)
    text = reg.to_prometheus()
    assert "# TYPE gen_requests counter" in text
    assert "gen_requests 4" in text
    assert "# TYPE inflight gauge" in text
    assert "# TYPE ttft_seconds histogram" in text
    assert 'ttft_seconds_bucket{le="+Inf"} 2' in text
    assert "ttft_seconds_count 2" in text
    # cumulative bucket counts are monotone
    import re

    cums = [int(m) for m in re.findall(r'ttft_seconds_bucket\{le="[^+]*"\} (\d+)', text)]
    assert cums == sorted(cums)

    events = EventLog(str(tmp_path), main_process=True)
    reg.emit_snapshot(events)
    assert not reg.maybe_emit(events, min_interval_s=60)  # rate-limited
    rows = [e for e in read_events(tmp_path) if e["event"] == "metrics"]
    assert len(rows) == 1
    assert rows[0]["counters"]["gen_requests"] == 4
    assert rows[0]["histograms"]["ttft_seconds"]["n"] == 2


def test_histogram_counts_merge_exactly():
    """The property SLO aggregation rests on: merging two histograms' sparse
    counts equals recording every sample into one histogram."""
    from perceiver_io_tpu.obs.metrics import (
        Histogram,
        merge_counts,
        percentile_from_counts,
    )

    a, b, both = Histogram("a"), Histogram("b"), Histogram("both")
    rng = np.random.default_rng(3)
    for _ in range(200):
        v = float(rng.lognormal(-5, 1))
        (a if rng.random() < 0.5 else b).record(v)
        both.record(v)
    merged = merge_counts(a.counts, {str(k): v for k, v in b.counts.items()})
    assert merged == both.counts
    for p in (50, 90, 99):
        assert percentile_from_counts(merged, p) == pytest.approx(
            percentile_from_counts(both.counts, p)
        )


def test_histogram_empty_percentile_and_to_dict():
    """ISSUE 9 satellite: an empty histogram reports None percentiles (not
    a crash, not a fake 0) and a stat-free to_dict."""
    from perceiver_io_tpu.obs.metrics import Histogram, percentile_from_counts

    h = Histogram("empty_s")
    for p in (0, 50, 99, 100):
        assert h.percentile(p) is None
    assert percentile_from_counts({}, 50) is None
    d = h.to_dict()
    assert d["n"] == 0 and d["min"] is None and d["max"] is None
    assert "p50" not in d and "p99" not in d and "low_n" not in d
    with pytest.raises(ValueError):
        h.percentile(101)


def test_histogram_merge_exactly_associative_across_three_shards():
    """ISSUE 9 satellite: merging >= 3 shards' sparse counts is EXACTLY
    associative and commutative — any merge tree gives the same counts and
    the same percentiles (the property multi-process SLO aggregation and
    the obs_report fallback both lean on)."""
    from perceiver_io_tpu.obs.metrics import Histogram, merge_counts, percentile_from_counts

    rng = np.random.default_rng(7)
    shards = [Histogram(f"s{i}") for i in range(4)]
    ref = Histogram("ref")
    for _ in range(500):
        v = float(rng.lognormal(-6, 2))
        shards[int(rng.integers(0, 4))].record(v)
        ref.record(v)
    counts = [s.counts for s in shards]
    left = merge_counts(merge_counts(merge_counts(counts[0], counts[1]), counts[2]), counts[3])
    right = merge_counts(counts[0], merge_counts(counts[1], merge_counts(counts[2], counts[3])))
    flat = merge_counts(*counts)
    rev = merge_counts(*reversed(counts))
    assert left == right == flat == rev == ref.counts
    for p in (50, 90, 99):
        assert percentile_from_counts(flat, p) == percentile_from_counts(ref.counts, p)


def test_histogram_to_prometheus_bucket_monotonicity():
    """ISSUE 9 satellite: the exposition's cumulative buckets must be
    non-decreasing with strictly increasing le bounds, +Inf == count — on a
    histogram with GAPS between occupied buckets (the sparse-counts case a
    naive cumulative walk gets wrong)."""
    import re

    from perceiver_io_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("gappy_s")
    for v in (1e-6, 1e-6, 1e-3, 5.0, 5.0, 5.0):  # three distant clusters
        h.record(v)
    text = reg.to_prometheus()
    pairs = re.findall(r'gappy_s_bucket\{le="([^"}]+)"\} (\d+)', text)
    les = [le for le, _ in pairs]
    cums = [int(c) for _, c in pairs]
    assert les[-1] == "+Inf" and cums[-1] == h.n == 6
    finite_les = [float(le) for le in les[:-1]]
    assert finite_les == sorted(finite_les) and len(set(finite_les)) == len(finite_les)
    assert cums == sorted(cums)  # non-decreasing cumulative counts
    assert "gappy_s_count 6" in text


def test_prometheus_exposition_golden():
    """ISSUE 11 satellite: the exposition FORMAT is the contract a real
    Prometheus scraper parses — pin it byte-for-byte. Per histogram: the
    cumulative sparse buckets, the ``+Inf`` bucket equal to ``_count``, and
    the ``_sum``/``_count`` series ``histogram_quantile``/``rate`` need;
    metrics name-sorted; HELP only where help text exists; names
    sanitized."""
    from perceiver_io_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("reqs").inc(3)
    reg.gauge("depth").set(2)
    h = reg.histogram("lat_s", help="request latency")
    h.record(1.0)  # bucket 0: le = 2**0.25
    h.record(2.0)  # bucket 4: le = 2**1.25
    assert reg.to_prometheus() == (
        "# TYPE depth gauge\n"
        "depth 2\n"
        "# HELP lat_s request latency\n"
        "# TYPE lat_s histogram\n"
        'lat_s_bucket{le="1.18921"} 1\n'
        'lat_s_bucket{le="2.37841"} 2\n'
        'lat_s_bucket{le="+Inf"} 2\n'
        "lat_s_sum 3\n"
        "lat_s_count 2\n"
        "# TYPE reqs counter\n"
        "reqs 3\n"
    )
    # dotted names sanitize to the Prometheus charset; empty registry is ""
    reg2 = MetricsRegistry()
    reg2.counter("a.b/c").inc()
    assert "a_b_c 1" in reg2.to_prometheus()
    assert MetricsRegistry().to_prometheus() == ""
    # an empty histogram still exposes a complete (+Inf/_sum/_count) family
    reg3 = MetricsRegistry()
    reg3.histogram("never_s")
    assert reg3.to_prometheus() == (
        "# TYPE never_s histogram\n"
        'never_s_bucket{le="+Inf"} 0\n'
        "never_s_sum 0\n"
        "never_s_count 0\n"
    )


def test_validate_events_unknown_kinds_warn_forward_compatibly(tmp_path):
    """ISSUE 9 satellite: kinds outside KNOWN_EVENT_KINDS are NEVER
    problems (older tooling survives newer streams) but are collected into
    warnings_out; probe/probe.blast rows get required-field checks."""
    from perceiver_io_tpu.obs.events import KNOWN_EVENT_KINDS, EventLog, validate_events

    d = str(tmp_path)
    events = EventLog(d, main_process=True)
    events.emit("fit_start", start_step=0, max_steps=1)
    events.emit("probe", step=1, scopes={"000:embed": {"rms": 1.0}})
    events.emit(
        "probe.blast", trigger="skip", scope="embed", step=1,
        affected=["embed"], n_affected=1,
    )
    events.emit("shiny.future_kind", payload=123)
    events.emit("shiny.future_kind", payload=456)  # second occurrence: one warning
    warnings_out = []
    problems = validate_events(d, warnings_out=warnings_out)
    assert problems == [], problems  # unknown kind is NOT a failure
    assert len(warnings_out) == 1 and "shiny.future_kind" in warnings_out[0]
    assert validate_events(d) == []  # no warnings_out: same verdict, no crash
    assert "probe" in KNOWN_EVENT_KINDS and "probe.blast" in KNOWN_EVENT_KINDS
    assert "fault.rollback" in KNOWN_EVENT_KINDS

    # planted drift in the probe kinds IS a failure
    events.emit("probe", scopes={})  # missing step
    events.emit("probe.blast", trigger="skip")  # missing scope/step/affected
    problems = validate_events(d)
    assert any("[probe]" in p and "step" in p for p in problems)
    assert any("[probe.blast]" in p and "scope" in p for p in problems)


def test_prometheus_exposition_golden_labeled():
    """ISSUE 16 satellite: labeled children (Simline per-tenant series)
    render INSIDE the parent's family — one # TYPE line, the unlabeled
    series first (the all-label total), then each child with its
    key-sorted, value-escaped label set — pinned byte-for-byte. The
    unlabeled golden above passing unchanged is the other half of the
    contract: a label-free registry's exposition is byte-identical to the
    pre-label format."""
    from perceiver_io_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("serve_reqs")
    c.inc(2)                            # the all-tenant total
    c.labels(tenant="acme").inc(1)
    c.labels(tenant='b"corp').inc(1)    # quote must escape in the value
    reg.gauge("depth").labels(tenant="acme").set(4)
    h = reg.histogram("lat_s")
    h.record(1.0)                       # bucket le = 2**0.25
    h.labels(tenant="acme").record(2.0)  # bucket le = 2**1.25
    assert reg.to_prometheus() == (
        "# TYPE depth gauge\n"
        "depth 0\n"
        'depth{tenant="acme"} 4\n'
        "# TYPE lat_s histogram\n"
        'lat_s_bucket{le="1.18921"} 1\n'
        'lat_s_bucket{le="+Inf"} 1\n'
        "lat_s_sum 1\n"
        "lat_s_count 1\n"
        'lat_s_bucket{tenant="acme",le="2.37841"} 1\n'
        'lat_s_bucket{tenant="acme",le="+Inf"} 1\n'
        'lat_s_sum{tenant="acme"} 2\n'
        'lat_s_count{tenant="acme"} 1\n'
        "# TYPE serve_reqs counter\n"
        "serve_reqs 2\n"
        'serve_reqs{tenant="acme"} 1\n'
        'serve_reqs{tenant="b\\"corp"} 1\n'
    )


def test_labeled_metrics_children_semantics_and_snapshot(tmp_path):
    """ISSUE 16 satellite: labels() is get-or-create on the sorted label
    set, children record independently of the parent, nesting is refused,
    and the metrics-event snapshot carries labeled series (plus gauge
    high-water marks in gauge_peaks) under rendered series names."""
    from perceiver_io_tpu.obs.events import EventLog, validate_events
    from perceiver_io_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("reqs")
    assert c.labels(tenant="a") is c.labels(tenant="a")  # get-or-create
    assert c.labels(tenant="a") is not c.labels(tenant="b")
    c.labels(tenant="a").inc(3)
    assert c.value == 0  # children never write the parent implicitly
    with pytest.raises(ValueError):
        c.labels(tenant="a").labels(zone="z")  # one level only
    with pytest.raises(ValueError):
        c.labels()
    g = reg.gauge("pages")
    g.labels(tenant="a").set(7)
    g.labels(tenant="a").set(2)
    assert g.labels(tenant="a").peak == 7  # high-water mark survives the drop
    snap = reg.snapshot()
    assert snap["counters"]['reqs{tenant="a"}'] == 3
    assert snap["gauges"]['pages{tenant="a"}'] == 2
    assert snap["gauge_peaks"]['pages{tenant="a"}'] == 7
    assert "pages" not in snap["gauge_peaks"]  # parent never written: no peak
    # the snapshot still validates as a metrics event row
    events = EventLog(str(tmp_path), main_process=True)
    reg.emit_snapshot(events)
    warnings_out = []
    assert validate_events(str(tmp_path), warnings_out=warnings_out) == []
    assert warnings_out == []


def test_metrics_registry_rate_limits_on_injected_clock(tmp_path):
    """Hostlint fix pin (clock-discipline): maybe_emit's rate limit runs on
    the injected clock, so a virtual-time (ManualClock) run emits snapshots
    on the virtual timeline instead of silently reading the wall."""
    from perceiver_io_tpu.obs.metrics import MetricsRegistry

    t = [100.0]
    reg = MetricsRegistry(clock=lambda: t[0])
    reg.counter("n").inc()
    events = EventLog(str(tmp_path), main_process=True)
    assert reg.maybe_emit(events, min_interval_s=30)
    assert not reg.maybe_emit(events, min_interval_s=30)  # inside the window
    t[0] += 29.0
    assert not reg.maybe_emit(events, min_interval_s=30)  # still inside
    t[0] += 1.5
    assert reg.maybe_emit(events, min_interval_s=30)  # virtual window passed
    rows = [e for e in read_events(tmp_path) if e["event"] == "metrics"]
    assert len(rows) == 2
