"""Telemetry subsystem (obs/): a CPU-backed Trainer.fit run must produce
events.jsonl + run_manifest.json with non-null MFU/throughput fields and a
compile event; the xplane per-scope rollup must reproduce the raw per-op
totals on a hand-built varint-encoded golden; MetricsLogger must survive a
resume without corrupting its CSV; StepTimer delivers the percentile
summary its docstring promises; obs_report renders it all."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.obs import (
    EventLog,
    RecompileTracker,
    clm_train_telemetry,
    config_hash,
    device_peak_flops,
)
from perceiver_io_tpu.obs.mfu import GoodputTracker
from perceiver_io_tpu.training import (
    MetricsLogger,
    TrainState,
    Trainer,
    TrainerConfig,
    clm_loss_fn,
    make_optimizer,
)


def tiny_clm():
    config = CausalLanguageModelConfig(
        vocab_size=50, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    return CausalLanguageModel(config), config


def clm_batch(config, batch=4, seed=0):
    rng = np.random.default_rng(seed)
    t = rng.integers(0, config.vocab_size, size=(batch, config.max_seq_len + 1))
    return {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }


def run_tiny_fit(tmp_path, max_steps=4, log_interval=2):
    """A short CPU-backed training run with full telemetry (the ISSUE's
    acceptance workload)."""
    model, config = tiny_clm()
    batch = clm_batch(config)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=16)
    state = TrainState.create(model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1))
    tokens_per_sample, flops_per_sample = clm_train_telemetry(config)
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(
        clm_loss_fn(model.apply, max_latents=config.max_latents),
        logger=logger,
        config=TrainerConfig(
            max_steps=max_steps,
            log_interval=log_interval,
            prefetch_batches=0,
            tokens_per_sample=tokens_per_sample,
            flops_per_sample=flops_per_sample,
        ),
    )
    state = trainer.fit(state, iter([batch] * max_steps), model_config=config)
    trainer.close()
    logger.close()
    return state


def read_events(run_dir):
    with open(os.path.join(str(run_dir), "events.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------- trainer


def test_trainer_emits_events_manifest_and_mfu(tmp_path):
    run_tiny_fit(tmp_path)
    events = read_events(tmp_path)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "fit_start"
    assert kinds[-1] == "fit_end"
    assert "compile" in kinds  # the train step's first trace+compile surfaced

    # every log row carries non-null throughput/MFU accounting
    logs = [e for e in events if e["event"] == "log"]
    assert len(logs) == 2  # steps 2 and 4 at log_interval=2
    for row in logs:
        assert row["tokens_per_sec"] > 0
        assert row["model_flops_per_sec"] > 0
        assert row["mfu"] > 0
        assert 0.0 <= row["goodput"] <= 1.0
        assert "train_loss" in row

    # the same fields land in metrics.csv (the human-facing mirror)
    import csv

    with open(os.path.join(str(tmp_path), "metrics.csv"), newline="") as f:
        rows = list(csv.DictReader(f))
    assert rows and float(rows[-1]["mfu"]) > 0
    assert float(rows[-1]["tokens_per_sec"]) > 0

    # fit_end carries the goodput breakdown and the recompile audit
    end = events[-1]
    assert end["recompiles"]["train_step"] == 1
    assert end["total_s"] > 0 and end["compile_s"] > 0
    assert 0.0 <= end["goodput"] <= 1.0

    manifest = json.load(open(os.path.join(str(tmp_path), "run_manifest.json")))
    assert manifest["jax_version"] == jax.__version__
    assert manifest["device_kind"]
    assert manifest["device_count"] >= 1
    assert manifest["mesh"] is None  # no mesh in this run
    assert len(manifest["config_hash"]) == 12
    # the hash is stable across identical configs
    _, config = tiny_clm()
    assert config_hash(config, None) == config_hash(config, None)


def test_trainer_aborted_run_still_emits_fit_end(tmp_path):
    """A run killed mid-loop must still leave the goodput/recompile audit —
    it is exactly the run that needs diagnosing."""
    model, config = tiny_clm()
    batch = clm_batch(config)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=16)
    state = TrainState.create(model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1))
    tokens_per_sample, flops_per_sample = clm_train_telemetry(config)
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(
        clm_loss_fn(model.apply, max_latents=config.max_latents),
        logger=logger,
        config=TrainerConfig(
            max_steps=10, log_interval=2, prefetch_batches=0,
            tokens_per_sample=tokens_per_sample, flops_per_sample=flops_per_sample,
        ),
    )
    def dying_loader():
        yield batch
        yield batch
        raise RuntimeError("data source died")

    with pytest.raises(RuntimeError, match="data source died"):
        trainer.fit(state, dying_loader(), model_config=config)
    trainer.close()
    logger.close()
    end = [e for e in read_events(tmp_path) if e["event"] == "fit_end"]
    assert len(end) == 1 and end[0]["aborted"] is True
    assert end[0]["recompiles"]["train_step"] == 1
    assert end[0]["compile_s"] > 0


def test_trainer_telemetry_off_without_logger(tmp_path):
    model, config = tiny_clm()
    batch = clm_batch(config)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=16)
    state = TrainState.create(model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1))
    trainer = Trainer(
        clm_loss_fn(model.apply, max_latents=config.max_latents),
        config=TrainerConfig(max_steps=1, log_interval=1, prefetch_batches=0),
    )
    trainer.fit(state, iter([batch]), model_config=config)
    trainer.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "events.jsonl"))


def test_clm_train_telemetry_matches_bench_cost_model():
    """The trainer's MFU numerator and bench.py's telemetry block must share
    ONE cost model, or the two surfaces report incomparable MFU for the
    same config on the same chip."""
    _, config = tiny_clm()
    tokens, flops = clm_train_telemetry(config)
    assert tokens == config.max_latents
    from perceiver_io_tpu.utils.flops import train_step_flops

    keep = 1.0 - config.cross_attention_dropout
    assert flops == pytest.approx(train_step_flops(config, 1, prefix_dropout_keep=keep))
    import bench

    assert bench.train_step_flops is train_step_flops  # bench re-exports, not forks
    # non-CLM configs have no analytic model: None, not a bogus number
    assert clm_train_telemetry(object()) is None


# ------------------------------------------------------------- recompiles


def test_recompile_tracker_counts_shape_driven_recompiles(tmp_path):
    events = EventLog(str(tmp_path), main_process=True)
    tracker = RecompileTracker(events=events, goodput=GoodputTracker())
    f = tracker.wrap(jax.jit(lambda x: x * 2), "f")
    f(jnp.ones((2,)))
    f(jnp.ones((2,)))  # cache hit: no event
    f(jnp.ones((3,)))  # new shape: silent recompile surfaces
    assert tracker.counts()["f"] == 2
    compiles = [e for e in read_events(tmp_path) if e["event"] == "compile"]
    assert len(compiles) == 2
    # the shape signatures differ — that's what identifies the leak
    assert compiles[0]["arg_shapes"] != compiles[1]["arg_shapes"]
    assert all(c["wall_s"] >= 0 for c in compiles)
    assert tracker.total_compile_s >= 0


# ---------------------------------------------------------- xplane golden


def _vint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _varint_field(fnum: int, n: int) -> bytes:
    return _vint(fnum << 3) + _vint(n)


def _len_field(fnum: int, payload: bytes) -> bytes:
    return _vint((fnum << 3) | 2) + _vint(len(payload)) + payload


def golden_xplane() -> (bytes, dict):
    """A hand-encoded XSpace: one device plane, one "XLA Ops" line, six ops —
    two with scope paths in their display names, one raw HLO op, one with
    the path in an XEventMetadata ``tf_op`` stat (str_value), one with an
    interned per-event stat (ref_value), one unscoped. Field numbers match
    the parser's contract (obs/xplane.py wire-format notes)."""
    ops = {
        1: ("jit(train_step)/perceiver_ar/cross_attend/fusion.1", 3000),
        2: ("jit(train_step)/perceiver_ar/cross_attend/dot.7", 1500),
        3: ("jit(train_step)/perceiver_ar/self_attend/fusion.2", 2000),
        4: ("copy.3", 500),
        5: ("fusion.9", 1000),  # scope via metadata tf_op stat
        6: ("dot.11", 250),  # scope via per-event interned ref stat
    }
    # stat_metadata: 50 = the "tf_op" stat key; 60 = an interned path string
    ref_path = "jit(train_step)/decode/sample/dot.11"
    stat_metadata = b"".join(
        _len_field(5, _varint_field(1, sid) + _len_field(2, _varint_field(1, sid) + _len_field(2, sname.encode())))
        for sid, sname in ((50, "tf_op"), (60, ref_path))
    )

    def event(mid, dur, stats=b""):
        return _len_field(4, _varint_field(1, mid) + _varint_field(3, dur) + stats)

    ref_stat = _len_field(4, _varint_field(1, 50) + _varint_field(7, 60))  # XEvent.stats
    events = b"".join(
        event(mid, dur, stats=ref_stat if mid == 6 else b"")
        for mid, (_, dur) in ops.items()
    )
    line = _len_field(2, b"XLA Ops") + events

    tf_op_stat = _len_field(
        5, _varint_field(1, 50) + _len_field(5, b"jit(train_step)/perceiver_ar/mlp/fusion.9")
    )  # XEventMetadata.stats

    def meta(mid, name):
        payload = _varint_field(1, mid) + _len_field(2, name.encode())
        if mid == 5:
            payload += tf_op_stat
        return _len_field(4, _varint_field(1, mid) + _len_field(2, payload))

    metadata = b"".join(meta(mid, name) for mid, (name, _) in ops.items())
    plane = _len_field(2, b"/device:TPU:0") + _len_field(3, line) + metadata + stat_metadata
    return _len_field(1, plane), ops


def test_xplane_golden_parse_and_scope_rollup(tmp_path):
    from perceiver_io_tpu.obs import xplane as ox

    buf, ops = golden_xplane()
    path = os.path.join(str(tmp_path), "golden.xplane.pb")
    with open(path, "wb") as f:
        f.write(buf)

    # raw per-op totals (the tools/xplane.py view)
    planes = list(ox.iter_planes(path))
    assert len(planes) == 1
    plane = planes[0]
    assert plane.name == "/device:TPU:0"
    total = sum(dur for _, dur in ops.values())
    assert plane.total_ps == total == 8250
    assert plane.per_op[ops[1][0]] == 3000
    assert plane.per_line == {"XLA Ops": total}
    # the stat-carried paths were resolved (metadata stat + interned event stat)
    assert plane.op_scopes["fusion.9"] == "jit(train_step)/perceiver_ar/mlp/fusion.9"
    assert plane.op_scopes["dot.11"] == "jit(train_step)/decode/sample/dot.11"

    # per-scope rollup: aggregates by module path, reproduces the totals
    rolls = ox.rollup(path)
    assert len(rolls) == 1
    scopes = rolls[0].scopes
    assert scopes["perceiver_ar/cross_attend"] == (4500, 2)  # fusion.1 + dot.7
    assert scopes["perceiver_ar/self_attend"] == (2000, 1)
    assert scopes["perceiver_ar/mlp"] == (1000, 1)  # via XEventMetadata tf_op stat
    assert scopes["decode/sample"] == (250, 1)  # via per-event ref stat
    assert scopes[ox.UNSCOPED] == (500, 1)
    assert rolls[0].total_ps == plane.total_ps  # acceptance: same totals

    # depth truncation merges sibling scopes
    deep = ox.rollup(path, depth=1)[0].scopes
    assert deep["perceiver_ar"] == (7500, 4)

    # the tools/xplane.py CLI entry resolves to the same numbers
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "tools_xplane", os.path.join(root, "tools", "xplane.py")
    )
    tools_xplane = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tools_xplane)
    out = []
    cli_planes = tools_xplane.summarize(path, top=10, print_fn=out.append)
    assert cli_planes[0].total_ps == rolls[0].total_ps
    assert any("XLA Ops" in line for line in out)  # the CLI rendering ran


def test_scope_of_rules():
    from perceiver_io_tpu.obs.xplane import UNSCOPED, scope_of

    assert scope_of("jit(f)/jit(main)/a/b/op") == "a/b"
    assert scope_of("transpose(jit(f))/a/op") == "a"
    assert scope_of("jit(f)/a/b/op", depth=1) == "a"
    assert scope_of("fusion.12") == UNSCOPED
    assert scope_of("jit(f)/op") == UNSCOPED


# ------------------------------------------------------- metrics resume


def test_metrics_logger_resume_keeps_single_header(tmp_path):
    d = str(tmp_path)
    l1 = MetricsLogger(d, use_tensorboard=False, main_process=True)
    l1.log(1, {"a": 1.0})
    l1.close()

    # restart: a new logger against the same metrics.csv, with a widening key
    l2 = MetricsLogger(d, use_tensorboard=False, main_process=True)
    l2.log(2, {"a": 2.0, "b": 3.0})
    l2.log(3, {"a": 4.0})
    l2.close()

    import csv

    with open(os.path.join(d, "metrics.csv"), newline="") as f:
        raw = f.read().splitlines()
    # exactly one header row, first line, widened to include b
    assert sum(1 for line in raw if line.startswith("step,")) == 1
    header = raw[0].split(",")
    assert "a" in header and "b" in header
    with open(os.path.join(d, "metrics.csv"), newline="") as f:
        rows = list(csv.DictReader(f))
    assert [int(float(r["step"])) for r in rows] == [1, 2, 3]
    assert rows[0]["b"] == ""  # pre-widening row backfilled empty
    assert float(rows[1]["b"]) == 3.0


def test_metrics_logger_resume_foreign_header_rewritten(tmp_path):
    """A metrics.csv whose header lacks the step/time contract keys must be
    rewritten on resume — appending to _keys alone would misalign rows."""
    import csv

    d = str(tmp_path)
    path = os.path.join(d, "metrics.csv")
    with open(path, "w", newline="") as f:
        f.write("loss\n0.9\n")
    logger = MetricsLogger(d, use_tensorboard=False, main_process=True)
    logger.log(1, {"loss": 0.4})
    logger.close()
    with open(path, newline="") as f:
        raw = f.read().splitlines()
    header = raw[0].split(",")
    assert header[0] == "loss" and "step" in header and "time" in header
    assert len(raw) == 3  # one header + the old row + the new row
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    assert float(rows[0]["loss"]) == 0.9 and rows[0]["step"] == ""
    assert float(rows[1]["loss"]) == 0.4 and int(float(rows[1]["step"])) == 1


# -------------------------------------------------------------- profiling


def test_steptimer_percentile_summary():
    from perceiver_io_tpu.utils.profiling import StepTimer, percentile

    timer = StepTimer(warmup=1)
    timer._times = [99.0] + [float(i) for i in range(1, 11)]  # warmup discarded
    assert timer.percentile(50) == pytest.approx(5.5)
    assert timer.percentile(0) == 1.0 and timer.percentile(100) == 10.0
    s = timer.summary()
    assert s["p50"] == pytest.approx(5.5)
    assert s["p90"] == pytest.approx(9.1)
    assert s["p99"] == pytest.approx(9.91)
    assert s["mean"] == pytest.approx(5.5)
    assert s["n"] == 10
    with pytest.raises(ValueError):
        StepTimer().percentile(50)
    with pytest.raises(ValueError):
        percentile([1.0], 150)


# -------------------------------------------------------------- goodput


def test_goodput_tracker_buckets():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    g = GoodputTracker(clock=clock)
    t[0] = 10.0
    with g.measure("compile"):
        t[0] = 12.0
    with g.measure("eval"):
        t[0] = 13.0
    s = g.summary()
    assert s["total_s"] == pytest.approx(13.0)
    assert s["compile_s"] == pytest.approx(2.0)
    assert s["eval_s"] == pytest.approx(1.0)
    assert s["productive_s"] == pytest.approx(10.0)
    assert s["goodput"] == pytest.approx(10.0 / 13.0, abs=1e-3)


def test_device_peak_flops_table():
    # the current (CPU) device resolves to the nominal placeholder entry
    assert device_peak_flops() == 100e9

    class Fake:
        def __init__(self, kind, platform="tpu"):
            self.device_kind = kind
            self.platform = platform

    assert device_peak_flops(Fake("TPU v5 lite")) == 197e12
    assert device_peak_flops(Fake("TPU v4")) == 275e12
    assert device_peak_flops(Fake("NVIDIA A100-SXM4-40GB", "gpu")) == 312e12
    assert device_peak_flops(Fake("warp drive", "quantum")) is None


# ------------------------------------------------------------ obs_report


def test_obs_report_renders_run_summary(tmp_path):
    run_tiny_fit(tmp_path)
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(root, "tools", "obs_report.py")
    )
    obs_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_report)
    text = obs_report.render(str(tmp_path))
    assert "== manifest ==" in text
    assert "jax_version" in text
    assert "== compiles ==" in text and "train_step" in text
    assert "mfu" in text and "tokens_per_sec" in text
    assert "== goodput (fit_end) ==" in text
    # no spurious recompile warning on a clean single-shape run
    assert "WARNING: recompiles" not in text

    # a RESUMED run appends a second legitimate first-compile (fresh process,
    # n_compiles resets to 1) — still no leak warning; a genuine same-process
    # recompile (n_compiles=2) must warn
    with open(os.path.join(str(tmp_path), "events.jsonl"), "a") as f:
        f.write(json.dumps({"ts": 0, "event": "compile", "fn": "train_step",
                            "wall_s": 1.0, "n_compiles": 1}) + "\n")
    assert "WARNING: recompiles" not in obs_report.render(str(tmp_path))
    with open(os.path.join(str(tmp_path), "events.jsonl"), "a") as f:
        f.write(json.dumps({"ts": 0, "event": "compile", "fn": "train_step",
                            "wall_s": 1.0, "n_compiles": 2}) + "\n")
    assert "WARNING: recompiles after the first on: train_step" in obs_report.render(str(tmp_path))


# ------------------------------------------------------------ generation


def test_instrumented_generation_stats_and_events(tmp_path):
    from perceiver_io_tpu.generation import GenerationConfig, make_instrumented_generate_fn

    model, config = tiny_clm()
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, config.vocab_size, size=(2, 12)))
    params = model.init(jax.random.PRNGKey(0), prompt, prefix_len=8)
    events = EventLog(str(tmp_path), main_process=True)
    fn = make_instrumented_generate_fn(
        model, num_latents=4, config=GenerationConfig(max_new_tokens=4), events=events
    )
    out, stats = fn(params, prompt)
    assert out.shape == (2, 16)
    assert stats.compiled  # first call pays the compiles
    assert stats.prefill_s > 0 and stats.decode_s >= 0
    assert stats.tokens_per_sec > 0
    assert stats.batch == 2 and stats.prompt_len == 12 and stats.new_tokens == 4

    out2, stats2 = fn(params, prompt)
    assert not stats2.compiled  # warm call: no recompile
    assert np.array_equal(np.asarray(out), np.asarray(out2))  # same rng default

    evs = read_events(tmp_path)
    gen_events = [e for e in evs if e["event"] == "generate"]
    assert len(gen_events) == 2
    assert gen_events[0]["per_token_s"] >= 0
    # both compiled programs surfaced as compile events on the first call
    compile_fns = {e["fn"] for e in evs if e["event"] == "compile"}
    assert compile_fns == {"generate_prefill", "generate_full"}
