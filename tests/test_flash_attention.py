"""Flash-attention kernel vs plain einsum attention (values and grads).

The kernels run in Pallas interpret mode on CPU; the contract they must meet
is the reference attention math (reference: perceiver/model/core/
modules.py:90-170) with the right-aligned causal mask of modules.py:135-140.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.ops.flash_attention import flash_attention


def einsum_attention(q, k, v, pad_mask=None, causal=False, sm_scale=1.0):
    """Plain attention with the same mask semantics (f32 softmax)."""
    nq, nkv = q.shape[2], k.shape[2]
    s = jnp.einsum("bhic,bhjc->bhij", q, k).astype(jnp.float32) * sm_scale
    masked = jnp.zeros((1, 1, 1, nkv), bool)
    if pad_mask is not None:
        masked = masked | pad_mask[:, None, None, :]
    if causal:
        i = jnp.arange(nq)[:, None]
        j = jnp.arange(nkv)[None, :]
        masked = masked | (j > i + (nkv - nq))[None, None]
    s = jnp.where(masked, -0.7 * jnp.finfo(jnp.float32).max, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhij,bhjc->bhic", p.astype(v.dtype), v)


CASES = [
    # (nq, nkv, causal, padded)
    (256, 256, True, False),  # square causal self-attention
    (256, 640, True, False),  # AR cross-attention (prefix + latents)
    (256, 640, True, True),  # ... with pad mask
    (256, 512, False, True),  # encoder cross-attention, padded input
    (200, 300, True, False),  # non-block-multiple lengths
]


@pytest.mark.parametrize("nq,nkv,causal,padded", CASES)
def test_forward_matches_einsum(rng, nq, nkv, causal, padded):
    b, h, d = 2, 2, 16
    q = jnp.asarray(rng.normal(size=(b, h, nq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, nkv, d)), jnp.float32)
    pad = jnp.asarray(rng.random((b, nkv)) < 0.2) if padded else None

    out = flash_attention(q, k, v, pad_mask=pad, causal=causal, sm_scale=d**-0.5,
                          block_q=128, block_kv=128)
    ref = einsum_attention(q, k, v, pad_mask=pad, causal=causal, sm_scale=d**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("nq,nkv,causal,padded", CASES[:3])
def test_gradients_match_einsum(rng, nq, nkv, causal, padded):
    b, h, d = 1, 2, 16
    q = jnp.asarray(rng.normal(size=(b, h, nq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, nkv, d)), jnp.float32)
    pad = jnp.asarray(rng.random((b, nkv)) < 0.2) if padded else None
    w = jnp.asarray(rng.normal(size=(b, h, nq, d)), jnp.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, pad_mask=pad, causal=causal, sm_scale=d**-0.5,
                            block_q=128, block_kv=128)
        return jnp.sum(o * w)

    def loss_ref(q, k, v):
        return jnp.sum(einsum_attention(q, k, v, pad_mask=pad, causal=causal, sm_scale=d**-0.5) * w)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5)


def test_bfloat16_forward(rng):
    b, h, nq, nkv, d = 1, 2, 256, 512, 32
    q = jnp.asarray(rng.normal(size=(b, h, nq, d)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(b, h, nkv, d)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(b, h, nkv, d)), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, sm_scale=d**-0.5, block_q=128, block_kv=128)
    ref = einsum_attention(q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
                           causal=True, sm_scale=d**-0.5)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2
    )


def test_odd_head_dims_match_einsum(rng):
    """Head widths that are not multiples of 8 are zero-padded inside the
    wrapper (the vision classifier's qk width 261 — pixels + Fourier bands —
    takes this path); values and gradients must match the dense reference."""
    b, h, nq, nkv, d_qk, d_v = 1, 2, 256, 384, 37, 21
    q = jnp.asarray(rng.normal(size=(b, h, nq, d_qk)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, nkv, d_qk)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, nkv, d_v)), jnp.float32)

    out = flash_attention(q, k, v, causal=True, sm_scale=d_qk**-0.5,
                          block_q=128, block_kv=128)
    ref = einsum_attention(q, k, v, causal=True, sm_scale=d_qk**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def f(fn):
        def loss(q, k, v):
            o = fn(q, k, v)
            return (o.astype(jnp.float32) ** 2).sum()
        return jax.grad(loss, argnums=(0, 1, 2))

    g_flash = f(lambda q, k, v: flash_attention(
        q, k, v, causal=True, sm_scale=d_qk**-0.5, block_q=128, block_kv=128))(q, k, v)
    g_ref = f(lambda q, k, v: einsum_attention(q, k, v, causal=True, sm_scale=d_qk**-0.5))(q, k, v)
    for a, r in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=5e-5, rtol=5e-5)


def test_fast_kernel_flags_context_scoped():
    """Feature flags are contextvars: scoped by the context manager, reset on
    exit, invisible to other threads — no mutable module global reaches
    trace time (VERDICT r3)."""
    import threading

    from perceiver_io_tpu.ops.flash_attention import (
        ALL_FEATURES,
        fast_features,
        fast_kernels,
        set_fast_kernels,
    )

    assert fast_features() == frozenset()
    with fast_kernels(["base2", "nobias"]):
        assert fast_features() == {"base2", "nobias"}
        seen = {}
        t = threading.Thread(target=lambda: seen.setdefault("f", fast_features()))
        t.start()
        t.join()
        assert seen["f"] == frozenset()  # fresh thread, fresh context
        with fast_kernels(True):
            assert fast_features() == ALL_FEATURES
        assert fast_features() == {"base2", "nobias"}
    assert fast_features() == frozenset()

    import pytest as _pytest

    with _pytest.raises(ValueError, match="unknown kernel features"):
        set_fast_kernels(["warp_speed"])
