"""Shareline tests (ISSUE 17): the refcounted sharing laws of the page
allocator (shared grants, copy-on-write forks, last-holder-frees, loud
double-free forensics), the radix prefix index's page-granularity match /
expire discipline, the engine-level isolation and crash-recovery behavior of
shared pages, and the ``decode_shared`` pin — the shared-prefill route
(pool-page gather + suffix-only forward) is BIT-exact equal to the unshared
full-prompt prefill on the einsum attend route, cache bytes, rng chain and
sampled stream included (the claim generation.py's ``make_shared_prefill_fn``
and core/modules.py's ``pos_offset`` seam document)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.generation import (
    GenerationConfig,
    make_decode_fns,
    make_shared_prefill_fn,
)
from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.obs.loadgen import WorkloadSpec
from perceiver_io_tpu.serving import (
    EngineConfig,
    EngineFrontEnd,
    EngineCrash,
    FaultInjector,
    PageAllocator,
    RequestJournal,
)
from perceiver_io_tpu.serving.prefix import PrefixIndex

NUM_LATENTS = 4
VOCAB = 64


# ------------------------------------------------------- allocator sharing


def test_shared_alloc_refcounts_and_stats():
    """``alloc_tokens_shared`` bumps each shared page's refcount and takes
    only the remainder off the free list; the stats surface counts the
    physically-shared pages; ``refcount``/``holders`` answer per page."""
    a = PageAllocator(10, 8)
    g1 = a.alloc_tokens(24)  # 3 pages, sole owner
    assert g1.n_pages == 3 and g1.shared_pages == ()
    free0 = a.pages_free
    g2 = a.alloc_tokens_shared(40, g1.pages[:2])  # 5 pages: 2 shared + 3 fresh
    assert g2.n_pages == 5 and g2.shared_pages == g1.pages[:2]
    assert g2.pages[:2] == g1.pages[:2]
    assert a.pages_free == free0 - 3  # the shared head cost nothing
    for p in g1.pages[:2]:
        assert a.refcount(p) == 2
        assert a.holders(p) == sorted([g1.grant_id, g2.grant_id])
    assert a.refcount(g1.pages[2]) == 1
    s = a.stats()
    assert s.pages_shared == 2 and s.grants == 2
    assert s.pages_used == 6  # 3 + 3 fresh: shared pages counted once
    assert a.audit() == []


def test_share_append_fork_release():
    """The share -> append -> fork law: a writer about to dirty a shared
    tail page forks it (``cow_fork``) — a fresh page lands in the SAME grant
    position, the original drops to its remaining holder, and the forked
    grant no longer calls the page shared. Frees then release everything."""
    a = PageAllocator(10, 8)
    g1 = a.alloc_tokens(16)
    g2 = a.alloc_tokens_shared(24, g1.pages)  # shares both, one fresh tail
    tail = g2.pages[1]  # shared page g2 would append into
    assert a.refcount(tail) == 2
    g2b = a.cow_fork(g2, tail)
    assert g2b is not None and g2b.grant_id == g2.grant_id
    assert g2b.pages[0] == g2.pages[0] and g2b.pages[2] == g2.pages[2]
    assert g2b.pages[1] != tail  # fresh page, same position
    assert g2b.shared_pages == (g2.pages[0],)
    assert a.refcount(tail) == 1 and a.holders(tail) == [g1.grant_id]
    assert a.refcount(g2b.pages[1]) == 1
    assert a.audit() == []
    # the PRE-fork handle drifted from the books: its free is refused loudly
    with pytest.raises(ValueError, match="drifted"):
        a.free(g2)
    a._violations.clear()  # the rejection above was the point, not a leak
    released = a.free(g2b)
    assert set(released) == {g2b.pages[1], g2b.pages[2]}  # g1 still holds [0]
    assert a.free(g1) and a.pages_used == 0
    assert a.audit() == [] and a.stats().pages_shared == 0


def test_shared_pages_survive_sibling_free():
    """Share -> evict-sibling isolation: freeing the PUBLISHER releases only
    its exclusively-held pages — the shared run stays resident (and off the
    free list) until the last sharer drops it, so a sibling's eviction can
    never recycle bytes under a live reader."""
    a = PageAllocator(10, 8)
    g1 = a.alloc_tokens(24)  # publisher: 3 pages
    g2 = a.alloc_tokens_shared(16, g1.pages[:2])  # sharer holds the first 2
    released = a.free(g1)
    assert released == [g1.pages[2]]  # ONLY the unshared page came back
    for p in g2.pages:
        assert a.refcount(p) == 1 and a.holders(p) == [g2.grant_id]
    assert p not in a._free
    assert a.audit() == []
    released = a.free(g2)
    assert set(released) == set(g2.pages)
    assert a.pages_used == 0 and a._rc == {}


def test_cow_fork_exhausted_pool_is_clean():
    """A fork with an EMPTY free list cannot proceed: ``None``, never a torn
    grant — books, refcounts and audit identical before and after (the
    engine maps this answer to a clean ``kv_pages_exhausted`` shed)."""
    a = PageAllocator(5, 8)  # 4 allocatable pages
    g1 = a.alloc_tokens(16)
    g3 = a.alloc_tokens(8)  # an unrelated neighbor holding headroom
    g2 = a.alloc_tokens_shared(24, g1.pages)  # takes the last free page
    assert a.pages_free == 0
    shared = g2.pages[0]
    rc_before = dict(a._rc)
    assert a.cow_fork(g2, shared) is None
    assert a._rc == rc_before and a._grants[g2.grant_id]["pages"] == list(g2.pages)
    assert a.audit() == []
    # the neighbor retiring opens headroom and the same fork now succeeds
    # (the page is still shared: g1 AND g2 hold it)
    a.free(g3)
    assert a.cow_fork(g2, shared) is not None
    assert a.audit() == []


def test_cow_fork_rejects_unshared_and_foreign_pages():
    a = PageAllocator(10, 8)
    g1 = a.alloc_tokens(16)
    with pytest.raises(ValueError, match="not shared"):
        a.cow_fork(g1, g1.pages[0])  # sole holder appends in place
    g2 = a.alloc_tokens(8)
    with pytest.raises(ValueError, match="does not hold"):
        a.cow_fork(g1, g2.pages[0])
    assert a.audit() == []


def test_double_free_names_pages_and_holders():
    """The ISSUE 17 forensics fix: a double free is rejected (raised AND
    recorded) with the grant's PAGE INDICES and each page's CURRENT holders
    in the violation — the post-mortem reads which sharer still owns what
    instead of a bare grant id."""
    a = PageAllocator(10, 8)
    g1 = a.alloc_tokens(16)
    g2 = a.alloc_tokens_shared(16, g1.pages[:1])
    a.free(g1)
    with pytest.raises(ValueError, match="double free"):
        a.free(g1)
    problems = a.audit()
    assert len(problems) == 1
    v = problems[0]
    assert f"pages {list(g1.pages)}" in v
    # the still-shared page names its surviving holder; the released page
    # reads as free
    assert f"page {g1.pages[0]} held by grants [{g2.grant_id}]" in v
    assert f"page {g1.pages[1]} free" in v


def test_shared_alloc_rejections_and_shortfall():
    """Matcher bugs are loud (shared run too long / duplicated / dead pages);
    a FRESH-page shortfall is backpressure: ``None`` with nothing bumped."""
    a = PageAllocator(6, 8)  # 5 allocatable
    g1 = a.alloc_tokens(16)
    with pytest.raises(ValueError, match="exceeds the grant"):
        a.alloc_tokens_shared(8, g1.pages)  # 2 shared into a 1-page grant
    with pytest.raises(ValueError, match="duplicate"):
        a.alloc_tokens_shared(24, (g1.pages[0], g1.pages[0]))
    with pytest.raises(ValueError, match="not live"):
        a.alloc_tokens_shared(16, (5,))  # free page: recycled-content alias
    with pytest.raises(ValueError, match="not live"):
        a.alloc_tokens_shared(16, (0,))  # scratch
    rc_before = dict(a._rc)
    free_before = list(a._free)
    # 2 shared + 4 fresh needed, only 3 free: all-or-nothing None
    assert a.alloc_tokens_shared(48, g1.pages) is None
    assert a._rc == rc_before and a._free == free_before
    assert a.audit() == []


# ------------------------------------------------------------- radix index


def test_prefix_index_insert_match_roundtrip():
    idx = PrefixIndex(8)
    prompt = list(range(20))  # 2 full chunks + a 4-token partial tail
    assert idx.insert(prompt[:16], [5, 6]) == 2
    assert idx.match(prompt) == (5, 6)
    assert idx.match(prompt[:16]) == (5, 6)
    assert idx.match(prompt[:12]) == (5,)  # one full chunk resident
    assert idx.pages() == (5, 6)
    assert len(idx) == 2 and idx.audit() == []
    # re-inserting the same run creates nothing
    assert idx.insert(prompt[:16], [5, 6]) == 0


def test_prefix_index_partial_tail_never_matches():
    """Page-granularity sharing: the partial tail chunk is neither indexed
    nor matched — a prompt agreeing only inside a chunk (or a sub-page
    prompt) shares nothing, and covering the tail with a page is an error."""
    idx = PrefixIndex(8)
    prompt = list(range(20))
    with pytest.raises(ValueError, match="full chunks"):
        idx.insert(prompt, [5, 6, 7])  # page 7 would cover the 4-token tail
    idx.insert(prompt[:16], [5, 6])
    assert idx.match(prompt[:8] + [99] * 8) == (5,)  # diverges in chunk 2
    assert idx.match(prompt[:4]) == ()  # sub-page prompt: no full chunk
    assert idx.match(prompt[:4] + [99] * 8) == ()  # agrees only inside chunk 1
    assert idx.match([99] + prompt[:8]) == ()  # shifted: different chunk bytes


def test_prefix_index_expire_drops_subtree():
    """Expiring a released page removes its node AND the whole subtree under
    it (a match cannot skip a chunk), and unknown pages are a no-op — the
    ``PageAllocator.free`` -> ``expire_pages`` seam."""
    idx = PrefixIndex(8)
    prompt = list(range(24))
    idx.insert(prompt, [3, 4, 5])
    assert idx.match(prompt) == (3, 4, 5)
    assert idx.expire_pages([4]) == 2  # the node and its child
    assert idx.match(prompt) == (3,)
    assert idx.pages() == (3,) and len(idx) == 1
    assert idx.expire_pages([99]) == 0
    assert idx.audit() == []


def test_prefix_index_reinsert_repoints_page():
    """Republishing a chunk path under a NEWER resident page repoints the
    node (the old copy was released); the page map follows."""
    idx = PrefixIndex(8)
    prompt = list(range(16))
    idx.insert(prompt, [3, 4])
    assert idx.insert(prompt, [7, 4]) == 0  # repoint, no new nodes
    assert idx.match(prompt) == (7, 4)
    assert idx.pages() == (4, 7)
    assert idx.audit() == []


# --------------------------------------------------- engine-level sharing


@pytest.fixture(scope="module")
def model_and_params():
    config = CausalLanguageModelConfig(
        vocab_size=VOCAB, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    ids = np.random.default_rng(0).integers(0, VOCAB, size=(1, 12))
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids), prefix_len=8)
    return model, params


def _engine(model, params, base_config=None, *, max_sa_tokens=16, **kw):
    # journal/eviction engines need the no-slide bound max_sa_tokens <=
    # max_latents (8) — budgets <= 4 keep sa_tokens within it
    return EngineFrontEnd(
        model, params, num_latents=NUM_LATENTS, base_config=base_config,
        engine_config=EngineConfig(slots=4, page_size=8, max_ca_tokens=24,
                                   max_sa_tokens=max_sa_tokens),
        **kw,
    )


def _shared_specs(n, seed=21):
    # prompt 16, latents 4 -> context 12: exactly ONE full shareable page
    wspec = WorkloadSpec(seed=seed, prompt_lens=(16,), max_new_tokens=(3, 4),
                         shared_prefix_len=8)
    return wspec.draw(n, VOCAB)


def _sequential_tokens(model, params, spec, base_config=None):
    cfg = dataclasses.replace(
        base_config or GenerationConfig(), max_new_tokens=spec.max_new_tokens
    )
    prefill, step = make_decode_fns(model, NUM_LATENTS, cfg)
    tok, state = prefill(
        params, jnp.asarray(spec.input_ids), None, jax.random.PRNGKey(spec.rng_seed)
    )
    out = [int(tok[0])]
    for _ in range(spec.max_new_tokens - 1):
        state, tok = step(state)
        out.append(int(tok[0]))
    return out


def test_engine_sharing_token_exact_and_isolated(model_and_params):
    """Requests sharing a prefix serve token-exact (each equals ITS OWN
    sequential stream), with the publisher retiring before its sharers —
    the shared page survives the sibling's retire (refcount, not ownership)
    and everything drains clean: refcounts balanced, index expired."""
    model, params = model_and_params
    fe = _engine(model, params)
    specs = _shared_specs(6)
    recs = fe.run_closed(specs, concurrency=6)
    assert all(r.outcome == "ok" for r in recs), [vars(r) for r in recs]
    assert fe._n_prefix_hits >= 1, "nothing shared — the test is vacuous"
    for spec in specs:
        want = _sequential_tokens(model, params, spec)
        assert fe.served_tokens[spec.index] == want, spec.index
    assert fe.books()["balanced"] and fe.audit() == []
    assert fe.sharing_audit() == []
    assert fe.ca_alloc.pages_used == 0 and fe.ca_alloc._rc == {}
    assert fe.prefix_index.pages() == ()


def test_recovery_rebuilds_refcounts(model_and_params, tmp_path):
    """Crash mid-flight with shared-prefix requests in every state (live,
    queued): the second engine's recovery re-admits them into a FRESH
    allocator/index and the sharing machinery rebuilds its refcounts from
    the replays — streams token-exact, refcounts balanced at drain, and the
    re-served requests SHARE AGAIN (queued recoveries go through the
    matching join)."""
    model, params = model_and_params
    jpath = str(tmp_path / "journal.jsonl")
    specs = _shared_specs(6, seed=23)
    fe1 = _engine(model, params, max_sa_tokens=8, journal=jpath,
                  injector=FaultInjector().crash_at(2, 1))
    with pytest.raises(EngineCrash):
        fe1.run_closed(specs, concurrency=6)
    journal = RequestJournal(jpath)
    owed = journal.pending()
    assert len(owed) >= 2, "crash too late — nothing left to share on replay"

    fe2 = _engine(model, params, max_sa_tokens=8)
    info = fe2.recover(journal)
    assert info["recovered"] == len(owed)
    fe2.pump()
    books = fe2.books()
    assert books["balanced"] and books["parked"] == 0, books
    assert fe2.audit() == [] and fe2.sharing_audit() == []
    assert fe2.ca_alloc.pages_used == 0 and fe2.ca_alloc._rc == {}
    assert fe2.prefix_index.pages() == ()
    assert fe2._n_prefix_hits >= 1, "recovered requests never re-shared"
    served = dict(fe1.served_tokens)
    served.update(fe2.served_tokens)
    for spec in specs:
        want = _sequential_tokens(model, params, spec)
        assert served.get(spec.index) == want, spec.index


def test_engine_cow_fork_seam_protects_co_owner(model_and_params):
    """The engine end of the COW contract (``pages.cow_fork`` leaves the
    device copy to its caller — Fleetline satellite, ISSUE 20): a write
    into a SHARED append page goes through ``_fork_shared_append_page``,
    which forks the grant AND duplicates the page's pool rows into the
    fresh page — the co-owner's resident bytes survive untouched, the
    appender owns a bit-identical private copy, and the allocator books
    stay clean. An unshared append page passes through untouched (today's
    whole-page sharing cap makes that every production append); a dry pool
    answers ``None`` with the grant and the device pool unchanged (the
    caller sheds exactly like a failed allocation)."""
    model, params = model_and_params
    fe = _engine(model, params)
    a = fe.ca_alloc
    g1 = a.alloc_tokens(16)                    # publisher: 2 pages
    g2 = a.alloc_tokens_shared(24, g1.pages)   # shares both + 1 fresh tail
    tail = g2.pages[1]
    assert a.refcount(tail) == 2

    # plant sentinel rows in the shared page so the device copy is visible
    pool = fe._state["cache"][0]
    marker_k = jnp.full(pool.k.shape[1:], 7.0, pool.k.dtype)
    marker_v = jnp.full(pool.v.shape[1:], -3.0, pool.v.dtype)
    caches = list(fe._state["cache"])
    caches[0] = pool.replace(k=pool.k.at[tail].set(marker_k),
                             v=pool.v.at[tail].set(marker_v))
    fe._state = dict(fe._state, cache=tuple(caches))

    forked = fe._fork_shared_append_page(g2, 12)  # position in page slot 1
    assert forked is not None and forked.grant_id == g2.grant_id
    fresh = forked.pages[1]
    assert fresh != tail and forked.pages[0] == g2.pages[0]
    assert forked.shared_pages == (g2.pages[0],)
    pool = fe._state["cache"][0]
    assert np.array_equal(np.asarray(pool.k[fresh]), np.asarray(marker_k))
    assert np.array_equal(np.asarray(pool.v[fresh]), np.asarray(marker_v))
    assert np.array_equal(np.asarray(pool.k[tail]), np.asarray(marker_k))
    assert a.refcount(tail) == 1 and a.holders(tail) == [g1.grant_id]
    assert a.refcount(fresh) == 1
    assert a.audit() == []

    # unshared append page: identity passthrough, no fork, no copy
    assert fe._fork_shared_append_page(forked, 20) is forked

    # pool dry: None, nothing torn on host or device (slot 0 still shared)
    hog = a.alloc_tokens(a.pages_free * 8)
    assert hog is not None and a.pages_free == 0
    k_before = np.asarray(fe._state["cache"][0].k)
    assert fe._fork_shared_append_page(forked, 4) is None
    assert np.array_equal(np.asarray(fe._state["cache"][0].k), k_before)
    assert a.audit() == []

    a.free(hog)
    a.free(forked)
    a.free(g1)
    assert a.pages_used == 0 and a._rc == {}


# -------------------------------------------------- decode_shared pin


@pytest.mark.parametrize("sampling", ["greedy", "temperature"])
def test_decode_shared_bit_exact(model_and_params, sampling):
    """THE exactness pin behind Shareline: prefilling only the suffix over
    CA rows gathered from shared pool pages produces a state BITWISE equal
    to the full-prompt prefill's — cache bytes, first token, rng — and the
    decode stream continued from it is token-exact equal, greedy AND
    temperature. Holds because context-region rows under rotate-at-write
    RoPE depend only on (token id, absolute position) and both routes run
    the same einsum attend (``pos_offset`` right-aligns the suffix's
    positions and causal mask)."""
    model, params = model_and_params
    cfg = (
        GenerationConfig(max_new_tokens=4)
        if sampling == "greedy"
        else GenerationConfig(max_new_tokens=4, do_sample=True,
                              temperature=0.8, top_k=10)
    )
    prompt = np.random.default_rng(3).integers(0, VOCAB, size=(1, 20))
    skip, ps = 16, 8  # 2 full pages, inside the 16-token context region
    rng = jax.random.PRNGKey(42)

    prefill, step = make_decode_fns(model, NUM_LATENTS, cfg)
    tok_ref, state_ref = prefill(params, jnp.asarray(prompt), None, rng)

    # the resident pool: the reference's context rows parked in pages 1, 3
    # of a 5-page pool (id order scrambled on purpose — the gather must
    # follow page_ids, not arithmetic)
    ca_ref = state_ref["cache"][0]
    n_ch = ca_ref.k.shape[-1]
    pool_k = jnp.zeros((5, ps, n_ch), ca_ref.k.dtype)
    pool_v = jnp.zeros((5, ps, n_ch), ca_ref.v.dtype)
    page_ids = jnp.asarray([3, 1], jnp.int32)
    rows_k = ca_ref.k[0, :skip].reshape(2, ps, n_ch)
    rows_v = ca_ref.v[0, :skip].reshape(2, ps, n_ch)
    pool_k = pool_k.at[page_ids].set(rows_k)
    pool_v = pool_v.at[page_ids].set(rows_v)

    shared_prefill = make_shared_prefill_fn(model, NUM_LATENTS, skip, 20, cfg)
    tok_sh, state_sh = shared_prefill(
        params, jnp.asarray(prompt)[:, skip:], pool_k, pool_v, page_ids, rng
    )
    assert int(tok_sh[0]) == int(tok_ref[0])
    # the caches agree BITWISE, CA and every SA layer (exactness, not
    # tolerance: same bytes in, same einsum, same bytes out)
    for c_sh, c_ref in zip(state_sh["cache"], state_ref["cache"]):
        assert np.array_equal(np.asarray(c_sh.k), np.asarray(c_ref.k))
        assert np.array_equal(np.asarray(c_sh.v), np.asarray(c_ref.v))
        assert int(c_sh.length) == int(c_ref.length)
    assert np.array_equal(np.asarray(state_sh["rng"]), np.asarray(state_ref["rng"]))

    # continue decoding from the shared state through the UNSHARED step fn
    # (the engine's decode path): the streams stay token-exact to the end
    full_state = dict(
        state_sh,
        params=state_ref["params"],
        ca_start=state_ref["ca_start"],
        sa_start=state_ref["sa_start"],
    )
    ref_state, got, want = state_ref, [int(tok_sh[0])], [int(tok_ref[0])]
    for _ in range(cfg.max_new_tokens - 1):
        full_state, tok_s = step(full_state)
        ref_state, tok_r = step(ref_state)
        got.append(int(tok_s[0]))
        want.append(int(tok_r[0]))
    assert got == want, f"{sampling}: shared {got} != unshared {want}"


def test_shared_prefill_rejects_latent_region_match():
    """A matched run reaching into the latent region is a constructor-time
    error (latent rows pass through q_norm + the SA stack and are NOT
    per-token): the engine's match cap makes this unreachable, the fn
    refuses to exist for such a geometry anyway."""
    config = CausalLanguageModelConfig(
        vocab_size=VOCAB, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    with pytest.raises(ValueError, match="latent"):
        make_shared_prefill_fn(model, NUM_LATENTS, 16, 18,
                               GenerationConfig(max_new_tokens=2))
