"""The benchmark's stall-hardened slope measurement, under a fake clock.

robust_slope's contract: per-iteration time from interleaved short/long
chain timings, min-reduced per estimate, median across estimates, with
stall-corrupted (non-positive) estimates dropped — a tunnel stall must not
surface as inflated throughput (the failure mode the median replaced min
for), and an all-stall measurement must fail loudly instead of returning a
garbage sentinel.
"""

import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
import bench  # noqa: E402


class FakeClock:
    """perf_counter substitute advanced by the fake run() below."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def make_run(clock, per_step: float, stall_schedule=None):
    """run(k) advances the clock by k * per_step, plus any scheduled stall:
    ``stall_schedule`` maps call index -> extra seconds."""
    calls = {"n": 0}
    stall_schedule = stall_schedule or {}

    def run(k):
        extra = stall_schedule.get(calls["n"], 0.0)
        calls["n"] += 1
        clock.now += k * per_step + extra

    return run


@pytest.fixture
def clock(monkeypatch):
    c = FakeClock()
    monkeypatch.setattr(bench.time, "perf_counter", c)
    return c


def test_clean_measurement_recovers_step_time(clock):
    run = make_run(clock, per_step=0.005)
    s = bench.robust_slope(run, 2, 22, estimates=3, reps=2)
    assert s == pytest.approx(0.005, rel=1e-9)


def test_stall_on_long_chain_does_not_inflate_estimate(clock):
    # calls: 2 compile, then per estimate: reps * (short, long).
    # Stall every long-chain rep of estimate 0 (call idxs 3 and 5): that
    # estimate's slope is inflated; the median of the three estimates must
    # still be the clean step time.
    run = make_run(clock, per_step=0.005, stall_schedule={3: 2.0, 5: 2.0})
    s = bench.robust_slope(run, 2, 22, estimates=3, reps=2)
    assert s == pytest.approx(0.005, rel=1e-9)


def test_stall_on_short_chain_does_not_deflate_result(clock):
    # Stall both short-chain reps of estimate 0 (call idxs 2 and 4): that
    # estimate's slope goes negative (t_short > t_long) and must be dropped,
    # not selected — min-of-estimates would have returned it.
    run = make_run(clock, per_step=0.005, stall_schedule={2: 2.0, 4: 2.0})
    s = bench.robust_slope(run, 2, 22, estimates=3, reps=2)
    assert s == pytest.approx(0.005, rel=1e-9)


def test_all_estimates_corrupted_raises(clock):
    # every short-chain rep stalls -> every estimate non-positive
    stalls = {i: 5.0 for i in range(2, 20, 2)}
    run = make_run(clock, per_step=0.005, stall_schedule=stalls)
    with pytest.raises(RuntimeError, match="non-positive"):
        bench.robust_slope(run, 2, 22, estimates=3, reps=2)


# --- interleaved_slopes (the multi-variant harness shared by tools/*_ab.py) ---


def test_interleaved_recovers_each_variant(clock):
    runs = {"a": make_run(clock, per_step=0.005), "b": make_run(clock, per_step=0.008)}
    meds = bench.interleaved_slopes(runs, 2, 22, estimates=3, reps=2)
    assert meds["a"] == pytest.approx(0.005, rel=1e-9)
    assert meds["b"] == pytest.approx(0.008, rel=1e-9)


def test_interleaved_stall_on_one_variant_leaves_other_clean(clock):
    # Call order per rep is a-short, a-long, b-short, b-long. Stall b's
    # short chains in estimate 0 (per-variant call idxs 0 and 2 of the
    # measurement phase): b's first estimate goes negative and is dropped;
    # a must be untouched and b's median comes from its clean estimates.
    runs = {
        "a": make_run(clock, per_step=0.005),
        "b": make_run(clock, per_step=0.008, stall_schedule={0: 2.0, 2: 2.0}),
    }
    meds = bench.interleaved_slopes(runs, 2, 22, estimates=3, reps=2)
    assert meds["a"] == pytest.approx(0.005, rel=1e-9)
    assert meds["b"] == pytest.approx(0.008, rel=1e-9)


def test_interleaved_all_stalled_variant_returns_none(clock):
    # every short chain of 'b' stalls -> all b estimates non-positive ->
    # None (the tools print a rerun message), while 'a' still measures
    stalls = {i: 5.0 for i in range(0, 12, 2)}
    runs = {
        "a": make_run(clock, per_step=0.005),
        "b": make_run(clock, per_step=0.008, stall_schedule=stalls),
    }
    meds = bench.interleaved_slopes(runs, 2, 22, estimates=3, reps=2)
    assert meds["a"] == pytest.approx(0.005, rel=1e-9)
    assert meds["b"] is None


def test_auto_microbatch_always_divides():
    """The derived chunk count must divide every batch size (an indivisible
    pair silently disables chunking in the train path) and prefer chunks of
    4 where possible."""
    for b in range(1, 65):
        mb = bench.auto_microbatch(b)
        assert b % mb == 0, (b, mb)
        chunk = b // mb
        assert chunk in (1, 2, 4), (b, mb)
        if b % 4 == 0:
            assert chunk == 4, (b, mb)
        elif b % 2 == 0:
            assert chunk == 2, (b, mb)
