"""Cached-incremental decoding must equal the full uncached forward — the
central numerical contract, ported from the reference's crown-jewel test
(reference: tests/kv_cache_test.py:82-234) onto fixed-capacity caches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.core.attention import init_kv_cache
from perceiver_io_tpu.core.config import CausalSequenceModelConfig
from perceiver_io_tpu.core.modules import CausalSequenceModel, CrossAttentionLayer, SelfAttentionBlock
from perceiver_io_tpu.core.position import frequency_position_encoding, positions

NUM_PREFIX = 8
NUM_LATENTS = 16
NUM_CHANNELS = 128
NUM_HEADS = 8
NUM_LAYERS = 4
BATCH_SIZE = 2
ROPE_DIM = NUM_CHANNELS // NUM_HEADS // 4

ATOL = 1e-5


def create_pad_mask(seq_len):
    pad_mask = np.zeros((BATCH_SIZE, seq_len), dtype=bool)
    pad_mask[1, :2] = True
    return jnp.asarray(pad_mask)


def create_enc(seq_len, pad_mask=None):
    shift = None if pad_mask is None else pad_mask.sum(axis=1, keepdims=True).astype(jnp.int32)
    return frequency_position_encoding(positions(BATCH_SIZE, seq_len, shift=shift), ROPE_DIM)


@pytest.fixture(scope="module")
def self_attn():
    block = SelfAttentionBlock(
        num_layers=NUM_LAYERS,
        num_heads=NUM_HEADS,
        num_channels=NUM_CHANNELS,
        num_qk_channels=NUM_CHANNELS // 2,
        num_v_channels=NUM_CHANNELS // 2,
        causal_attention=True,
        num_rotary_layers=-1,
    )
    x = jnp.zeros((BATCH_SIZE, NUM_LATENTS, NUM_CHANNELS))
    params = block.init(jax.random.PRNGKey(0), x)
    return block, params


@pytest.fixture(scope="module")
def cross_attn():
    layer = CrossAttentionLayer(
        num_heads=NUM_HEADS,
        num_q_input_channels=NUM_CHANNELS,
        num_kv_input_channels=NUM_CHANNELS,
        num_qk_channels=NUM_CHANNELS // 2,
        num_v_channels=NUM_CHANNELS // 2,
        causal_attention=True,
    )
    x = jnp.zeros((BATCH_SIZE, NUM_LATENTS, NUM_CHANNELS))
    params = layer.init(jax.random.PRNGKey(0), x, x_kv_prefix=jnp.zeros((BATCH_SIZE, NUM_PREFIX, NUM_CHANNELS)))
    return layer, params


@pytest.fixture(scope="module")
def csm():
    config = CausalSequenceModelConfig(
        vocab_size=100,
        max_seq_len=NUM_LATENTS + NUM_PREFIX,
        max_latents=NUM_LATENTS,
        num_channels=NUM_CHANNELS,
        num_self_attention_layers=NUM_LAYERS,
        num_self_attention_rotary_layers=-1,
        output_norm=True,
    )
    model = CausalSequenceModel(config)
    x = jnp.zeros((BATCH_SIZE, NUM_PREFIX + NUM_LATENTS), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=NUM_PREFIX)
    return model, params, config


def make_sa_cache(capacity):
    return tuple(
        init_kv_cache(BATCH_SIZE, capacity, NUM_CHANNELS // 2, NUM_CHANNELS // 2)
        for _ in range(NUM_LAYERS)
    )


def test_self_attn_cache(self_attn):
    block, params = self_attn
    x = jnp.asarray(np.random.default_rng(0).normal(size=(BATCH_SIZE, NUM_LATENTS, NUM_CHANNELS)), jnp.float32)
    enc = create_enc(NUM_LATENTS)

    # full forward, caches populated in one shot
    out_ref = block.apply(params, x, rope_q=enc, rope_k=enc, kv_cache=make_sa_cache(NUM_LATENTS))
    hidden_ref, cache_ref = out_ref.last_hidden_state, out_ref.kv_cache

    # incremental: one latent at a time against the fixed-capacity cache
    # (rope_k covers the newly appended token — keys rotate at write)
    cache = make_sa_cache(NUM_LATENTS)
    hidden = []
    for i in range(NUM_LATENTS):
        out = block.apply(
            params,
            x[:, i : i + 1],
            rope_q=enc[:, i : i + 1],
            rope_k=enc[:, i : i + 1],
            kv_cache=cache,
        )
        hidden.append(out.last_hidden_state)
        cache = out.kv_cache

    hidden = jnp.concatenate(hidden, axis=1)
    assert hidden.shape == hidden_ref.shape
    np.testing.assert_allclose(np.asarray(hidden), np.asarray(hidden_ref), atol=ATOL)

    for i in range(NUM_LAYERS):
        np.testing.assert_allclose(np.asarray(cache[i].k), np.asarray(cache_ref[i].k), atol=ATOL)
        np.testing.assert_allclose(np.asarray(cache[i].v), np.asarray(cache_ref[i].v), atol=ATOL)
        assert int(cache[i].length) == NUM_LATENTS


def test_cross_attn_cache(cross_attn):
    layer, params = cross_attn
    rng = np.random.default_rng(1)
    x_q = jnp.asarray(rng.normal(size=(BATCH_SIZE, NUM_LATENTS, NUM_CHANNELS)), jnp.float32)
    x_kv_prefix = jnp.asarray(rng.normal(size=(BATCH_SIZE, NUM_PREFIX, NUM_CHANNELS)), jnp.float32)

    total = NUM_PREFIX + NUM_LATENTS
    pad_mask = create_pad_mask(total)
    enc = create_enc(total, pad_mask=pad_mask)

    def empty_cache():
        return init_kv_cache(BATCH_SIZE, total, NUM_CHANNELS // 2, NUM_CHANNELS // 2)

    out_ref = layer.apply(
        params,
        x_q,
        x_kv_prefix=x_kv_prefix,
        pad_mask=pad_mask,
        rope_q=enc[:, NUM_PREFIX:],
        rope_k=enc,
        kv_cache=empty_cache(),
    )
    hidden_ref, cache_ref = out_ref.last_hidden_state, out_ref.kv_cache

    # incremental: prefix + first latent, then one latent at a time
    # (rope_k covers exactly the tokens appended by each call)
    cache = empty_cache()
    hidden = []
    empty_prefix = jnp.zeros((BATCH_SIZE, 0, NUM_CHANNELS))
    for i in range(NUM_LATENTS):
        rope_k = (
            enc[:, : NUM_PREFIX + 1]
            if i == 0
            else enc[:, NUM_PREFIX + i : NUM_PREFIX + i + 1]
        )
        out = layer.apply(
            params,
            x_q[:, i : i + 1],
            x_kv_prefix=x_kv_prefix if i == 0 else empty_prefix,
            pad_mask=pad_mask,
            rope_q=enc[:, NUM_PREFIX + i : NUM_PREFIX + i + 1],
            rope_k=rope_k,
            kv_cache=cache,
        )
        hidden.append(out.last_hidden_state)
        cache = out.kv_cache

    hidden = jnp.concatenate(hidden, axis=1)
    assert hidden.shape == hidden_ref.shape
    np.testing.assert_allclose(np.asarray(hidden), np.asarray(hidden_ref), atol=ATOL)
    np.testing.assert_allclose(np.asarray(cache.k), np.asarray(cache_ref.k), atol=ATOL)
    np.testing.assert_allclose(np.asarray(cache.v), np.asarray(cache_ref.v), atol=ATOL)


@pytest.mark.slow
def test_csm_cache(csm):
    model, params, config = csm
    total = NUM_PREFIX + NUM_LATENTS
    x = jnp.asarray(np.random.default_rng(2).integers(0, config.vocab_size, size=(BATCH_SIZE, total)))
    pad_mask = create_pad_mask(total)

    out_ref = model.apply(
        params,
        x,
        prefix_len=NUM_PREFIX,
        pad_mask=pad_mask,
        kv_cache=CausalSequenceModel.init_cache(config, BATCH_SIZE),
    )
    logits_ref, cache_ref = out_ref.logits, out_ref.kv_cache

    # uncached forward agrees with the cache-populating full forward
    out_nocache = model.apply(params, x, prefix_len=NUM_PREFIX, pad_mask=pad_mask)
    np.testing.assert_allclose(np.asarray(out_nocache.logits), np.asarray(logits_ref), atol=ATOL)

    # incremental: init with prefix + 2 latents, then one token at a time
    cache = CausalSequenceModel.init_cache(config, BATCH_SIZE)
    out = model.apply(
        params,
        x[:, : NUM_PREFIX + 2],
        prefix_len=NUM_PREFIX,
        pad_mask=pad_mask[:, : NUM_PREFIX + 2],
        kv_cache=cache,
    )
    logits = [out.logits]
    cache = out.kv_cache

    for i in range(2, NUM_LATENTS):
        out = model.apply(
            params,
            x[:, NUM_PREFIX + i : NUM_PREFIX + i + 1],
            prefix_len=NUM_PREFIX,
            pad_mask=pad_mask,
            kv_cache=cache,
            decode=True,
        )
        logits.append(out.logits)
        cache = out.kv_cache

    logits = jnp.concatenate(logits, axis=1)
    assert logits.shape == logits_ref.shape
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits_ref), atol=ATOL)

    for i in range(1 + NUM_LAYERS):
        np.testing.assert_allclose(np.asarray(cache[i].k), np.asarray(cache_ref[i].k), atol=ATOL)
        np.testing.assert_allclose(np.asarray(cache[i].v), np.asarray(cache_ref[i].v), atol=ATOL)


def test_max_heads_parallel_matches_full(cross_attn):
    """Head-chunked attention (reference: max_heads_parallel,
    modules.py:142-166) must equal the all-heads computation, with and
    without a cache (the cached path slices the slots-major head axis)."""
    rng = np.random.default_rng(4)
    x_q = jnp.asarray(rng.normal(size=(BATCH_SIZE, NUM_LATENTS, NUM_CHANNELS)), jnp.float32)
    x_kv_prefix = jnp.asarray(rng.normal(size=(BATCH_SIZE, NUM_PREFIX, NUM_CHANNELS)), jnp.float32)

    def layer(chunk):
        return CrossAttentionLayer(
            num_heads=NUM_HEADS,
            num_q_input_channels=NUM_CHANNELS,
            num_kv_input_channels=NUM_CHANNELS,
            num_qk_channels=NUM_CHANNELS // 2,
            num_v_channels=NUM_CHANNELS // 2,
            causal_attention=True,
            max_heads_parallel=chunk,
        )

    _, params = cross_attn  # same param structure for any chunking
    full = layer(None).apply(params, x_q, x_kv_prefix=x_kv_prefix)
    # chunk=3 leaves a partial final chunk (8 heads) — must also work
    for chunk in (2, 3):
        chunked = layer(chunk).apply(params, x_q, x_kv_prefix=x_kv_prefix)
        np.testing.assert_allclose(
            np.asarray(chunked.last_hidden_state), np.asarray(full.last_hidden_state), atol=ATOL
        )

    total = NUM_PREFIX + NUM_LATENTS
    cache_full = init_kv_cache(BATCH_SIZE, total, NUM_CHANNELS // 2, NUM_CHANNELS // 2)
    full_c = layer(None).apply(params, x_q, x_kv_prefix=x_kv_prefix, kv_cache=cache_full)
    cache_chunk = init_kv_cache(BATCH_SIZE, total, NUM_CHANNELS // 2, NUM_CHANNELS // 2)
    chunked_c = layer(2).apply(params, x_q, x_kv_prefix=x_kv_prefix, kv_cache=cache_chunk)
    np.testing.assert_allclose(
        np.asarray(chunked_c.last_hidden_state),
        np.asarray(full_c.last_hidden_state),
        atol=ATOL,
    )
    np.testing.assert_allclose(
        np.asarray(chunked_c.last_hidden_state), np.asarray(full.last_hidden_state), atol=ATOL
    )


def test_prefill_mode_matches_einsum_prime():
    """generation.py's prompt pass under ``prefill_mode`` (packed flash over
    the fresh k/v) must reproduce the slot-capacity einsum prime exactly:
    same latent logits, same cache contents — including a left-padded row.
    Geometry is flash-sized (the fused path needs >=128 queries/keys)."""
    from perceiver_io_tpu.core.attention import prefill_mode
    from perceiver_io_tpu.ops.flash_attention import set_default_flash

    config = CausalSequenceModelConfig(
        vocab_size=100,
        max_seq_len=256,
        max_latents=128,
        num_channels=64,
        num_heads=4,
        num_self_attention_layers=2,
        num_self_attention_rotary_layers=-1,
        output_norm=True,
    )
    model = CausalSequenceModel(config)
    total = 256
    x = jnp.asarray(np.random.default_rng(3).integers(0, 100, size=(BATCH_SIZE, total)))
    pad_mask = jnp.zeros((BATCH_SIZE, total), bool).at[0, :5].set(True)
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=128)

    out_ref = model.apply(
        params, x, prefix_len=128, pad_mask=pad_mask,
        kv_cache=CausalSequenceModel.init_cache(config, BATCH_SIZE),
    )

    set_default_flash(True)
    try:
        with prefill_mode():
            out_flash = model.apply(
                params, x, prefix_len=128, pad_mask=pad_mask,
                kv_cache=CausalSequenceModel.init_cache(config, BATCH_SIZE),
            )
    finally:
        set_default_flash(None)

    np.testing.assert_allclose(
        np.asarray(out_flash.logits), np.asarray(out_ref.logits), atol=2e-5, rtol=2e-5
    )
    for i, (c_f, c_r) in enumerate(zip(out_flash.kv_cache, out_ref.kv_cache)):
        assert int(c_f.length) == int(c_r.length)
        np.testing.assert_allclose(
            np.asarray(c_f.k), np.asarray(c_r.k), atol=1e-6, err_msg=f"cache {i} k"
        )
        np.testing.assert_allclose(
            np.asarray(c_f.v), np.asarray(c_r.v), atol=1e-6, err_msg=f"cache {i} v"
        )


def test_prefill_nonempty_cache_poisons_output():
    """The prefill empty-cache contract cannot be checked at trace time (the
    cache length is traced inside the caller's jit); a jitted forward whose
    cache turns out NON-empty under ``prefill_mode`` must fail loudly — its
    output is NaN-poisoned at run time — instead of returning silently wrong
    numbers. The same program with length 0 computes normally."""
    from perceiver_io_tpu.core.attention import KVCache, prefill_mode
    from perceiver_io_tpu.ops.flash_attention import default_flash

    config = CausalSequenceModelConfig(
        vocab_size=100,
        max_seq_len=256,
        max_latents=128,
        num_channels=64,
        num_heads=4,
        num_self_attention_layers=1,
        num_self_attention_rotary_layers=-1,
    )
    model = CausalSequenceModel(config)
    x = jnp.asarray(np.random.default_rng(5).integers(0, 100, size=(BATCH_SIZE, 256)))
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=128)

    def fwd(ca_len):
        cache = CausalSequenceModel.init_cache(config, BATCH_SIZE)
        ca = cache[0]
        cache = (KVCache(k=ca.k, v=ca.v, length=ca_len),) + cache[1:]
        return model.apply(params, x, prefix_len=128, kv_cache=cache).logits

    with default_flash(True), prefill_mode():
        bad = jax.jit(fwd)(jnp.int32(4))
        good = jax.jit(fwd)(jnp.int32(0))
    assert np.isnan(np.asarray(bad)).all()
    assert np.isfinite(np.asarray(good)).all()


def test_prefill_flag_is_context_scoped():
    """prefill_mode must not leak across threads (it is a contextvar, not a
    module global): a thread started inside the with-block sees the default."""
    import threading

    from perceiver_io_tpu.core import attention as att

    seen = {}

    def probe():
        seen["prefill"] = att._PREFILL.get()

    with att.prefill_mode():
        t = threading.Thread(target=probe)
        t.start()
        t.join()
        assert att._PREFILL.get() is True
    assert att._PREFILL.get() is False
    assert seen["prefill"] is False
