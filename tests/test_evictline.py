"""Evictline tests (ISSUE 15): page-pressure preemption with token-exact
resume and journal-backed engine crash recovery. The eviction seam parks an
in-flight slot (pages reclaimed) and resumes it by replaying the existing
prefill program over prompt + emitted prefix with the rng chain advanced one
split per emitted token — pinned token-exact vs the uninterrupted sequential
path, greedy AND temperature. The write-ahead request journal
(``serving.journal``) survives an injected ``EngineCrash`` and a fresh
engine's ``recover()`` re-admits every non-terminal request with the
combined books balancing across the restart. Satellites: ``PageAllocator``
double-free/drift hardening and fragmentation edge cases, the extended
books identity (``submitted == terminal + queued + in_flight + parked``),
and the ``Gauge.peak`` high-water mark the LOAD artifact reads."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.generation import GenerationConfig, advance_rng_chain, make_decode_fns
from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.obs.loadgen import WorkloadSpec
from perceiver_io_tpu.serving import (
    EngineConfig,
    EngineCrash,
    EngineFrontEnd,
    FaultInjector,
    PageAllocator,
    RequestJournal,
)

NUM_LATENTS = 4
VOCAB = 64


@pytest.fixture(scope="module")
def model_and_params():
    config = CausalLanguageModelConfig(
        vocab_size=VOCAB, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    ids = np.random.default_rng(0).integers(0, VOCAB, size=(1, 12))
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids), prefix_len=8)
    return model, params


def _engine(model, params, base_config=None, *, headroom=1.0, eviction=False, **kw):
    # budgets <= 4 keep sa_tokens (num_latents + budget) within the gate
    # model's max_latents=8 — the no-slide bound eviction mode validates
    return EngineFrontEnd(
        model, params, num_latents=NUM_LATENTS, base_config=base_config,
        engine_config=EngineConfig(slots=4, page_size=8, max_ca_tokens=16,
                                   max_sa_tokens=8, pool_headroom=headroom,
                                   eviction=eviction),
        **kw,
    )


def _specs(n, seed=13):
    return WorkloadSpec(seed=seed, prompt_lens=(8, 12), max_new_tokens=(3, 4)).draw(n, VOCAB)


def _sequential_tokens(model, params, spec, base_config=None):
    cfg = dataclasses.replace(
        base_config or GenerationConfig(), max_new_tokens=spec.max_new_tokens
    )
    prefill, step = make_decode_fns(model, NUM_LATENTS, cfg)
    tok, state = prefill(
        params, jnp.asarray(spec.input_ids), None, jax.random.PRNGKey(spec.rng_seed)
    )
    out = [int(tok[0])]
    for _ in range(spec.max_new_tokens - 1):
        state, tok = step(state)
        out.append(int(tok[0]))
    return out


_SAMPLERS = {
    "greedy": lambda: GenerationConfig(),
    "temperature": lambda: GenerationConfig(do_sample=True, temperature=0.8, top_k=10),
}


# ------------------------------------------------------- rng-chain alignment


def test_advance_rng_chain_matches_manual_splits():
    """The resume seam's whole correctness argument in one pin: the chain
    position IS the emitted-token count — advancing n splits reproduces the
    key the uninterrupted run would hold before token n+1."""
    key = jax.random.PRNGKey(123)
    manual = key
    for n in range(6):
        assert np.array_equal(np.asarray(advance_rng_chain(key, n)), np.asarray(manual))
        manual, _ = jax.random.split(manual)
    assert np.array_equal(np.asarray(advance_rng_chain(key, 0)), np.asarray(key))


# ------------------------------------------- eviction with token-exact resume


@pytest.mark.parametrize("sampling", ["greedy", "temperature"])
def test_eviction_resume_token_exact(model_and_params, sampling):
    """A half-size page pool forces real evictions; every request still
    serves ``ok`` with ZERO sheds and every stream — the evicted-and-
    resumed ones included — equals the uninterrupted sequential reference
    exactly. The extended books identity closes and the pages come back."""
    model, params = model_and_params
    base = _SAMPLERS[sampling]()
    fe = _engine(model, params, base, headroom=0.5, eviction=True)
    specs = _specs(8)
    recs = fe.run_closed(specs, concurrency=8)
    books = fe.books()
    assert books["evictions"] >= 1, "pool never pressured — the test is vacuous"
    assert books["evictions"] == books["resumes"], books
    assert books["ok"] == 8 and books["shed"] == 0 and books["balanced"], books
    assert all(r.outcome == "ok" for r in recs)
    assert fe.audit() == []
    assert fe.ca_alloc.pages_used == 0 and fe.sa_alloc.pages_used == 0
    assert fe.ca_alloc.audit() == [] and fe.sa_alloc.audit() == []
    for spec in specs:
        want = _sequential_tokens(model, params, spec, base)
        assert fe.served_tokens[spec.index] == want, (
            f"request {spec.index} ({sampling}): {fe.served_tokens[spec.index]} != {want}"
        )


def test_eviction_disabled_is_pure_backpressure(model_and_params):
    """The same starved pool WITHOUT eviction: everything still serves (the
    pre-Evictline backpressure behavior), but nothing is ever preempted —
    the flag is the only difference."""
    model, params = model_and_params
    fe = _engine(model, params, headroom=0.5, eviction=False)
    recs = fe.run_closed(_specs(8), concurrency=8)
    books = fe.books()
    assert books["evictions"] == 0 and books["resumes"] == 0, books
    assert books["ok"] == 8 and books["balanced"], books


def test_eviction_requires_no_slide_geometry(model_and_params):
    """Eviction mode validates the no-slide window bound loudly at
    construction: the replay prefill rebuilds the victim's latents as
    prompt-tail latents, which a slid window cannot express."""
    model, params = model_and_params
    with pytest.raises(ValueError, match="never slide the window"):
        EngineFrontEnd(
            model, params, num_latents=NUM_LATENTS,
            engine_config=EngineConfig(slots=4, page_size=8, max_ca_tokens=16,
                                       max_sa_tokens=16, eviction=True),
        )


def test_parked_population_in_books_identity(model_and_params):
    """Mid-run, an evicted request sits in ``parked`` and the identity
    ``submitted == terminal + queued + in_flight + parked`` holds at every
    engine-step boundary (sampled via a step hook), not only after drain."""
    model, params = model_and_params
    fe = _engine(model, params, headroom=0.5, eviction=True)
    seen_parked = []
    orig = fe._engine_step

    def stepped():
        orig()
        b = fe.books()
        assert b["balanced"], b
        seen_parked.append(b["parked"])

    fe._engine_step = stepped
    fe.run_closed(_specs(8), concurrency=8)
    assert max(seen_parked) >= 1, "no request was ever observed parked"
    assert fe.books()["parked"] == 0  # drained clean
    # the parked-depth gauge's high-water mark saw it too (the LOAD
    # artifact's parked_depth_peak reads this)
    assert fe.registry.gauge("serve_parked_depth").peak >= 1


# ------------------------------------------------------------ crash recovery


@pytest.mark.parametrize("sampling", ["greedy", "temperature"])
def test_crash_recovery_token_exact_books_balanced(model_and_params, tmp_path, sampling):
    """The engine dies mid-decode (injected ``EngineCrash`` — a
    BaseException no accounting seam books, so in-flight slots freeze and
    no terminal records land); a second engine recovers every non-terminal
    request from the write-ahead journal and serves it token-exactly. The
    journal's cross-incarnation books balance: submitted == terminal."""
    model, params = model_and_params
    base = _SAMPLERS[sampling]()
    jpath = str(tmp_path / f"journal_{sampling}.jsonl")
    specs = _specs(6)
    fe1 = _engine(model, params, base, journal=jpath,
                  injector=FaultInjector().crash_at(2, 1))
    with pytest.raises(EngineCrash):
        fe1.run_closed(specs, concurrency=6)
    books1 = fe1.books()
    assert books1["terminal"] < books1["submitted"], books1

    journal = RequestJournal(jpath)
    owed = journal.pending()
    assert len(owed) == books1["submitted"] - books1["terminal"]
    assert any(e.tokens for e in owed), "nothing crashed mid-decode — vacuous"

    fe2 = _engine(model, params, base)
    info = fe2.recover(journal)
    assert info["recovered"] == len(owed)
    assert info["parked"] >= 1
    fe2.pump()
    books2 = fe2.books()
    assert books2["balanced"] and books2["parked"] == 0, books2
    assert books2["recovered"] == len(owed), books2
    assert fe2.audit() == []
    jb = journal.books()
    assert jb["balanced"] and jb["pending"] == 0, jb
    assert jb["submitted"] == 6 and jb["outcomes"] == {"ok": 6}, jb
    assert journal.audit() == []
    served = dict(fe1.served_tokens)
    served.update(fe2.served_tokens)
    for spec in specs:
        want = _sequential_tokens(model, params, spec, base)
        assert served.get(spec.index) == want, (
            f"request {spec.index} ({sampling}): {served.get(spec.index)} != {want}"
        )


def test_recover_books_complete_stream_without_replay(model_and_params, tmp_path):
    """A journal whose progress already covers the full budget (the crash
    landed in the emit-to-retire window) books terminal ``ok`` at recover
    time — nothing is re-decoded, nothing is parked."""
    model, params = model_and_params
    jpath = str(tmp_path / "journal_done.jsonl")
    spec = _specs(1)[0]
    j = RequestJournal(jpath)
    j.append("submitted", spec.index, prompt_len=spec.prompt_len,
             max_new_tokens=spec.max_new_tokens,
             input_ids=np.asarray(spec.input_ids).tolist(),
             rng_seed=spec.rng_seed, deadline_s=None)
    j.append("admitted", spec.index)
    full = _sequential_tokens(model, params, spec)
    j.append("progress", spec.index, tokens=full)
    fe = _engine(model, params)
    info = fe.recover(j)
    assert info == {"recovered": 1, "parked": 0, "queued": 0,
                    "already_complete": 1, "shed": 0, "skipped": 0}
    books = fe.books()
    assert books["ok"] == 1 and books["balanced"], books
    assert j.books()["balanced"]
    assert fe.served_tokens[spec.index] == full


def test_recover_is_idempotent_on_request_index(model_and_params, tmp_path):
    """Fleetline satellite (ISSUE 20): replay is IDEMPOTENT on request
    index — an index this engine already carries (queued, parked, or
    terminal) is deduped, so applying the same journal twice never
    double-admits. The second pass answers all-zeros except ``skipped``,
    the books don't move, and the streams still serve token-exact ONCE."""
    model, params = model_and_params
    jpath = str(tmp_path / "journal_idem.jsonl")
    specs = _specs(6)
    fe1 = _engine(model, params, journal=jpath,
                  injector=FaultInjector().crash_at(2, 1))
    with pytest.raises(EngineCrash):
        fe1.run_closed(specs, concurrency=6)
    journal = RequestJournal(jpath)
    owed = journal.pending()
    assert len(owed) >= 2, "crash too late — nothing left to dedupe"

    fe2 = _engine(model, params)
    first = fe2.recover(journal)
    assert first["recovered"] == len(owed) and first["skipped"] == 0
    submitted = fe2.books()["submitted"]
    # second pass BEFORE the replays drain: every still-pending index is
    # already carried (queued or parked) — deduped, nothing re-admitted
    # (the parked/queued depths in the summary are point-reads: unmoved)
    still_owed = journal.pending()  # already-complete ones booked terminal
    assert len(still_owed) == len(owed) - first["already_complete"]
    second = fe2.recover(journal)
    assert second == {"recovered": 0, "parked": first["parked"],
                      "queued": first["queued"], "already_complete": 0,
                      "shed": 0, "skipped": len(still_owed)}, second
    assert second["skipped"] >= 2, "nothing deduped — the test is vacuous"
    assert fe2.books()["submitted"] == submitted

    fe2.pump()
    books = fe2.books()
    assert books["balanced"] and books["parked"] == 0, books
    assert books["ok"] == len(owed), books
    # third pass AFTER the drain: the adopted journal's books are closed,
    # nothing pends — recover is a complete no-op
    third = fe2.recover(journal)
    assert third == {"recovered": 0, "parked": 0, "queued": 0,
                     "already_complete": 0, "shed": 0, "skipped": 0}, third
    assert fe2.audit() == []
    jb = journal.books()
    assert jb["balanced"] and jb["pending"] == 0, jb
    assert jb["submitted"] == 6 and jb["outcomes"] == {"ok": 6}, jb
    served = dict(fe1.served_tokens)
    served.update(fe2.served_tokens)
    for spec in specs:
        want = _sequential_tokens(model, params, spec)
        assert served.get(spec.index) == want, spec.index


def test_cancel_reaches_parked_request(model_and_params):
    """Review fix: ``fe.cancel()`` on a page-evicted (parked) request marks
    its ticket so the resume loop books terminal ``cancelled`` instead of
    burning a prefill replay for a caller who already hung up."""
    model, params = model_and_params
    fe = _engine(model, params, headroom=0.5, eviction=True)
    cancelled = []
    orig = fe._engine_step

    def stepped():
        orig()
        if not cancelled and fe._parked:
            idx = fe._parked[0].ticket.record.index
            assert fe.cancel(idx) is True
            cancelled.append(idx)

    fe._engine_step = stepped
    recs = fe.run_closed(_specs(8), concurrency=8)
    assert cancelled, "no request was ever parked — the test is vacuous"
    books = fe.books()
    assert books["balanced"] and books["parked"] == 0, books
    assert books["cancelled"] == 1 and books["ok"] == 7, books
    rec = next(r for r in recs if r.index == cancelled[0])
    assert rec.outcome == "cancelled"
    assert fe.audit(expect_drained=True) == []


def test_journal_requires_no_slide_geometry(model_and_params, tmp_path):
    """Review fix: a journal demands the no-slide replay geometry exactly
    like eviction mode — its whole purpose is token-exact crash recovery,
    which runs the same prefill replay. Loud at construction when
    ``journal=`` is passed, and again at ``recover()``, which can adopt a
    journal onto an engine built without one."""
    model, params = model_and_params
    sliding = EngineConfig(slots=2, page_size=8, max_ca_tokens=32, max_sa_tokens=8)
    with pytest.raises(ValueError, match="never slide"):
        EngineFrontEnd(model, params, num_latents=NUM_LATENTS,
                       engine_config=sliding, journal=str(tmp_path / "j.jsonl"))
    fe = EngineFrontEnd(model, params, num_latents=NUM_LATENTS,
                        engine_config=sliding)
    with pytest.raises(ValueError, match="never slide"):
        fe.recover(str(tmp_path / "j2.jsonl"))


def test_recover_skips_torn_submitted_entry(model_and_params, tmp_path):
    """Review fix: an entry whose ``submitted`` record was torn away
    mid-file (its progress rows intact) has no spec identity to rebuild —
    ``pending()`` excludes it so ``recover()`` re-admits the INTACT
    requests instead of dying on the broken one, and the loss surfaces as
    a journal audit problem."""
    model, params = model_and_params
    jpath = str(tmp_path / "torn.jsonl")
    specs = _specs(2)
    j = RequestJournal(jpath)
    for spec in specs:
        j.append("submitted", spec.index, prompt_len=spec.prompt_len,
                 max_new_tokens=spec.max_new_tokens,
                 input_ids=np.asarray(spec.input_ids).tolist(),
                 rng_seed=spec.rng_seed, deadline_s=None)
        j.append("admitted", spec.index)
    j.append("progress", specs[1].index, tokens=[5])
    with open(jpath) as f:
        lines = f.readlines()
    lines[0] = lines[0][: len(lines[0]) // 2] + "\n"  # tear spec 0's identity
    with open(jpath, "w") as f:
        f.writelines(lines)
    j2 = RequestJournal(jpath)
    assert [e.index for e in j2.pending()] == [specs[1].index]
    assert any("without a parseable submitted record" in p for p in j2.audit())
    fe = _engine(model, params)
    info = fe.recover(j2)
    assert info["recovered"] == 1 and info["parked"] == 1, info
    fe.pump()
    books = fe.books()
    assert books["ok"] == 1 and books["balanced"], books


def test_recover_sheds_unfit_request_instead_of_spinning(model_and_params, tmp_path):
    """Review fix: a journaled request THIS engine's window can never fit
    (the geometry shrank across the restart) is booked ``shed
    kv_pages_exhausted`` at recover time — re-queueing it would busy-spin
    the drive loops forever on a request no allocation can satisfy."""
    jpath = str(tmp_path / "journal.jsonl")
    model, params = model_and_params
    j = RequestJournal(jpath)
    # prompt 14 + budget 4 = 18 CA tokens: fits the dead engine's
    # max_ca_tokens=24 geometry, NOT this engine's 16
    j.append("submitted", 999, prompt_len=14, max_new_tokens=4,
             input_ids=[list(range(14))], rng_seed=7, deadline_s=None)
    j.append("admitted", 999)
    spec_ok = _specs(1)[0]
    j.append("submitted", spec_ok.index, prompt_len=spec_ok.prompt_len,
             max_new_tokens=spec_ok.max_new_tokens,
             input_ids=np.asarray(spec_ok.input_ids).tolist(),
             rng_seed=spec_ok.rng_seed, deadline_s=None)
    j.append("admitted", spec_ok.index)
    fe = _engine(model, params)
    info = fe.recover(j)
    assert info["shed"] == 1 and info["recovered"] == 1, info
    fe.pump()
    books = fe.books()
    assert books["balanced"] and books["shed"] == 1 and books["ok"] == 1, books
    jb = j.books()
    assert jb["balanced"] and jb["outcomes"] == {"shed": 1, "ok": 1}, jb
    shed_rec = next(r for r in fe.records if r.index == 999)
    assert shed_rec.outcome == "shed" and shed_rec.shed_reason == "kv_pages_exhausted"


def test_prefill_program_cache_is_bounded(model_and_params, monkeypatch):
    """Review fix: resume replay can hit a distinct (remaining, latents)
    point per eviction progress mark — the program cache is LRU-bounded so
    a long-lived engine cannot grow it without limit."""
    model, params = model_and_params
    fe = _engine(model, params)
    monkeypatch.setattr(type(fe), "_PREFILL_CACHE_MAX", 2)
    fe._prefill_fns.clear()
    a = fe._prefill_for(2)
    fe._prefill_for(3)
    assert fe._prefill_for(2) is a  # hit, LRU-touched to the tail
    fe._prefill_for(4)  # evicts (3, num_latents) — the least recent
    assert len(fe._prefill_fns) == 2
    assert (2, NUM_LATENTS) in fe._prefill_fns and (4, NUM_LATENTS) in fe._prefill_fns


def test_recover_span_carries_request_identity(model_and_params, tmp_path):
    """Review fix: the ``serve.recover`` span of a mid-decode recovered
    request carries the SAME ``request_id`` its terminal ``request`` row
    will (the parked slot mints it before the span opens) plus the durable
    ``request_index`` — a post-mortem joins the recover event to the
    request's subsequent lifecycle instead of finding two unrelated ids."""
    import json

    from perceiver_io_tpu.obs.events import EventLog

    model, params = model_and_params
    jpath = str(tmp_path / "journal.jsonl")
    specs = _specs(4)
    fe1 = _engine(model, params, journal=jpath,
                  injector=FaultInjector().crash_at(1, 1))
    with pytest.raises(EngineCrash):
        fe1.run_closed(specs, concurrency=4)
    run_dir = str(tmp_path / "run")
    events = EventLog(run_dir, main_process=True)
    fe2 = _engine(model, params, events=events)
    fe2.recover(jpath)
    fe2.pump()
    events.close()
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        rows = [json.loads(l) for l in f if l.strip()]
    spans = {r["span_id"]: r for r in rows if r.get("event") == "span"}
    recovers = [r for r in rows if r.get("event") == "serve.recover"
                and r.get("tokens_resumed", 0) > 0]
    resumes = {r.get("request_index"): r for r in rows
               if r.get("event") == "serve.resume"}
    request_ids = {r.get("request_id") for r in rows if r.get("event") == "request"}
    assert recovers, "nothing recovered mid-decode — the test is vacuous"
    for rec_row in recovers:
        span = spans[rec_row["span_id"]]
        idx = rec_row["request_index"]
        assert span["attrs"].get("request_index") == idx, span
        rid = span["attrs"].get("request_id")
        # the SAME identity rides the resume segment's span and the
        # terminal request row — one request_id across the whole lifecycle
        resume_span = spans[resumes[idx]["span_id"]]
        assert resume_span["attrs"].get("request_id") == rid, (span, resume_span)
        assert rid in request_ids, (rid, request_ids)


# ------------------------------------------------------------- journal unit


def test_journal_replay_books_and_torn_lines(tmp_path):
    """Replay folds progress records in order, ``pending`` is
    submitted-minus-terminal, books balance only when every submission
    terminated, a torn tail is tolerated on read, and a torn MID-file line
    is an audit problem, not a reader crash (the events.jsonl hygiene)."""
    jpath = str(tmp_path / "j.jsonl")
    j = RequestJournal(jpath)
    j.append("submitted", 0, prompt_len=4, max_new_tokens=3,
             input_ids=[[1, 2, 3, 4]], rng_seed=7, deadline_s=None)
    j.append("admitted", 0)
    j.append("progress", 0, tokens=[5])
    j.append("progress", 0, tokens=[6, 7])
    j.append("submitted", 1, prompt_len=4, max_new_tokens=2,
             input_ids=[[1, 2, 3, 4]], rng_seed=8, deadline_s=1.5)
    state = j.replay()
    assert state[0].tokens == [5, 6, 7] and state[1].tokens == []
    assert [e.index for e in j.pending()] == [0, 1]
    b = j.books()
    assert b["submitted"] == 2 and b["terminal"] == 0 and not b["balanced"]
    assert len(j.audit()) == 2  # two submitted-but-never-terminal problems
    j.append("terminal", 0, outcome="ok", tokens_out=3)
    j.append("terminal", 1, outcome="cancelled", tokens_out=0)
    b = j.books()
    assert b["balanced"] and b["outcomes"] == {"ok": 1, "cancelled": 1}
    assert j.audit() == []
    # the reconstructed spec round-trips the submission verbatim
    spec = j.replay()[1].spec()
    assert (spec.index, spec.prompt_len, spec.max_new_tokens, spec.rng_seed) == (1, 4, 2, 8)
    assert spec.input_ids.tolist() == [[1, 2, 3, 4]]
    # torn tail (the crash): tolerated by the reader, invisible to books
    with open(jpath, "a") as f:
        f.write('{"kind": "progress", "index": 0, "tok')
    assert j.books()["balanced"]
    # torn MID-file: still read around, but audit names the line
    lines = open(jpath).read().splitlines()
    lines.insert(2, '{"torn mid-file')
    with open(jpath, "w") as f:
        f.write("\n".join(lines) + "\n")
    assert j.books()["balanced"]  # reader survives
    assert any("unparseable mid-file" in p for p in j.audit())


def test_journal_rejects_unknown_kind_and_double_terminal(tmp_path):
    j = RequestJournal(str(tmp_path / "j2.jsonl"))
    with pytest.raises(ValueError, match="unknown journal record kind"):
        j.append("vanished", 0)
    j.append("submitted", 0, prompt_len=2, max_new_tokens=1,
             input_ids=[[1, 2]], rng_seed=1, deadline_s=None)
    j.append("terminal", 0, outcome="ok", tokens_out=1)
    j.append("terminal", 0, outcome="ok", tokens_out=1)
    assert any("2 terminal records" in p for p in j.audit())
    # a terminal with no submission is a books problem too
    j.append("terminal", 9, outcome="error")
    assert any("terminal without a submitted record" in p for p in j.audit())


# ------------------------------------------------- PageAllocator hardening


def test_allocator_double_free_rejected_with_audit_trail():
    """A double free raises AND leaves an audit entry — never silent
    free-list corruption: the free list and books are untouched, and a
    caller that swallowed the exception still can't hide the incident."""
    a = PageAllocator(num_pages=6, page_size=4)
    g = a.alloc_tokens(8)
    a.free(g)
    free_before = a.pages_free
    with pytest.raises(ValueError, match="double free"):
        a.free(g)
    assert a.pages_free == free_before  # free list NOT corrupted
    assert any("double free rejected" in p for p in a.audit())
    # page-ownership invariants still hold alongside the recorded violation
    assert not any("owned by grants" in p or "leaked" in p for p in a.audit())


def test_allocator_drifted_grant_rejected():
    """A grant handle whose pages disagree with the live books is refused
    wholesale (the books are authoritative) and recorded."""
    import dataclasses as _dc

    a = PageAllocator(num_pages=6, page_size=4)
    g = a.alloc_tokens(8)
    forged = _dc.replace(g, pages=(4,))
    with pytest.raises(ValueError, match="drifted"):
        a.free(forged)
    assert any("drifted free rejected" in p for p in a.audit())
    a.free(g)  # the honest handle still frees cleanly
    assert a.pages_used == 0


def test_allocator_audit_positive_and_negative():
    """audit() is empty for a clean allocator through a full alloc/free
    cycle, and names each planted corruption class."""
    a = PageAllocator(num_pages=8, page_size=2)
    grants = [a.alloc_tokens(3) for _ in range(3)]
    assert a.audit() == []
    for g in grants:
        a.free(g)
    assert a.audit() == [] and a.pages_used == 0
    # planted corruption (white-box): one page owned twice. Shared
    # ownership is legal under refcounting, so the corruption surfaces
    # as a refcount/appearance imbalance rather than as ownership per se.
    b = PageAllocator(num_pages=8, page_size=2)
    g1, g2 = b.alloc_tokens(2), b.alloc_tokens(2)
    b._grants[g2.grant_id]["pages"] = list(g1.pages)
    problems = b.audit()
    assert any("appearances (grants" in p for p in problems)
    assert any("leaked" in p for p in problems)  # g2's real page now unowned


def test_allocator_fragmentation_edge_cases():
    """Fragmentation accounting at the edges: an exact page-boundary grant
    has zero slack, n_tokens=0 is a loud error (a zero-page grant would be
    unfreeable), and a grant over ``num_allocatable`` is ``None`` from an
    EMPTY pool (can_ever_fit False — the admission shed test)."""
    a = PageAllocator(num_pages=5, page_size=4)  # 4 allocatable
    exact = a.alloc_tokens(8)  # exactly 2 pages
    assert exact.n_pages == 2 and exact.frag_tokens == 0
    ragged = a.alloc_tokens(5)  # 2 pages, 3 slack
    assert ragged.n_pages == 2 and ragged.frag_tokens == 3
    st = a.stats()
    assert st.internal_frag_tokens == 3 and st.tokens_reserved == 13
    with pytest.raises(ValueError, match="n_tokens >= 1"):
        a.alloc_tokens(0)
    a.free(exact)
    a.free(ragged)
    # over the whole pool: never fits, alloc answers None (not an exception)
    assert not a.can_ever_fit(4 * 4 + 1)
    assert a.alloc_tokens(4 * 4 + 1) is None
    assert a.pages_used == 0 and a.audit() == []
    # exactly the whole pool: fits an empty pool
    whole = a.alloc_tokens(16)
    assert whole is not None and whole.n_pages == 4
    assert not a.can_fit_now(1)
    a.free(whole)


# --------------------------------------------------------------- gauge peak


def test_gauge_peak_high_water_mark():
    """``Gauge.peak`` keeps the max over every write — the between-scrapes
    spike ``value`` alone cannot answer; None before the first write."""
    from perceiver_io_tpu.obs.metrics import Gauge

    g = Gauge("depth")
    assert g.peak is None
    g.set(2.0)
    g.set(5.0)
    g.set(1.0)
    assert g.value == 1.0 and g.peak == 5.0
    g.add(7.0)
    assert g.value == 8.0 and g.peak == 8.0
    # the measured-window boundary seam: reset_peak restarts the mark at
    # the CURRENT value (loadgen's warmup churn stops contaminating the
    # committed parked_depth_peak); a never-written gauge stays peak-less
    g.set(3.0)
    g.reset_peak()
    assert g.peak == 3.0
    g.set(4.0)
    assert g.peak == 4.0
    g2 = Gauge("untouched")
    g2.reset_peak()
    assert g2.peak is None


# ------------------------------------------------ journal survives frontend


def test_frontend_journals_submit_shed_and_terminal(model_and_params, tmp_path):
    """The write-ahead discipline on the engine front end: submitted lands
    BEFORE admission (a shed still closes its entry with a terminal
    record), served requests close through _finish — the journal balances
    whenever the books do."""
    from perceiver_io_tpu.obs.loadgen import RequestSpec

    model, params = model_and_params
    jpath = str(tmp_path / "fe.jsonl")
    fe = _engine(model, params, journal=jpath)
    specs = _specs(3)
    # an impossible request: sheds kv_pages_exhausted at admission
    rng = np.random.default_rng(3)
    impossible = RequestSpec(index=99, prompt_len=20, max_new_tokens=16,
                             input_ids=rng.integers(0, VOCAB, size=(1, 20)),
                             rng_seed=7)
    fe.run_closed(list(specs) + [impossible], concurrency=4)
    j = RequestJournal(jpath)
    jb = j.books()
    assert jb["submitted"] == 4 and jb["balanced"], jb
    assert jb["outcomes"] == {"ok": 3, "shed": 1}, jb
    assert j.audit() == []
    shed_row = [r for r in j.rows()
                if r["kind"] == "terminal" and r["index"] == 99]
    assert shed_row[0]["shed_reason"] == "kv_pages_exhausted"
