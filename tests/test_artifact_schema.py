"""Committed-artifact schema pins: BENCH_*.json, contracts/*.json and
contracts/ledger.json must stay machine-readable — the re-anchor reviewer,
the bench-floor gate and graphcheck all parse them, and a malformed artifact
should fail tier-1 here instead of confusing the next round."""

import glob
import json
import os
import re

import pytest

from perceiver_io_tpu.analysis.fingerprint import PROGRAMS, validate_contract
from perceiver_io_tpu.analysis.ledger import validate_ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONTRACTS = os.path.join(REPO, "contracts")

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _rounds(pattern):
    out = {}
    for path in sorted(glob.glob(os.path.join(REPO, pattern))):
        m = _ROUND_RE.search(path)
        assert m, f"{os.path.basename(path)} must end in _r<round>.json"
        out[int(m.group(1))] = path
    return out


def test_bench_rounds_monotone_and_well_formed():
    rounds = _rounds("BENCH_r*.json")
    assert rounds, "no BENCH_r*.json artifacts committed"
    # contiguous monotone numbering from round 1: a skipped or duplicated
    # round breaks the floor gate's latest-artifact resolution
    assert sorted(rounds) == list(range(1, max(rounds) + 1)), sorted(rounds)
    for n, path in rounds.items():
        doc = json.load(open(path))
        base = os.path.basename(path)
        for key, typ in (("n", int), ("cmd", str), ("rc", int), ("tail", str)):
            assert isinstance(doc.get(key), typ), f"{base}: {key} must be {typ.__name__}"
        assert doc["n"] == n, f"{base}: field n={doc['n']} != filename round {n}"
        if doc.get("parsed") is not None:
            parsed = doc["parsed"]
            assert isinstance(parsed.get("metric"), str), base
            assert isinstance(parsed.get("value"), (int, float)), base
            assert isinstance(parsed.get("unit"), str), base


def test_bench_extra_rounds_well_formed():
    rounds = _rounds("BENCH_extra_r*.json")
    for n, path in rounds.items():
        base = os.path.basename(path)
        doc = json.load(open(path))
        assert isinstance(doc, dict) and doc, base
        for name, entry in doc.items():
            assert isinstance(entry, dict), f"{base}:{name}"
            assert isinstance(entry.get("metric"), str), f"{base}:{name}"
            assert isinstance(entry.get("value"), (int, float)), f"{base}:{name}"
            assert isinstance(entry.get("unit"), str), f"{base}:{name}"


def test_contract_files_validate_against_schema():
    paths = sorted(glob.glob(os.path.join(CONTRACTS, "*.json")))
    # ledger.json and hostlint_allow.json are contracts of a different
    # shape, schema-pinned by their own tests below
    program_files = [
        p for p in paths
        if os.path.basename(p) not in ("ledger.json", "hostlint_allow.json")
    ]
    assert program_files, "no program contracts committed under contracts/"
    seen = set()
    for path in program_files:
        base = os.path.basename(path)
        doc = json.load(open(path))
        problems = validate_contract(doc)
        assert problems == [], f"{base}: {problems}"
        stem = base[: -len(".json")]
        assert doc["program"] == stem, f"{base}: program field must match filename"
        assert stem in PROGRAMS, f"{base}: unknown program (known: {PROGRAMS})"
        assert doc["updated_reason"].strip(), f"{base}: empty updated_reason"
        seen.add(stem)
    # every flagship program is under contract — a dropped file would
    # silently shrink the gate
    assert seen == set(PROGRAMS), f"contracts cover {sorted(seen)}, want {sorted(PROGRAMS)}"


def test_ledger_validates_and_cites_existing_artifacts():
    doc = json.load(open(os.path.join(CONTRACTS, "ledger.json")))
    assert validate_ledger(doc) == []
    for name, floor in doc.get("floors", {}).items():
        assert glob.glob(os.path.join(REPO, floor["artifact"])), (
            f"floor {name} cites artifact pattern {floor['artifact']!r} with no match"
        )


def test_elastic_resume_event_kinds_pinned(tmp_path):
    """The elastic-resume vocabulary (ISSUE 10): ``resume.reshard`` and
    ``fault.ckpt_retry`` are KNOWN kinds with required-field enforcement —
    a reshard event missing its old/new mesh (or a retry event missing its
    attempt/delay) fails validation instead of silently confusing
    obs_report/obs_diff."""
    from perceiver_io_tpu.obs.events import (
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        KNOWN_EVENT_KINDS,
        validate_events,
    )

    assert "resume.reshard" in KNOWN_EVENT_KINDS
    assert "fault.ckpt_retry" in KNOWN_EVENT_KINDS
    assert set(_REQUIRED_FIELDS["resume.reshard"]) == {"old_mesh", "new_mesh", "step"}
    assert set(_REQUIRED_FIELDS["fault.ckpt_retry"]) == {"attempt", "delay_s"}

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    good = write_stream(
        [
            {"event": "resume.reshard", "step": 5, "old_mesh": {"data": 2, "fsdp": 4},
             "new_mesh": {"data": 2, "fsdp": 2}, "leaves_resharded": 6, "bytes_moved": 400},
            {"event": "fault.ckpt_retry", "attempt": 0, "delay_s": 0.2, "op": "save"},
        ]
    )
    assert validate_events(good, strict_spans=False) == []
    # missing required fields fail loudly, and neither kind warns as unknown
    bad = write_stream([{"event": "resume.reshard", "step": 5}, {"event": "fault.ckpt_retry"}])
    warnings_out = []
    problems = validate_events(bad, strict_spans=False, warnings_out=warnings_out)
    assert any("old_mesh" in p for p in problems) and any("new_mesh" in p for p in problems)
    assert any("attempt" in p for p in problems) and any("delay_s" in p for p in problems)
    assert warnings_out == []


def test_serving_observability_event_kinds_pinned(tmp_path):
    """The Loadline vocabulary (ISSUE 11): ``load.summary`` and
    ``flight.dump`` are KNOWN kinds with required-field enforcement — a
    summary missing its achieved rate, or a dump event that doesn't name
    the triggering span, fails validation instead of silently confusing
    obs_report/obs_diff/the post-mortem reader. Queue-wait fields ride the
    (already-required) ``request`` rows as optional admission telemetry."""
    from perceiver_io_tpu.obs.events import (
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        KNOWN_EVENT_KINDS,
        validate_events,
    )

    assert "load.summary" in KNOWN_EVENT_KINDS
    assert "flight.dump" in KNOWN_EVENT_KINDS
    assert set(_REQUIRED_FIELDS["load.summary"]) == {"mode", "n_requests", "achieved_rps"}
    assert set(_REQUIRED_FIELDS["flight.dump"]) == {
        "trigger", "path", "n_events", "trigger_span_id",
    }
    # queue-wait is NOT required on request rows: only loadgen-issued
    # requests carry admission telemetry
    assert "queue_wait_s" not in _REQUIRED_FIELDS["request"]

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    good = write_stream(
        [
            {"event": "load.summary", "mode": "closed", "n_requests": 200,
             "achieved_rps": 34.8, "throughput_tok_s": 280.9, "error_rate": 0.0},
            {"event": "flight.dump", "trigger": "slo_ttft", "path": "flight-slo_ttft-1.json",
             "n_events": 12, "trigger_span_id": "abc123", "seq": 1},
        ]
    )
    warnings_out = []
    assert validate_events(good, strict_spans=False, warnings_out=warnings_out) == []
    assert warnings_out == []  # neither kind warns as unknown
    bad = write_stream([{"event": "load.summary", "mode": "closed"},
                        {"event": "flight.dump", "trigger": "error"}])
    problems = validate_events(bad, strict_spans=False)
    assert any("achieved_rps" in p for p in problems)
    assert any("trigger_span_id" in p for p in problems)


def test_serving_hardening_event_kinds_and_outcomes_pinned(tmp_path):
    """The Shedline vocabulary (ISSUE 12): ``serve.breaker`` /
    ``serve.retry`` / ``serve.drain`` are KNOWN kinds with required-field
    enforcement, and the ``request`` outcome field is validated against the
    CLOSED taxonomy — a missing outcome fails, an unknown one only warns
    (forward compatibility), so shed/timeout accounting can never silently
    drift under older tooling."""
    from perceiver_io_tpu.obs.events import (
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        KNOWN_EVENT_KINDS,
        REQUEST_OUTCOMES,
        validate_events,
    )

    assert REQUEST_OUTCOMES == {"ok", "error", "timeout", "shed", "cancelled"}
    for kind in ("serve.breaker", "serve.retry", "serve.drain", "serve.preempt"):
        assert kind in KNOWN_EVENT_KINDS, kind
    assert set(_REQUIRED_FIELDS["serve.breaker"]) == {"state", "prev", "reason"}
    assert set(_REQUIRED_FIELDS["serve.retry"]) == {"attempt", "delay_s"}
    assert set(_REQUIRED_FIELDS["serve.drain"]) == {"books"}
    assert "outcome" in _REQUIRED_FIELDS["request"]  # missing outcome FAILS

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    req = {"event": "request", "request_id": "r", "batch": 1, "prompt_len": 8,
           "ttft_s": 0.0, "tokens_out": 0}
    good = write_stream(
        [
            {"event": "serve.breaker", "state": "open", "prev": "closed",
             "reason": "error-rate", "error_rate": 0.5},
            {"event": "serve.retry", "attempt": 0, "delay_s": 0.01, "error": "x"},
            {"event": "serve.drain", "finished": 3, "books": {"balanced": True}},
            *({**req, "outcome": o} for o in sorted(REQUEST_OUTCOMES)),
        ]
    )
    warnings_out = []
    assert validate_events(good, strict_spans=False, warnings_out=warnings_out) == []
    assert warnings_out == []  # every closed-vocabulary outcome passes silently

    # unknown outcome: warning, never a problem (a newer taxonomy must not
    # fail an older gate); non-string outcome: a problem
    odd = write_stream([{**req, "outcome": "evicted"}, {**req, "outcome": 3}])
    warnings_out = []
    problems = validate_events(odd, strict_spans=False, warnings_out=warnings_out)
    assert any("not a string" in p for p in problems) and len(problems) == 1
    assert len(warnings_out) == 1 and "evicted" in warnings_out[0]

    # missing outcome / missing required serve.* fields: hard failures
    bad = write_stream([
        {k: v for k, v in {**req, "outcome": "ok"}.items() if k != "outcome"},
        {"event": "serve.breaker", "state": "open"},
        {"event": "serve.drain", "finished": 1},
    ])
    problems = validate_events(bad, strict_spans=False)
    assert any("[request]: missing field 'outcome'" in p for p in problems)
    assert any("[serve.breaker]: missing field 'prev'" in p for p in problems)
    assert any("[serve.drain]: missing field 'books'" in p for p in problems)


def test_engine_event_vocabulary_pinned(tmp_path):
    """The Pageline vocabulary (ISSUE 13): ``kv_pages_exhausted`` is a
    first-class shed reason, and ``batch_size_at_decode`` is an OPTIONAL
    request-row field — a row carrying either validates with zero problems
    and zero forward-compat warnings, and neither is required (older
    streams without them stay valid), so the engine's telemetry is
    forward-compatible by construction."""
    from perceiver_io_tpu.obs.events import (
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        validate_events,
    )
    from perceiver_io_tpu.serving import SHED_REASONS

    assert "kv_pages_exhausted" in SHED_REASONS
    # forward-compat: the new fields must NOT be required on request rows
    assert "batch_size_at_decode" not in _REQUIRED_FIELDS["request"]
    assert "shed_reason" not in _REQUIRED_FIELDS["request"]

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    req = {"event": "request", "request_id": "r", "batch": 1, "prompt_len": 8,
           "ttft_s": 0.0, "tokens_out": 0}
    good = write_stream(
        [
            {**req, "outcome": "shed", "shed_reason": "kv_pages_exhausted"},
            {**req, "outcome": "ok", "tokens_out": 6, "batch_size_at_decode": 3.5,
             "queue_wait_s": 0.01},
        ]
    )
    warnings_out = []
    assert validate_events(good, strict_spans=False, warnings_out=warnings_out) == []
    assert warnings_out == []
    # rows WITHOUT the engine fields stay valid (older streams)
    old = write_stream([{**req, "outcome": "ok"}])
    assert validate_events(old, strict_spans=False) == []


def test_ledger_floor_ceilings_supported():
    """Ledger floors support ``max`` ceilings (ISSUE 13: the engine p99-TPOT
    ceiling rides one) alongside ``min`` floors; an entry with neither is
    invalid."""
    base = {"schema_version": 1, "features": {}}
    ok = {**base, "floors": {
        "f1": {"artifact": "X_r*.json", "key": "a.b", "min": 1.0},
        "f2": {"artifact": "X_r*.json", "key": "a.c", "max": 0.5},
        "f3": {"artifact": "X_r*.json", "key": "a.d", "min": 0, "max": 2},
    }}
    assert validate_ledger(ok) == []
    bad = {**base, "floors": {"f": {"artifact": "X_r*.json", "key": "a"}}}
    assert any("min and/or max" in p for p in validate_ledger(bad))
    # the committed ledger actually USES a ceiling for the engine tail
    doc = json.load(open(os.path.join(CONTRACTS, "ledger.json")))
    assert "max" in doc["floors"]["engine_tpot_p99_s"]
    assert "min" in doc["floors"]["engine_throughput_tok_s"]


def test_load_rounds_monotone_and_well_formed():
    """LOAD_r*.json — the committed serving-load artifacts (ISSUE 11):
    contiguous round numbering and the machine-read surface the load gate's
    floors and diff_load parse (keys, types, percentile blocks)."""
    rounds = _rounds("LOAD_r*.json")
    assert rounds, "no LOAD_r*.json artifacts committed"
    assert sorted(rounds) == list(range(1, max(rounds) + 1)), sorted(rounds)
    for n, path in rounds.items():
        base = os.path.basename(path)
        doc = json.load(open(path))
        assert doc.get("n") == n, f"{base}: field n={doc.get('n')} != filename round {n}"
        assert isinstance(doc.get("schema_version"), int), base
        assert doc.get("mode") in ("closed", "open"), base
        workload = doc.get("workload")
        assert isinstance(workload, dict) and isinstance(workload.get("spec"), dict), base
        assert isinstance(doc.get("manifest"), dict), base
        summary = doc.get("summary")
        assert isinstance(summary, dict), base
        for key, typ in (
            ("n_requests", int), ("achieved_rps", (int, float)),
            ("throughput_tok_s", (int, float)), ("error_rate", (int, float)),
            ("ok_rate", (int, float)), ("duration_s", (int, float)),
        ):
            assert isinstance(summary.get(key), typ), f"{base}: summary.{key}"
        for fam in ("ttft_s", "queue_wait_s"):
            block = summary.get(fam)
            assert isinstance(block, dict), f"{base}: summary.{fam}"
            for p in ("p50", "p99"):
                assert isinstance(block.get(p), (int, float)), f"{base}: summary.{fam}.{p}"
        assert isinstance(summary.get("breakdown_ms"), dict), base
        # warm-only percentiles are the committed contract — a cold-only
        # artifact has no steady state worth diffing
        assert summary.get("warm_only") is True, base


def test_smoke_fit_event_stream_validates(tmp_path):
    """The event stream a real (tiny) fit writes must pass validate_events —
    the runtime analog of the BENCH_* pins above: silent schema drift in
    events.jsonl fails tier-1 here instead of confusing obs_report/obs_diff
    (and the re-anchor reviewer) a round later."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
    from perceiver_io_tpu.obs.events import EVENT_SCHEMA_VERSION, merged_events, validate_events
    from perceiver_io_tpu.training import (
        MetricsLogger,
        TrainState,
        Trainer,
        TrainerConfig,
        clm_loss_fn,
        make_optimizer,
    )

    config = CausalLanguageModelConfig(
        vocab_size=50, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    t = np.random.default_rng(0).integers(0, 50, size=(4, config.max_seq_len + 1))
    batch = {"labels": jnp.asarray(t[:, 1:]), "input_ids": jnp.asarray(t[:, :-1]),
             "pad_mask": None}
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=16)
    state = TrainState.create(model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1))
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(
        clm_loss_fn(model.apply, max_latents=config.max_latents),
        logger=logger,
        config=TrainerConfig(max_steps=3, log_interval=2, prefetch_batches=0),
    )
    trainer.fit(state, iter([batch] * 3), model_config=config)
    trainer.close()
    logger.close()

    assert validate_events(str(tmp_path)) == [], "smoke-fit event stream drifted"
    events = merged_events(str(tmp_path))
    assert all(e["schema_version"] == EVENT_SCHEMA_VERSION for e in events)
    kinds = {e["event"] for e in events}
    assert {"fit_start", "log", "compile", "span", "fit_end"} <= kinds


def test_speculative_event_fields_and_artifacts_pinned(tmp_path):
    """The Specline vocabulary (ISSUE 14): ``acceptance_rate`` and
    ``tokens_per_step`` are OPTIONAL request-row fields VALIDATED when
    present (numeric — a malformed value is a problem, absence is not:
    mirroring ``batch_size_at_decode``), the ``speculative`` feature stands
    measured in the ledger with its tokens-per-step floor, and the
    committed BENCH_extra round's ``decode_spec`` entry records a
    serial-step multiple above 1.0 (the acceptance criterion)."""
    from perceiver_io_tpu.analysis.ledger import feature_state, load_ledger
    from perceiver_io_tpu.obs.events import (
        _OPTIONAL_FIELD_TYPES,
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        validate_events,
    )

    for field in ("acceptance_rate", "tokens_per_step", "batch_size_at_decode"):
        assert field in _OPTIONAL_FIELD_TYPES["request"], field
        assert field not in _REQUIRED_FIELDS["request"], field

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    req = {"event": "request", "request_id": "r", "batch": 1, "prompt_len": 8,
           "ttft_s": 0.0, "tokens_out": 6, "outcome": "ok"}
    good = write_stream(
        [
            {**req, "acceptance_rate": 0.45, "tokens_per_step": 2.2},
            req,  # rows WITHOUT the fields stay valid (older streams)
        ]
    )
    warnings_out = []
    assert validate_events(good, strict_spans=False, warnings_out=warnings_out) == []
    assert warnings_out == []
    bad = write_stream([{**req, "acceptance_rate": "high", "tokens_per_step": None}])
    problems = validate_events(bad, strict_spans=False)
    assert any("acceptance_rate" in p for p in problems), problems
    assert any("tokens_per_step" in p for p in problems), problems
    # bool is an int subclass — it must NOT pass the numeric check
    booly = write_stream([{**req, "acceptance_rate": True, "tokens_per_step": False}])
    problems = validate_events(booly, strict_spans=False)
    assert any("acceptance_rate" in p for p in problems), problems
    assert any("tokens_per_step" in p for p in problems), problems

    ledger = load_ledger(CONTRACTS)
    assert feature_state(ledger, "speculative") == "measured"
    assert "spec_tokens_per_step" in ledger["floors"]

    rounds = _rounds("BENCH_extra_r*.json")
    latest = json.load(open(rounds[max(rounds)]))
    spec = latest["decode_spec"]
    assert spec["tokens_per_step"] > 1.0, spec
    assert 0.0 <= spec["acceptance_rate"] <= 1.0, spec
    assert spec.get("token_exact") is True, spec


def test_evictline_event_vocabulary_pinned(tmp_path):
    """The Evictline vocabulary (ISSUE 15): ``serve.evict`` /
    ``serve.resume`` / ``serve.recover`` are KNOWN kinds with
    required-field enforcement, kept DISTINCT from ``serve.preempt`` (the
    SIGTERM/drain signal — whole-process wind-down; the three new kinds are
    per-REQUEST preemption: page-evicted, replay-resumed, journal-
    recovered), and the engine leg's eviction telemetry on ``load.summary``
    (``evictions`` / ``resumes`` / ``parked_depth_peak``) is OPTIONAL and
    numeric-validated when present — missing fields on the new kinds fail
    hard, an unknown sibling kind only warns (forward compatibility)."""
    from perceiver_io_tpu.obs.events import (
        _OPTIONAL_FIELD_TYPES,
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        KNOWN_EVENT_KINDS,
        validate_events,
    )

    # the whole preemption vocabulary, pinned as a SET so the two meanings
    # (process drain vs per-request eviction) can't blur: serve.preempt
    # stays a known kind with NO required fields (it predates the table),
    # the three Evictline kinds carry their consumed schemas
    for kind in ("serve.preempt", "serve.evict", "serve.resume", "serve.recover"):
        assert kind in KNOWN_EVENT_KINDS, kind
    assert "serve.preempt" not in _REQUIRED_FIELDS  # the drain signal, unchanged
    assert set(_REQUIRED_FIELDS["serve.evict"]) == {
        "request_index", "tokens_out", "pages_freed"
    }
    assert set(_REQUIRED_FIELDS["serve.resume"]) == {"request_index", "tokens_out"}
    assert set(_REQUIRED_FIELDS["serve.recover"]) == {"request_index", "tokens_resumed"}
    for field in ("evictions", "resumes", "parked_depth_peak"):
        assert field in _OPTIONAL_FIELD_TYPES["load.summary"], field
        assert field not in _REQUIRED_FIELDS["load.summary"], field

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    summary = {"event": "load.summary", "mode": "closed", "n_requests": 8,
               "achieved_rps": 100.0}
    good = write_stream(
        [
            {"event": "serve.evict", "request_index": 3, "tokens_out": 2,
             "pages_freed": 3},
            {"event": "serve.resume", "request_index": 3, "tokens_out": 2},
            {"event": "serve.recover", "request_index": 3, "tokens_resumed": 2},
            {**summary, "evictions": 6, "resumes": 6, "parked_depth_peak": 2},
            summary,  # pre-Evictline summaries (no counters) stay valid
        ]
    )
    warnings_out = []
    assert validate_events(good, strict_spans=False, warnings_out=warnings_out) == []
    assert warnings_out == []

    # missing required fields on the new kinds: hard failures
    bad = write_stream([
        {"event": "serve.evict", "request_index": 3},
        {"event": "serve.resume", "tokens_out": 2},
        {"event": "serve.recover", "request_index": 3},
    ])
    problems = validate_events(bad, strict_spans=False)
    assert any("[serve.evict]: missing field 'tokens_out'" in p for p in problems)
    assert any("[serve.evict]: missing field 'pages_freed'" in p for p in problems)
    assert any("[serve.resume]: missing field 'request_index'" in p for p in problems)
    assert any("[serve.recover]: missing field 'tokens_resumed'" in p for p in problems)

    # malformed optional counters: problems; an unknown sibling kind from a
    # NEWER library: a warning, never a problem (forward compatibility)
    odd = write_stream([
        {**summary, "evictions": "many", "parked_depth_peak": True},
        {"event": "serve.evict2", "request_index": 1},
    ])
    warnings_out = []
    problems = validate_events(odd, strict_spans=False, warnings_out=warnings_out)
    assert any("evictions" in p for p in problems), problems
    assert any("parked_depth_peak" in p for p in problems), problems
    assert not any("serve.evict2" in p for p in problems), problems
    assert len(warnings_out) == 1 and "serve.evict2" in warnings_out[0]


def test_sim_event_vocabulary_and_tenant_pinned(tmp_path):
    """The Simline vocabulary (ISSUE 16): ``sim.summary`` is a KNOWN kind
    with required-field enforcement, and ``tenant`` is an OPTIONAL
    string-typed field on request rows and the per-request preemption
    audit trail (serve.evict/serve.resume/serve.recover) — absent it stays
    valid (single-tenant streams), present-but-non-string fails loudly."""
    from perceiver_io_tpu.obs.events import (
        _OPTIONAL_FIELD_TYPES,
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        KNOWN_EVENT_KINDS,
        validate_events,
    )

    assert "sim.summary" in KNOWN_EVENT_KINDS
    assert set(_REQUIRED_FIELDS["sim.summary"]) == {
        "n_requests", "n_tenants", "offered_rps", "achieved_rps",
        "fairness_jain", "max_starvation_age_s",
    }
    # forward-compat: tenant is never required, and is type-pinned to str
    assert "tenant" not in _REQUIRED_FIELDS["request"]
    for kind in ("request", "serve.evict", "serve.resume", "serve.recover"):
        assert _OPTIONAL_FIELD_TYPES[kind]["tenant"] == (str,), kind

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    req = {"event": "request", "request_id": "r", "batch": 1, "prompt_len": 8,
           "ttft_s": 0.0, "tokens_out": 4, "outcome": "ok"}
    good = write_stream(
        [
            {"event": "sim.summary", "n_requests": 12000, "n_tenants": 3,
             "offered_rps": 10000.0, "achieved_rps": 1428.1,
             "fairness_jain": 0.9978, "max_starvation_age_s": 0.2,
             "shed_rate": 0.83, "books_balanced": True},
            {**req, "tenant": "acme"},
            req,  # tenant-free rows stay valid (older / single-tenant streams)
            {"event": "serve.evict", "request_index": 4, "tokens_out": 2,
             "pages_freed": 3, "tenant": "acme"},
            {"event": "serve.resume", "request_index": 4, "tokens_out": 2,
             "tenant": "acme"},
        ]
    )
    warnings_out = []
    assert validate_events(good, strict_spans=False, warnings_out=warnings_out) == []
    assert warnings_out == []  # sim.summary never warns as unknown
    bad = write_stream([
        {"event": "sim.summary", "n_requests": 10},
        {**req, "tenant": 7},
    ])
    problems = validate_events(bad, strict_spans=False)
    assert any("fairness_jain" in p for p in problems)
    assert any("tenant" in p and "string" in p for p in problems)


def test_shareline_event_vocabulary_pinned(tmp_path):
    """The Shareline vocabulary (ISSUE 17): ``serve.prefix_hit`` is a KNOWN
    kind requiring ``request_index`` / ``pages_matched`` / ``pages_total``
    (the hit's shape — what fraction of the prompt came for free), with
    ``tenant`` and ``tokens_skipped`` optional-and-typed, and the prefix leg
    of ``load.summary`` rides an optional ``prefix`` dict — pre-Shareline
    streams stay valid, missing required fields fail hard."""
    from perceiver_io_tpu.obs.events import (
        _OPTIONAL_FIELD_TYPES,
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        KNOWN_EVENT_KINDS,
        validate_events,
    )

    assert "serve.prefix_hit" in KNOWN_EVENT_KINDS
    assert set(_REQUIRED_FIELDS["serve.prefix_hit"]) == {
        "request_index", "pages_matched", "pages_total"
    }
    assert _OPTIONAL_FIELD_TYPES["serve.prefix_hit"]["tenant"] == (str,)
    assert "tokens_skipped" in _OPTIONAL_FIELD_TYPES["serve.prefix_hit"]
    assert "tokens_skipped" not in _REQUIRED_FIELDS["serve.prefix_hit"]
    assert _OPTIONAL_FIELD_TYPES["load.summary"]["prefix"] == (dict,)
    assert "prefix" not in _REQUIRED_FIELDS["load.summary"]

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    summary = {"event": "load.summary", "mode": "closed", "n_requests": 200,
               "achieved_rps": 100.0}
    good = write_stream(
        [
            {"event": "serve.prefix_hit", "request_index": 7,
             "pages_matched": 55, "pages_total": 56,
             "tokens_skipped": 440, "tenant": "acme"},
            {"event": "serve.prefix_hit", "request_index": 8,
             "pages_matched": 1, "pages_total": 2},  # bare hit stays valid
            {**summary, "prefix": {"hit_rate": 0.995, "ttft_p50_ratio": 0.38}},
            summary,  # pre-Shareline summaries (no prefix block) stay valid
        ]
    )
    warnings_out = []
    assert validate_events(good, strict_spans=False, warnings_out=warnings_out) == []
    assert warnings_out == []
    bad = write_stream([
        {"event": "serve.prefix_hit", "request_index": 7},
        {"event": "serve.prefix_hit", "pages_matched": 1, "pages_total": 2,
         "tenant": 9},
        {**summary, "prefix": 0.995},
    ])
    problems = validate_events(bad, strict_spans=False)
    assert any("[serve.prefix_hit]: missing field 'pages_matched'" in p for p in problems)
    assert any("[serve.prefix_hit]: missing field 'pages_total'" in p for p in problems)
    assert any("[serve.prefix_hit]: missing field 'request_index'" in p for p in problems)
    assert any("tenant" in p for p in problems), problems
    assert any("prefix" in p for p in problems), problems


def test_fleet_event_vocabulary_pinned(tmp_path):
    """The Fleetline vocabulary (ISSUE 20): ``serve.replica`` (replica
    lifecycle transitions on the fleet router) and ``serve.failover`` (a
    dead replica's journal replayed onto a survivor) are KNOWN kinds with
    required-field enforcement — the failover row carries the replay
    accounting the post-mortem reads (``n_replayed`` required; the parked/
    queued/already-complete/shed split and the dead journal's path optional
    and type-pinned). Minimal transition rows stay valid (``reason`` and
    ``outstanding`` are optional), missing required fields fail hard."""
    from perceiver_io_tpu.obs.events import (
        _OPTIONAL_FIELD_TYPES,
        _REQUIRED_FIELDS,
        EVENT_SCHEMA_VERSION,
        KNOWN_EVENT_KINDS,
        validate_events,
    )

    for kind in ("serve.replica", "serve.failover"):
        assert kind in KNOWN_EVENT_KINDS, kind
    assert set(_REQUIRED_FIELDS["serve.replica"]) == {"replica_id", "transition"}
    assert set(_REQUIRED_FIELDS["serve.failover"]) == {
        "dead_replica", "survivor", "n_replayed"
    }
    assert _OPTIONAL_FIELD_TYPES["serve.replica"]["reason"] == (str,)
    assert "outstanding" in _OPTIONAL_FIELD_TYPES["serve.replica"]
    for field in ("n_parked", "n_queued", "n_already_complete", "n_shed"):
        assert field in _OPTIONAL_FIELD_TYPES["serve.failover"], field
        assert field not in _REQUIRED_FIELDS["serve.failover"], field
    assert _OPTIONAL_FIELD_TYPES["serve.failover"]["journal"] == (str,)

    def write_stream(rows):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({"ts": 1.0, "schema_version": EVENT_SCHEMA_VERSION, **row}) + "\n")
        return str(path)

    good = write_stream(
        [
            {"event": "serve.replica", "replica_id": "r0", "transition": "join"},
            {"event": "serve.replica", "replica_id": "r0", "transition": "dead",
             "reason": "heartbeat_timeout", "outstanding": 3},
            {"event": "serve.failover", "dead_replica": "r0", "survivor": "r1",
             "n_replayed": 5, "n_parked": 2, "n_queued": 3,
             "n_already_complete": 0, "n_shed": 0,
             "journal": "runs/journal-r0.jsonl"},
            # a minimal failover row (no optional accounting) stays valid
            {"event": "serve.failover", "dead_replica": "r0", "survivor": "r1",
             "n_replayed": 0},
        ]
    )
    warnings_out = []
    assert validate_events(good, strict_spans=False, warnings_out=warnings_out) == []
    assert warnings_out == []

    # missing required fields: hard failures; malformed optionals: problems
    bad = write_stream([
        {"event": "serve.replica", "replica_id": "r0"},
        {"event": "serve.replica", "transition": "join", "reason": 7},
        {"event": "serve.failover", "dead_replica": "r0", "survivor": "r1"},
        {"event": "serve.failover", "dead_replica": "r0", "survivor": "r1",
         "n_replayed": 5, "n_parked": "two", "journal": 9},
    ])
    problems = validate_events(bad, strict_spans=False)
    assert any("[serve.replica]: missing field 'transition'" in p for p in problems)
    assert any("[serve.replica]: missing field 'replica_id'" in p for p in problems)
    assert any("[serve.failover]: missing field 'n_replayed'" in p for p in problems)
    assert any("reason" in p for p in problems), problems
    assert any("n_parked" in p for p in problems), problems
    assert any("journal" in p for p in problems), problems


def test_sim_rounds_monotone_and_well_formed():
    """SIM_r*.json — the committed discrete-event certification artifacts
    (ISSUE 16): contiguous round numbering and the machine-read surface
    the sim gate's floors and diff_sim parse (comparability identity:
    tenants + service-model fit + engine geometry; summary: fairness,
    starvation, per-tenant blocks, balanced books)."""
    rounds = _rounds("SIM_r*.json")
    assert rounds, "no SIM_r*.json artifacts committed"
    assert sorted(rounds) == list(range(1, max(rounds) + 1)), sorted(rounds)
    for n, path in rounds.items():
        base = os.path.basename(path)
        doc = json.load(open(path))
        assert doc.get("n") == n, f"{base}: field n={doc.get('n')} != filename round {n}"
        assert isinstance(doc.get("schema_version"), int), base
        assert doc.get("mode") == "sim", base
        workload = doc.get("workload")
        assert isinstance(workload, dict), base
        tenants = workload.get("tenants")
        assert isinstance(tenants, list) and len(tenants) >= 1, base
        for t in tenants:
            assert isinstance(t.get("name"), str), f"{base}: tenant name"
            assert isinstance(t.get("rate_rps"), (int, float)), f"{base}: tenant rate"
        model = doc.get("service_model")
        assert isinstance(model, dict) and isinstance(model.get("source"), str), base
        for key in ("prefill_p50_s", "prefill_p99_s", "tpot_p50_s", "tpot_p99_s"):
            assert isinstance(model.get(key), (int, float)), f"{base}: service_model.{key}"
        assert isinstance(doc.get("engine_config"), dict), base
        # no device manifest BY DESIGN: a sim run never touches a device
        assert "manifest" not in doc, base
        summary = doc.get("summary")
        assert isinstance(summary, dict), base
        for key, typ in (
            ("n_requests", int), ("n_tenants", int),
            ("offered_rps", (int, float)), ("achieved_rps", (int, float)),
            ("fairness_jain", (int, float)),
            ("max_starvation_age_s", (int, float)),
            ("shed_rate", (int, float)), ("error_rate", (int, float)),
            ("duration_s", (int, float)), ("tenants", dict),
        ):
            assert isinstance(summary.get(key), typ), f"{base}: summary.{key}"
        assert summary.get("books_balanced") is True, base
        assert set(summary["tenants"]) == {t["name"] for t in tenants}, base
        for name, block in summary["tenants"].items():
            for key in ("offered_rps", "achieved_rps", "n_requests", "ok", "shed"):
                assert isinstance(block.get(key), (int, float)), (
                    f"{base}: tenants.{name}.{key}"
                )
        for fam in ("ttft_s", "queue_wait_s"):
            block = summary.get(fam)
            assert isinstance(block, dict), f"{base}: summary.{fam}"
            for p in ("p50", "p99"):
                assert isinstance(block.get(p), (int, float)), f"{base}: summary.{fam}.{p}"


def test_hostlint_allowlist_schema_pinned():
    """contracts/hostlint_allow.json: every suppression carries a unique
    pattern and a non-empty reason — an unexplained allowlist entry is
    indistinguishable from a weakened rule, and load_allowlist refuses it."""
    from perceiver_io_tpu.analysis.hostrules import load_allowlist

    path = os.path.join(REPO, "contracts", "hostlint_allow.json")
    doc = json.load(open(path))
    assert isinstance(doc.get("entries"), list) and doc["entries"]
    patterns, entries = load_allowlist(path)
    assert len(patterns) == len(set(patterns)), "duplicate allowlist patterns"
    for e in entries:
        assert isinstance(e["pattern"], str) and e["pattern"]
        assert isinstance(e["reason"], str) and e["reason"].strip()
        # patterns target a registered rule, not a glob over everything
        rule = e["pattern"].split(":", 1)[0]
        from perceiver_io_tpu.analysis.hostrules import HOST_RULES

        assert rule in HOST_RULES, f"{e['pattern']!r} names no registered rule"


def test_hostlint_allowlist_rejects_unreasoned_entries(tmp_path):
    from perceiver_io_tpu.analysis.hostrules import load_allowlist

    p = tmp_path / "allow.json"
    p.write_text(json.dumps({"entries": [{"pattern": "event-schema:*"}]}))
    with pytest.raises(ValueError, match="no reason"):
        load_allowlist(str(p))
    p.write_text(json.dumps({"entries": [{"pattern": "event-schema:*",
                                          "reason": "   "}]}))
    with pytest.raises(ValueError, match="no reason"):
        load_allowlist(str(p))
    p.write_text(json.dumps({"entries": [{"reason": "orphaned"}]}))
    with pytest.raises(ValueError, match="no pattern"):
        load_allowlist(str(p))
