"""Test configuration: force the CPU backend with 8 virtual devices so
multi-chip sharding tests run anywhere (the driver separately dry-runs the
multi-chip path on its own device count).

Note: the axon TPU tunnel presets JAX_PLATFORMS=axon and a sitecustomize
imports jax early, so the env-var route does not stick — the platform must be
set via jax.config before first backend use. XLA_FLAGS is read at backend
initialization, so setting it here (before any device query) still works.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# The suite is compile-bound (hundreds of distinct jit programs): a
# persistent compilation cache makes repeat runs hit compiled artifacts
# instead of XLA. Opt out with JAX_TEST_NO_COMPILE_CACHE=1.
if not os.environ.get("JAX_TEST_NO_COMPILE_CACHE"):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
