"""Sequence-parallel attention (ring + seq-sharded cross) must reproduce
dense softmax attention exactly, on an 8-virtual-device CPU mesh — masks,
right-aligned causality, and fully-masked rows included."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.parallel import make_mesh
from perceiver_io_tpu.parallel.ring_attention import (
    make_ring_cross_attention,
    make_ring_self_attention,
)

B, H, DK, DV = 2, 3, 8, 16


def dense_attention(q, k, v, pad_mask=None, causal=False):
    """Straight-line reference: full scores, right-aligned causal mask."""
    n_q, n_kv = q.shape[2], k.shape[2]
    s = jnp.einsum("bhnd,bhmd->bhnm", q, k).astype(jnp.float32)
    masked = jnp.zeros((1, 1, 1, n_kv), bool)
    if pad_mask is not None:
        masked = masked | pad_mask[:, None, None, :]
    if causal:
        q_abs = n_kv - n_q + jnp.arange(n_q)
        masked = masked | (jnp.arange(n_kv)[None, None, None, :] > q_abs[None, None, :, None])
    s = jnp.where(masked, -jnp.inf, s)
    a = jax.nn.softmax(s, axis=-1)
    a = jnp.where(jnp.isnan(a), 0.0, a)  # fully-masked rows
    return jnp.einsum("bhnm,bhmd->bhnd", a, v)


def make_qkv(rng, n_q, n_kv):
    q = jnp.asarray(rng.standard_normal((B, H, n_q, DK)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, n_kv, DK)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, n_kv, DV)), jnp.float32)
    return q, k, v


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(data=1, seq=4, devices=jax.devices()[:4])


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_pad", [False, True])
def test_seq_sharded_cross_attention(rng, seq_mesh, causal, with_pad):
    n_q, n_kv = 6, 32
    q, k, v = make_qkv(rng, n_q, n_kv)
    pad = jnp.asarray(rng.random((B, n_kv)) < 0.3) if with_pad else jnp.zeros((B, n_kv), bool)

    attn = make_ring_cross_attention(seq_mesh, causal=causal)
    out = attn(q, k, v, pad)
    ref = dense_attention(q, k, v, pad_mask=pad, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_pad", [False, True])
def test_ring_self_attention(rng, seq_mesh, causal, with_pad):
    n = 32  # both q and kv sharded: 8 per device
    q, k, v = make_qkv(rng, n, n)
    pad = jnp.asarray(rng.random((B, n)) < 0.25) if with_pad else jnp.zeros((B, n), bool)

    attn = make_ring_self_attention(seq_mesh, causal=causal)
    out = attn(q, k, v, pad)
    ref = dense_attention(q, k, v, pad_mask=pad, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_cross_attention_fully_masked_row_is_zero(rng, seq_mesh):
    n_q, n_kv = 4, 16
    q, k, v = make_qkv(rng, n_q, n_kv)
    pad = jnp.ones((B, n_kv), bool)  # everything masked
    attn = make_ring_cross_attention(seq_mesh)
    out = attn(q, k, v, pad)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_ring_self_attention_right_aligned_causal(rng, seq_mesh):
    """Global q shorter than global kv: query i sits at slot kv_total - q_total + i
    (the core attention right-alignment contract)."""
    n_q, n_kv = 16, 32
    q, k, v = make_qkv(rng, n_q, n_kv)
    out = make_ring_self_attention(seq_mesh, causal=True)(q, k, v)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_wrappers_accept_missing_pad_mask(rng, seq_mesh):
    n = 16
    q, k, v = make_qkv(rng, n, n)
    out = make_ring_cross_attention(seq_mesh)(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_matches_on_eight_devices(rng):
    mesh = make_mesh(data=1, seq=8)
    n = 64
    q, k, v = make_qkv(rng, n, n)
    pad = jnp.zeros((B, n), bool)
    out = make_ring_self_attention(mesh, causal=True)(q, k, v, pad)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
