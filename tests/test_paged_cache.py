"""Paged KV cache units (ISSUE 13 Pageline): the cache discipline seam —
paged append/gather-view exactness vs the contiguous cache, the prefill
commit path, int8 storage parity, the page-walk Pallas kernel vs its gather
reference (interpret mode), and the pure host-side page allocator."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.core.cache import (
    KVCache,
    PagedKVCache,
    commit_prefill,
    init_kv_cache,
    init_paged_kv_cache,
    release_slot,
)
from perceiver_io_tpu.serving.pages import PageAllocator

C = 64  # channels (8 heads x 8 or 4 x 16 — kernel tests pick their own)


def _rand(rng, *shape):
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32))


# ---------------------------------------------------------------- disciplines


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.int8])
def test_paged_append_matches_contiguous(dtype):
    """Token-for-token: appending the same stream into a contiguous cache
    and into pages yields identical slot contents through gather_view —
    the storage seam the engine's token-exactness rides on."""
    rng = np.random.default_rng(0)
    b, page, pps = 3, 4, 3
    cap = page * pps
    cont = init_kv_cache(b, cap, C, C, dtype=dtype)
    paged = init_paged_kv_cache(b, 1 + b * pps, page, pps, C, C, dtype=dtype)
    table = jnp.arange(1, 1 + b * pps, dtype=jnp.int32).reshape(b, pps)
    paged = PagedKVCache(
        k=paged.k, v=paged.v, page_table=table, length=paged.length,
        k_scale=paged.k_scale, v_scale=paged.v_scale,
    )
    for _ in range(cap):
        k = _rand(rng, b, 1, C)
        v = _rand(rng, b, 1, C)
        cont = cont.append(k, v)
        paged = paged.append(k, v)
    pk, pv, pks, pvs = paged.gather_view()
    np.testing.assert_array_equal(np.asarray(pk), np.asarray(cont.k))
    np.testing.assert_array_equal(np.asarray(pv), np.asarray(cont.v))
    assert np.all(np.asarray(paged.length) == cap)
    assert int(cont.length) == cap
    if dtype == jnp.int8:
        np.testing.assert_array_equal(np.asarray(pks), np.asarray(cont.k_scale))
        np.testing.assert_array_equal(np.asarray(pvs), np.asarray(cont.v_scale))


def test_ragged_lengths_stay_independent():
    """Per-slot lengths: appends advance every slot, but each slot's view
    masks at ITS length — slot contents never bleed across page tables."""
    rng = np.random.default_rng(1)
    b, page, pps = 2, 4, 2
    paged = init_paged_kv_cache(b, 1 + b * pps, page, pps, C, C)
    table = jnp.arange(1, 1 + b * pps, dtype=jnp.int32).reshape(b, pps)
    paged = PagedKVCache(k=paged.k, v=paged.v, page_table=table,
                         length=jnp.asarray([0, 3], jnp.int32))
    k = _rand(rng, b, 1, C)
    paged2 = paged.append(k, k)
    assert np.asarray(paged2.length).tolist() == [1, 4]
    pk, _, _, _ = paged2.gather_view()
    # slot 0 wrote its page 1 at offset 0; slot 1 wrote its page 3 at offset 3
    np.testing.assert_array_equal(np.asarray(pk[0, 0]), np.asarray(k[0, 0]))
    np.testing.assert_array_equal(np.asarray(pk[1, 3]), np.asarray(k[1, 0]))


def test_commit_prefill_and_release_roundtrip():
    """The disaggregation seam: a contiguous prefill cache's rows land in
    the granted pages with the request's true length; release parks the
    table row back on scratch without touching pool bytes."""
    rng = np.random.default_rng(2)
    b_slots, page, pps, n_tok = 2, 4, 3, 7
    paged = init_paged_kv_cache(b_slots, 1 + b_slots * pps, page, pps, C, C)
    pre = init_kv_cache(1, n_tok + 2, C, C)  # capacity beyond the tokens
    pre = pre.append(_rand(rng, 1, n_tok, C), _rand(rng, 1, n_tok, C))
    pages = jnp.asarray([2, 5], jnp.int32)  # ceil(7/4) = 2 pages
    out = commit_prefill(paged, 1, pages, pre, pre.length)
    assert int(out.length[1]) == n_tok and int(out.length[0]) == 0
    assert np.asarray(out.page_table[1]).tolist() == [2, 5, 0]
    pk, pv, _, _ = out.gather_view()
    np.testing.assert_array_equal(
        np.asarray(pk[1, :n_tok]), np.asarray(pre.k[0, :n_tok])
    )
    np.testing.assert_array_equal(
        np.asarray(pv[1, :n_tok]), np.asarray(pre.v[0, :n_tok])
    )
    released = release_slot(out, 1)
    assert int(released.length[1]) == 0
    assert np.asarray(released.page_table[1]).tolist() == [0, 0, 0]
    # pool bytes untouched — only the table moved
    np.testing.assert_array_equal(np.asarray(released.k), np.asarray(out.k))


def test_paged_append_rejects_multi_token():
    paged = init_paged_kv_cache(1, 3, 4, 2, C, C)
    with pytest.raises(ValueError, match="one token per slot"):
        paged.append(jnp.zeros((1, 2, C)), jnp.zeros((1, 2, C)))


# ------------------------------------------------------------- pallas kernel


def test_page_walk_kernel_matches_gather_reference():
    """The TPU page-walk kernel (scalar-prefetched page-table BlockSpecs)
    against the gather-view reference, in interpret mode — ragged lengths,
    including an empty slot (fully masked -> zeros)."""
    from perceiver_io_tpu.ops.paged_attention import (
        paged_attention_reference,
        paged_decode_attention,
        paged_kernel_supported,
    )

    rng = np.random.default_rng(3)
    s_slots, pool, page, h, d = 3, 10, 8, 4, 32  # h*d = 128 lanes
    table = np.zeros((s_slots, 3), np.int32)
    for s in range(s_slots):
        table[s] = [1 + 3 * s, 2 + 3 * s, 3 + 3 * s]
    cache = PagedKVCache(
        k=_rand(rng, pool, page, h * d),
        v=_rand(rng, pool, page, h * d),
        page_table=jnp.asarray(table),
        length=jnp.asarray([0, 17, 24], jnp.int32),
    )
    q = _rand(rng, s_slots, h, d)
    assert paged_kernel_supported(cache, h, d, d)
    got = paged_decode_attention(q, cache)
    ref = paged_attention_reference(q, cache)
    # every slot, including the EMPTY one (slot 0): a fully masked row
    # softmaxes uniform over MASK_VALUE scores in both implementations —
    # garbage either way, but the SAME garbage (the engine discards it)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_kernel_gate_excludes_unsupported():
    from perceiver_io_tpu.ops.paged_attention import paged_kernel_supported

    quant = init_paged_kv_cache(1, 3, 8, 2, 128, 128, dtype=jnp.int8)
    assert not paged_kernel_supported(quant, 4, 32, 32)  # int8 stays on fallback
    tiny_pages = init_paged_kv_cache(1, 3, 4, 2, 128, 128)
    assert not paged_kernel_supported(tiny_pages, 4, 32, 32)  # page < 8 rows
    odd = init_paged_kv_cache(1, 3, 8, 2, 96, 96)
    assert not paged_kernel_supported(odd, 4, 24, 24)  # 96 lanes unaligned


# ------------------------------------------------------------ page allocator


def test_allocator_deterministic_reuse():
    """Alloc/free determinism: same history, same page ids; LIFO reuse
    hands back the most recently freed pages first."""
    a = PageAllocator(num_pages=8, page_size=4)
    g1 = a.alloc_tokens(7)  # 2 pages
    g2 = a.alloc_tokens(4)  # 1 page
    assert g1.pages == (1, 2) and g2.pages == (3,)
    a.free(g1)
    g3 = a.alloc_tokens(5)  # 2 pages, LIFO: g1's pages back, most-recent first
    assert g3.pages == (1, 2)
    b = PageAllocator(num_pages=8, page_size=4)
    h1 = b.alloc_tokens(7)
    h2 = b.alloc_tokens(4)
    b.free(h1)
    h3 = b.alloc_tokens(5)
    assert (h1.pages, h2.pages, h3.pages) == (g1.pages, g2.pages, g3.pages)
    assert a.audit() == []


def test_allocator_fragmentation_accounting():
    a = PageAllocator(num_pages=10, page_size=8)
    a.alloc_tokens(9)   # 2 pages, 7 slack
    a.alloc_tokens(8)   # 1 page, 0 slack
    st = a.stats()
    assert st.pages_used == 3 and st.pages_free == 6
    assert st.tokens_reserved == 17
    assert st.internal_frag_tokens == 3 * 8 - 17 == 7
    assert 0 < st.internal_frag_frac < 1
    assert st.used_frac == 3 / 9


def test_allocator_exhaustion_and_double_free():
    a = PageAllocator(num_pages=4, page_size=4)  # 3 allocatable
    g = a.alloc_tokens(12)  # all 3 pages
    assert a.alloc_tokens(1) is None  # exhausted: first-class None, no raise
    assert not a.can_fit_now(1) and a.can_ever_fit(12)
    assert not a.can_ever_fit(13)  # beyond an EMPTY pool: shed territory
    a.free(g)
    with pytest.raises(ValueError, match="double free"):
        a.free(g)
    # Evictline hardening: the rejected double free is RECORDED (audit names
    # it — tests/test_evictline.py pins the full trail), while the page-
    # ownership invariants and the free list stay intact
    problems = a.audit()
    assert any("double free rejected" in p for p in problems)
    assert not any("owned by grants" in p or "leaked" in p for p in problems)
    assert a.pages_used == 0 and a.pages_free == 3


def test_allocator_scratch_reserved():
    a = PageAllocator(num_pages=3, page_size=2)
    g1, g2 = a.alloc_tokens(2), a.alloc_tokens(2)
    assert g2 is not None and 0 not in g1.pages + g2.pages
    with pytest.raises(ValueError):
        PageAllocator(num_pages=1, page_size=2)
