"""int8 KV-cache contract: quantized storage must track the exact cache
closely (per-token symmetric scales), survive every slot transformation
generation performs, and run end-to-end through generate/beam search.

Capability beyond the reference (its torch cache is full-precision,
huggingface.py:158-185): decode is bandwidth-bound, so int8 halves the
dominant traffic — measured 1.69x on the decode attention core
(tools/int8_cache_probe.py) and benchable via
``bench.py --mode decode --cache-dtype int8``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.core.attention import init_kv_cache, quantize_kv
from perceiver_io_tpu.core.config import CausalSequenceModelConfig
from perceiver_io_tpu.core.modules import CausalSequenceModel

NUM_PREFIX = 8
NUM_LATENTS = 16
NUM_CHANNELS = 128
NUM_LAYERS = 2
BATCH_SIZE = 2


def test_quantize_roundtrip_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 17, 64)) * rng.lognormal(size=(3, 17, 1)), jnp.float32)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.dtype == jnp.bfloat16
    deq = q.astype(jnp.float32) * s.astype(jnp.float32)[..., None]
    # rounding error is at most half a quantization step (+ bf16 scale slack)
    bound = np.broadcast_to(0.51 * np.asarray(s, np.float32)[..., None] + 1e-6, x.shape)
    np.testing.assert_array_less(np.abs(np.asarray(deq - x)), bound)


def test_map_slots_preserves_scales():
    cache = init_kv_cache(2, 8, 16, 16, jnp.int8)
    assert cache.quantized
    rolled = cache.map_slots(lambda a: jnp.roll(a, -1, axis=1))
    assert rolled.k_scale is not None and rolled.v_scale is not None
    assert rolled.k.dtype == jnp.int8
    plain = init_kv_cache(2, 8, 16, 16)
    assert not plain.quantized
    assert plain.map_slots(lambda a: a).k_scale is None


@pytest.fixture(scope="module")
def csm():
    config = CausalSequenceModelConfig(
        vocab_size=100,
        max_seq_len=NUM_LATENTS + NUM_PREFIX,
        max_latents=NUM_LATENTS,
        num_channels=NUM_CHANNELS,
        num_self_attention_layers=NUM_LAYERS,
        num_self_attention_rotary_layers=-1,
        output_norm=True,
    )
    model = CausalSequenceModel(config)
    x = jnp.zeros((BATCH_SIZE, NUM_PREFIX + NUM_LATENTS), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=NUM_PREFIX)
    return model, params, config


def test_csm_int8_cache_tracks_exact(csm):
    """Incremental decode on an int8 cache stays close to the exact uncached
    forward — the test_kv_cache.py contract with quantization tolerance."""
    model, params, config = csm
    total = NUM_PREFIX + NUM_LATENTS
    x = jnp.asarray(
        np.random.default_rng(2).integers(0, config.vocab_size, size=(BATCH_SIZE, total))
    )

    exact = model.apply(params, x, prefix_len=NUM_PREFIX).logits

    cache = CausalSequenceModel.init_cache(config, BATCH_SIZE, dtype=jnp.int8)
    assert cache[0].quantized
    out = model.apply(
        params, x[:, : NUM_PREFIX + 2], prefix_len=NUM_PREFIX, kv_cache=cache
    )
    logits = [out.logits]
    cache = out.kv_cache
    for i in range(2, NUM_LATENTS):
        out = model.apply(
            params,
            x[:, NUM_PREFIX + i : NUM_PREFIX + i + 1],
            prefix_len=NUM_PREFIX,
            kv_cache=cache,
            decode=True,
        )
        logits.append(out.logits)
        cache = out.kv_cache
    logits = jnp.concatenate(logits, axis=1)

    err = np.abs(np.asarray(logits) - np.asarray(exact))
    # int8 per-token quantization on a random-init f32 model: observed max
    # ~1e-2; the bound leaves ~3x headroom while still catching any scale
    # misalignment (which produces O(1) garbage)
    assert err.max() < 0.05, err.max()
    # the decode-relevant quantity — the top-1 ordering — must agree
    agree = (np.argmax(logits, -1) == np.argmax(np.asarray(exact), -1)).mean()
    assert agree > 0.9, agree


def test_generate_and_beam_run_with_int8_cache(csm):
    """End-to-end: greedy generate and beam search (slot roll + beam-gather
    reorder paths) execute with quantized caches and emit valid ids."""
    from perceiver_io_tpu.generation import GenerationConfig, beam_search, make_generate_fn

    model, params, config = csm
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, config.vocab_size, size=(BATCH_SIZE, NUM_PREFIX + 2))
    )
    fn = make_generate_fn(
        model, NUM_LATENTS, GenerationConfig(max_new_tokens=NUM_LATENTS + 2),
        cache_dtype=jnp.int8,
    )
    out = fn(params, prompt)
    assert out.shape == (BATCH_SIZE, prompt.shape[1] + NUM_LATENTS + 2)
    assert ((np.asarray(out) >= 0) & (np.asarray(out) < config.vocab_size)).all()

    seqs, _scores = beam_search(
        model, params, prompt, num_latents=NUM_LATENTS, num_beams=2, max_new_tokens=3,
        cache_dtype=jnp.int8,
    )
    assert ((np.asarray(seqs) >= 0) & (np.asarray(seqs) < config.vocab_size)).all()


def test_int8_graduation_ledger_and_numerics_gate(csm):
    """The ISSUE 14 graduation satellite: ``int8_cache``/``int8_weights``
    stand MEASURED in the committed ledger (citing the BENCH_extra_r5
    floors), and the PR-9 decode-health probes are the numerics safety
    gate — a decode over BOTH int8 stores with probes compiled in must
    report a zero non-finite-logit fraction and finite entropy on every
    token (quantization buys bandwidth, never silent numeric damage)."""
    import os

    from perceiver_io_tpu.analysis.ledger import feature_state, load_ledger
    from perceiver_io_tpu.generation import GenerationConfig, make_decode_fns

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ledger = load_ledger(os.path.join(repo, "contracts"))
    assert feature_state(ledger, "int8_cache") == "measured"
    assert feature_state(ledger, "int8_weights") == "measured"
    # the graduations cite floors that must actually exist in the ledger
    floors = ledger.get("floors", {})
    assert "decode_b8_int8_vs_baseline" in floors
    assert "int8_full_vs_baseline" in floors

    model, params, config = csm
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(
            0, config.vocab_size, size=(BATCH_SIZE, NUM_PREFIX + 2)
        )
    )
    prefill, step = make_decode_fns(
        model, NUM_LATENTS, GenerationConfig(max_new_tokens=6),
        cache_dtype=jnp.int8, weight_dtype=jnp.int8, probes=True,
    )
    _, state = prefill(params, prompt, None, jax.random.PRNGKey(1))
    healths = [state["probe"]]
    for _ in range(5):
        state, _ = step(state)
        healths.append(state["probe"])
    for h in healths:
        assert float(h["nonfinite_logit_frac"]) == 0.0, h
        assert np.isfinite(float(h["logit_entropy"])), h
        assert 0.0 <= float(h["kv_cache_frac"]) <= 1.0, h
