"""Generation contracts, ported from the reference
(reference: tests/causal_language_model_generate_test.py:28-97): exact error
messages, output shapes, and cached generation == uncached sliding-window
re-forward — including across the max_latents growth phase and the
max_seq_len slide."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.generation import GenerationConfig, generate
from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig

VOCAB = 64
MAX_SEQ_LEN = 24
MAX_LATENTS = 8
B = 2


@pytest.fixture(scope="module")
def model_and_params():
    config = CausalLanguageModelConfig(
        vocab_size=VOCAB,
        max_seq_len=MAX_SEQ_LEN,
        max_latents=MAX_LATENTS,
        num_channels=32,
        num_heads=4,
        num_self_attention_layers=2,
        num_self_attention_rotary_layers=-1,
        output_norm=True,
    )
    model = CausalLanguageModel(config)
    x = jnp.zeros((B, MAX_SEQ_LEN), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=MAX_SEQ_LEN - MAX_LATENTS)
    return model, params


def prompt(seq_len=10):
    return jnp.asarray(np.random.default_rng(5).integers(0, VOCAB, size=(B, seq_len)))


def test_generate_rejects_invalid_seq_len(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match=r"Input sequence length out of valid range \[1..24\]"):
        generate(model, params, jnp.zeros((B, MAX_SEQ_LEN + 1), jnp.int32))


def test_generate_rejects_invalid_num_latents(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match=r"num_latents=9 out of valid range \[1..8\]"):
        generate(model, params, prompt(), num_latents=9)


def test_generate_rejects_excessive_prefix(model_and_params):
    model, params = model_and_params
    # seq_len 20 with 1 latent -> prefix 19 > max_prefix 16
    with pytest.raises(ValueError, match=r"num_latents must be in range \[4..8\]"):
        generate(model, params, prompt(20), num_latents=1)


def test_generate_output_shape(model_and_params):
    model, params = model_and_params
    ids = prompt()
    out = generate(model, params, ids, num_latents=4, config=GenerationConfig(max_new_tokens=5))
    assert out.shape == (B, 15)
    np.testing.assert_array_equal(np.asarray(out[:, :10]), np.asarray(ids))


@pytest.mark.slow
def test_generate_cached_equals_uncached_sliding_window(model_and_params):
    """Greedy cached generation must match re-running the full uncached
    forward per step with the reference's window bookkeeping: latents grow to
    max_latents, then the prefix grows to max_prefix_len, then the window
    slides (reference: huggingface.py:89-138 + test_compare_cached_uncached)."""
    model, params = model_and_params
    ids = prompt(10)
    num_latents = 4
    max_new = 30  # crosses latent growth (4->8), prefix growth (6->16), and the slide

    out_cached = generate(
        model, params, ids, num_latents=num_latents, config=GenerationConfig(max_new_tokens=max_new)
    )

    # uncached reference loop
    seq = np.asarray(ids)
    prefix_len = 10 - num_latents
    max_prefix_len = MAX_SEQ_LEN - MAX_LATENTS
    for _ in range(max_new):
        window = jnp.asarray(seq[:, -MAX_SEQ_LEN:])
        out = model.apply(params, window, prefix_len=prefix_len)
        nxt = np.asarray(jnp.argmax(out.logits[:, -1], axis=-1))
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
        if seq.shape[1] - prefix_len > MAX_LATENTS and prefix_len < max_prefix_len:
            prefix_len += 1

    np.testing.assert_array_equal(np.asarray(out_cached), seq)


@pytest.mark.slow
def test_generate_with_left_padding(model_and_params):
    """Left-padded prompts: pad positions are masked and positions shifted."""
    model, params = model_and_params
    ids = np.array(prompt(10))
    pad = np.zeros((B, 10), bool)
    pad[1, :3] = True
    ids[1, :3] = 0

    out = generate(
        model,
        params,
        jnp.asarray(ids),
        pad_mask=jnp.asarray(pad),
        num_latents=4,
        config=GenerationConfig(max_new_tokens=4),
    )
    assert out.shape == (B, 14)

    # batch-of-one without padding produces the same continuation for row 0
    out_single = generate(
        model,
        params,
        jnp.asarray(ids[:1]),
        num_latents=4,
        config=GenerationConfig(max_new_tokens=4),
    )
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(out_single[0]))


@pytest.mark.slow
def test_sampling_strategies(model_and_params):
    model, params = model_and_params
    ids = prompt()
    cfg = GenerationConfig(max_new_tokens=6, do_sample=True, temperature=0.8, top_k=10)
    out1 = generate(model, params, ids, num_latents=4, config=cfg, rng=jax.random.PRNGKey(1))
    out2 = generate(model, params, ids, num_latents=4, config=cfg, rng=jax.random.PRNGKey(2))
    assert out1.shape == out2.shape == (B, 16)
    assert np.asarray((out1 >= 0) & (out1 < VOCAB)).all()

    cfg_p = GenerationConfig(max_new_tokens=4, do_sample=True, top_p=0.9)
    out3 = generate(model, params, ids, num_latents=4, config=cfg_p, rng=jax.random.PRNGKey(3))
    assert out3.shape == (B, 14)


@pytest.mark.slow  # long-compile; the fast subset keeps one representative of this path
def test_eos_stops_generation(model_and_params):
    model, params = model_and_params
    ids = prompt()
    # force eos to be whatever greedy produces first, then everything after is pad
    first = generate(model, params, ids, num_latents=4, config=GenerationConfig(max_new_tokens=1))
    eos = int(first[0, -1])
    cfg = GenerationConfig(max_new_tokens=6, eos_token_id=eos, pad_token_id=63)
    out = generate(model, params, ids, num_latents=4, config=cfg)
    row = np.asarray(out[0, 10:])
    assert row[0] == eos
    assert (row[1:] == 63).all()


# -------------------------------------------------------------- beam search


class TestBeamSearch:
    def test_beam_one_equals_greedy(self, model_and_params):
        from perceiver_io_tpu.generation import beam_search

        model, params = model_and_params
        p = prompt(8)
        greedy = generate(
            model, params, p, num_latents=4, config=GenerationConfig(max_new_tokens=6)
        )
        beam, _ = beam_search(model, params, p, num_latents=4, num_beams=1, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))

    def _sequence_logprob(self, model, params, p, seq):
        """Log-prob of the continuation under a full uncached forward."""
        full = jnp.concatenate([p, seq], axis=1)
        n = full.shape[1]
        out = model.apply(params, full, prefix_len=n - MAX_LATENTS)
        logp = jax.nn.log_softmax(out.logits.astype(jnp.float32), axis=-1)
        total = 0.0
        t0 = p.shape[1]
        for t in range(seq.shape[1]):
            # logits position predicting full[:, t0 + t] is at latent index
            # (t0 + t - 1) - (n - MAX_LATENTS)
            pos = t0 + t - 1 - (n - MAX_LATENTS)
            total = total + logp[jnp.arange(p.shape[0]), pos, seq[:, t]]
        return np.asarray(total)

    def test_beam_improves_or_matches_greedy_logprob(self, model_and_params):
        from perceiver_io_tpu.generation import beam_search

        model, params = model_and_params
        p = prompt(8)
        k = 6
        greedy = generate(
            model, params, p, num_latents=4, config=GenerationConfig(max_new_tokens=k)
        )[:, -k:]
        beam, scores = beam_search(
            model, params, p, num_latents=4, num_beams=4, max_new_tokens=k
        )
        beam = beam[:, -k:]
        lp_greedy = self._sequence_logprob(model, params, p, greedy)
        lp_beam = self._sequence_logprob(model, params, p, beam)
        assert (lp_beam >= lp_greedy - 1e-4).all()
        # reported score = mean log-prob at length_penalty 1
        np.testing.assert_allclose(np.asarray(scores), lp_beam / k, atol=2e-2)  # cached-vs-uncached f32 drift

    @pytest.mark.slow  # long-compile; the fast subset keeps one representative of this path
    def test_beam_one_equals_greedy_past_latent_window(self, model_and_params):
        """Regression: generation deeper than max_latents must slide the
        self-attention caches exactly like generate() does."""
        from perceiver_io_tpu.generation import beam_search

        model, params = model_and_params
        p = prompt(8)
        k = 14  # 4 latents + 14 tokens > max_latents (8)
        greedy = generate(
            model, params, p, num_latents=4, config=GenerationConfig(max_new_tokens=k)
        )
        beam, _ = beam_search(model, params, p, num_latents=4, num_beams=1, max_new_tokens=k)
        np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))

    def test_beam_rejects_window_overflow(self, model_and_params):
        from perceiver_io_tpu.generation import beam_search

        model, params = model_and_params
        with pytest.raises(ValueError, match="does not slide the window"):
            beam_search(model, params, prompt(20), num_latents=8, max_new_tokens=8)

    @pytest.mark.slow  # long-compile; the fast subset keeps one representative of this path
    def test_beam_padded_batch_equals_unpadded_rows(self, model_and_params):
        """Mixed-length prompts via left padding: each padded row's beam
        continuation equals the row run alone without padding (pad slots
        masked in the CA window, positions shifted per row)."""
        from perceiver_io_tpu.generation import beam_search

        model, params = model_and_params
        ids = np.array(prompt(10))
        pad = np.zeros((B, 10), bool)
        pad[1, :3] = True
        ids[1, :3] = 0
        k = 6

        out, _ = beam_search(
            model, params, jnp.asarray(ids), pad_mask=jnp.asarray(pad),
            num_latents=4, num_beams=3, max_new_tokens=k,
        )
        out0, _ = beam_search(
            model, params, jnp.asarray(ids[:1]), num_latents=4, num_beams=3, max_new_tokens=k
        )
        out1, _ = beam_search(
            model, params, jnp.asarray(ids[1:, 3:]), num_latents=4, num_beams=3, max_new_tokens=k
        )
        np.testing.assert_array_equal(np.asarray(out[0, -k:]), np.asarray(out0[0, -k:]))
        np.testing.assert_array_equal(np.asarray(out[1, -k:]), np.asarray(out1[0, -k:]))

    def test_beam_rejects_pads_in_latent_region(self, model_and_params):
        """Padding deeper than prefix_len would put a pad token into the
        (unmasked) latent self-attention — rejected eagerly."""
        from perceiver_io_tpu.generation import beam_search

        model, params = model_and_params
        ids = np.zeros((B, 10), np.int64)
        pad = np.zeros((B, 10), bool)
        pad[1, :8] = True  # 8 pads > prefix_len = 10 - 4 = 6
        with pytest.raises(ValueError, match="latent region"):
            beam_search(
                model, params, jnp.asarray(ids), pad_mask=jnp.asarray(pad),
                num_latents=4, num_beams=2, max_new_tokens=4,
            )

    def test_eos_freezes_beams(self, model_and_params):
        from perceiver_io_tpu.generation import beam_search

        model, params = model_and_params
        p = prompt(8)
        seqs, _ = beam_search(
            model, params, p, num_latents=4, num_beams=3, max_new_tokens=8,
            eos_token_id=3, pad_token_id=0,
        )
        tail = np.asarray(seqs)[:, 8:]
        for row in tail:
            hits = np.nonzero(row == 3)[0]
            if hits.size:  # everything after the first EOS must be PAD
                assert (row[hits[0] + 1 :] == 0).all()


def test_packed_small_params_token_exact(model_and_params):
    """The decode scan's small-parameter packing (round 5: one consolidated
    f32 buffer re-sliced in the scan body) must be token-exact vs the
    unpacked tree — the f32 pack/slice round-trip is bitwise — in both the
    plain and int8-weight regimes."""
    from perceiver_io_tpu.generation import pack_small_params

    model, params = model_and_params
    p = prompt(16)
    cfg = GenerationConfig(max_new_tokens=6, do_sample=True, top_k=5)
    for wd in (None, jnp.int8):
        with pack_small_params(True):
            on = np.asarray(
                generate(model, params, p, num_latents=4, config=cfg,
                         rng=jax.random.PRNGKey(3), weight_dtype=wd)
            )
        with pack_small_params(False):
            off = np.asarray(
                generate(model, params, p, num_latents=4, config=cfg,
                         rng=jax.random.PRNGKey(3), weight_dtype=wd)
            )
        np.testing.assert_array_equal(on, off)


def test_packed_small_params_beam_search_exact(model_and_params):
    """beam_search carries its own copy of the packing wiring — pin its
    sequence/score exactness too (packing auto-engages at
    batch*num_beams >= 4 in production beam decoding)."""
    from perceiver_io_tpu.generation import beam_search, pack_small_params

    model, params = model_and_params
    p = prompt(12)
    out = {}
    for mode in (True, False):
        with pack_small_params(mode):
            seqs, scores = beam_search(
                model, params, p, num_latents=4, num_beams=3, max_new_tokens=6
            )
        out[mode] = (np.asarray(seqs), np.asarray(scores))
    np.testing.assert_array_equal(out[True][0], out[False][0])
    np.testing.assert_array_equal(out[True][1], out[False][1])


def test_pack_small_params_skips_inexact_float_dtypes():
    """The pack stages leaves through ONE f32 buffer, so only dtypes whose
    f32 round-trip is exact may ride it (ADVICE r5): f32/bf16/f16 pack,
    anything else (here: float8) stays an unpacked leaf — and the rebuilt
    tree is bitwise the original either way."""
    from perceiver_io_tpu.generation import _pack_small_params

    f8 = jnp.float8_e4m3fn
    tree = {
        "ln_scale": jnp.linspace(0.5, 1.5, 64, dtype=jnp.float32),
        "bias_bf16": jnp.linspace(-1, 1, 32).astype(jnp.bfloat16),
        "bias_f16": jnp.linspace(-2, 2, 32).astype(jnp.float16),
        "scales_f8": jnp.linspace(0.1, 2.0, 16).astype(f8),
        "big": jnp.zeros((128, 128), jnp.float32),  # over the size cap
        "ids": jnp.arange(8, dtype=jnp.int32),
    }
    packed, unpack = _pack_small_params(tree)
    # only the exact-round-trip float leaves were consolidated
    assert packed.size == 64 + 32 + 32
    rebuilt = unpack(packed)
    for key, leaf in tree.items():
        assert rebuilt[key].dtype == leaf.dtype, key
        np.testing.assert_array_equal(
            np.asarray(rebuilt[key]).view(np.uint8), np.asarray(leaf).view(np.uint8),
            err_msg=key,
        )
    # a tree with ONLY inexact float leaves packs nothing at all
    packed_none, unpack_none = _pack_small_params({"s": jnp.ones((4,), f8)})
    assert packed_none is None and unpack_none is None
