"""graphcheck (analysis/fingerprint.py + analysis/ledger.py +
tools/graphcheck.py): fingerprint extraction/serialization, the semantic
differ with a deliberately planted regression in EACH class the gate exists
to catch (extra kv-axis concat, extra all-gather, >tolerance peak-memory
growth, dropped donation), the committed contracts/ passing clean against
the live flagship graphs, the graduation-ledger state machine, bench
floors, and the graphlint CLI exit-code semantics."""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import pytest

from perceiver_io_tpu.analysis import ledger as L
from perceiver_io_tpu.analysis.fingerprint import (
    PROGRAMS,
    DiffTolerances,
    GraphFingerprint,
    check_contracts,
    diff_fingerprints,
    fingerprint,
    load_contract,
    save_contract,
    validate_contract,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONTRACTS = os.path.join(REPO, "contracts")


# ------------------------------------------------------ extraction + roundtrip


def _toy_pair():
    a = jnp.ones((64, 64))
    return (a, a)


def test_fingerprint_roundtrip_and_stable_json():
    fn = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
    fp = fingerprint(fn, _toy_pair(), name="toy")
    assert fp.donation_aliases == 1  # same-shape donation commits even on CPU
    assert fp.memory is not None and fp.memory["gate_bytes"] > 0
    assert fp.dtype_histogram.get("float32", 0) >= 1

    # stable serialization: a round-trip re-serializes byte-identically
    j1 = fp.to_json()
    j2 = GraphFingerprint.from_dict(json.loads(j1)).to_json()
    assert j1 == j2
    # and a self-diff is empty
    assert diff_fingerprints(fp, GraphFingerprint.from_dict(fp.to_dict())).ok


def test_fingerprint_trace_only_skips_compiled_fields():
    fp = fingerprint(lambda x: x * 2, (jnp.ones((4,)),), name="t", compiled=False)
    assert fp.donation_aliases is None and fp.memory is None and fp.flops is None
    assert fp.n_ops >= 1


def test_memory_breakdown_fallback_matches_entry_shapes():
    from perceiver_io_tpu.analysis.memory import estimate_from_hlo, memory_breakdown

    fn = jax.jit(lambda x, w: (x @ w).sum())
    exe = fn.lower(jnp.ones((64, 128)), jnp.ones((128, 256))).compile()
    mb = memory_breakdown(exe)
    assert mb.method == "memory_analysis"
    assert mb.argument_bytes == (64 * 128 + 128 * 256) * 4
    est = estimate_from_hlo(exe.as_text())
    assert est.method == "hlo_estimate"
    assert est.argument_bytes == mb.argument_bytes
    assert est.output_bytes == 4  # scalar f32


# ------------------------------------------------- the differ, class by class


def _doctor(fp: GraphFingerprint, **changes) -> GraphFingerprint:
    d = fp.to_dict()
    d.update(changes)
    return GraphFingerprint.from_dict(d)


@pytest.fixture(scope="module")
def base_fp():
    return fingerprint(jax.jit(lambda s, b: s + b), _toy_pair(), name="p")


def test_diff_catches_new_hot_concat(base_fp):
    planted = _doctor(
        base_fp,
        hot_concats=[{"scope": "cross_attend/kv_concat", "axis": 2, "shape": [2, 328, 64]}],
    )
    d = diff_fingerprints(base_fp, planted)
    assert not d.ok and d.regressions[0].field == "hot_concats"
    assert "NEW concat" in d.regressions[0].detail
    # the mirror image is an improvement, not a failure
    back = diff_fingerprints(planted, base_fp)
    assert back.ok and back.improvements[0].field == "hot_concats"


def test_diff_catches_duplicate_and_reshaped_concat_at_existing_site(base_fp):
    """Scopes are not unique per call site (microbatch-unrolled chunks
    re-trace the same scope): MORE concats at an existing (scope, axis,
    shape), or the same site growing a fatter shape, must regress too."""
    site = {"scope": "cross_attend/kv_concat", "axis": 1, "shape": [2, 328, 64]}
    one = _doctor(base_fp, hot_concats=[site])
    two = _doctor(base_fp, hot_concats=[dict(site), dict(site)])
    d = diff_fingerprints(one, two)
    assert not d.ok and "1 -> 2" in d.regressions[0].detail

    grown = _doctor(base_fp, hot_concats=[dict(site, shape=[2, 4096, 64])])
    d2 = diff_fingerprints(one, grown)
    assert not d2.ok and "4096" in d2.regressions[0].detail


def test_diff_catches_extra_collective(base_fp):
    planted = _doctor(base_fp, collectives={"all-gather": {"count": 1, "bytes": 4096}})
    d = diff_fingerprints(base_fp, planted)
    assert not d.ok and d.regressions[0].field == "collectives.all-gather.count"


def test_diff_catches_peak_memory_growth_beyond_tolerance(base_fp):
    mem = dict(base_fp.memory)
    grown = dict(mem, gate_bytes=int(mem["gate_bytes"] * 1.10),
                 temp_bytes=int(mem["temp_bytes"] * 2 + 4096))
    d = diff_fingerprints(base_fp, _doctor(base_fp, memory=grown))
    assert not d.ok and d.regressions[0].field == "memory.gate_bytes"

    within = dict(mem, gate_bytes=int(mem["gate_bytes"] * 1.01))
    assert diff_fingerprints(base_fp, _doctor(base_fp, memory=within)).ok


def test_diff_catches_dropped_donation(tmp_path):
    donating = fingerprint(
        jax.jit(lambda s, b: s + b, donate_argnums=(0,)), _toy_pair(), name="train_flat"
    )
    dropped = fingerprint(jax.jit(lambda s, b: s + b), _toy_pair(), name="train_flat")
    assert donating.donation_aliases == 1 and dropped.donation_aliases == 0
    d = diff_fingerprints(donating, dropped)
    assert not d.ok and d.regressions[0].field == "donation_aliases"

    # and through the contract gate end to end
    save_contract(str(tmp_path), "train_flat", donating, reason="pin donation")
    res = check_contracts(
        str(tmp_path), programs=("train_flat",), live={"train_flat": dropped}
    )
    assert res["status"] == "regressed"
    assert "donation_aliases" in res["programs"]["train_flat"]["detail"]


def test_diff_refuses_cross_environment_comparison(base_fp):
    d = diff_fingerprints(base_fp, _doctor(base_fp, backend="tpu"))
    assert not d.comparable and "backend" in d.reason and not d.ok
    d = diff_fingerprints(base_fp, _doctor(base_fp, features=["twoseg"]))
    assert not d.comparable and "feature" in d.reason


def test_diff_tolerances_respected(base_fp):
    mem = dict(base_fp.memory, gate_bytes=int(base_fp.memory["gate_bytes"] * 1.07))
    strict = DiffTolerances(memory_frac=0.01)
    loose = DiffTolerances(memory_frac=0.25)
    assert not diff_fingerprints(base_fp, _doctor(base_fp, memory=mem), strict).ok
    assert diff_fingerprints(base_fp, _doctor(base_fp, memory=mem), loose).ok


# ------------------------------------------------------------- contract store


def test_contract_save_load_validate_roundtrip(tmp_path, base_fp):
    with pytest.raises(ValueError, match="reason"):
        save_contract(str(tmp_path), "p", base_fp, reason="  ")
    save_contract(str(tmp_path), "p", base_fp, reason="initial pin")
    doc = load_contract(str(tmp_path), "p")
    assert doc["updated_reason"] == "initial pin"
    assert validate_contract(doc) == []
    assert GraphFingerprint.from_dict(doc["fingerprint"]).to_dict() == base_fp.to_dict()

    bad = json.loads(json.dumps(doc))
    del bad["fingerprint"]["collectives"]
    assert any("collectives" in p for p in validate_contract(bad))


def test_missing_contract_reported(tmp_path, base_fp):
    res = check_contracts(str(tmp_path), programs=("train_flat",),
                          live={"train_flat": base_fp})
    assert res["status"] == "missing"


# ----------------------------------- the committed contracts vs the live graphs


@pytest.fixture(scope="module")
def flagship_fps():
    """Extract the real flagship fingerprints ONCE for the whole module —
    the same programs tools/graphcheck.py builds (8 virtual devices from
    conftest cover the data=2,fsdp=2 submesh)."""
    from perceiver_io_tpu.analysis.fingerprint import flagship_fingerprints

    return flagship_fingerprints()


def test_committed_contracts_pass_clean(flagship_fps):
    """THE gate: the live flagship graphs match the committed contracts/ on
    main — what `tasks.py perf` runs in CI."""
    res = check_contracts(CONTRACTS, live=flagship_fps)
    for name, entry in res["programs"].items():
        assert entry["status"] == "passed", f"{name}: {entry}"
    assert res["status"] == "passed"


def test_planted_kv_concat_regression_caught(flagship_fps):
    live = flagship_fps["train_flat"]
    planted = _doctor(
        live,
        hot_concats=list(live.to_dict()["hot_concats"])
        + [{"scope": "planted/cross_attend/kv_concat", "axis": 2, "shape": [2, 328, 64]}],
    )
    res = check_contracts(CONTRACTS, programs=("train_flat",),
                          live={"train_flat": planted})
    assert res["status"] == "regressed"
    assert "NEW concat" in res["programs"]["train_flat"]["detail"]


def test_planted_extra_all_gather_caught(flagship_fps):
    live = flagship_fps["train_overlap"]
    coll = {k: dict(v) for k, v in live.collectives.items()}
    coll["all-gather"]["count"] += 1
    res = check_contracts(CONTRACTS, programs=("train_overlap",),
                          live={"train_overlap": _doctor(live, collectives=coll)})
    assert res["status"] == "regressed"
    assert "all-gather" in res["programs"]["train_overlap"]["detail"]


def test_planted_peak_memory_growth_caught(flagship_fps):
    live = flagship_fps["train_flat"]
    mem = dict(live.memory)
    mem["gate_bytes"] = int(mem["gate_bytes"] * 1.10)
    res = check_contracts(CONTRACTS, programs=("train_flat",),
                          live={"train_flat": _doctor(live, memory=mem)})
    assert res["status"] == "regressed"
    assert "memory.gate_bytes" in res["programs"]["train_flat"]["detail"]


def test_stale_contract_reported_not_regressed(flagship_fps):
    live = flagship_fps["decode"]
    res = check_contracts(CONTRACTS, programs=("decode",),
                          live={"decode": _doctor(live, backend="tpu")})
    assert res["status"] == "stale"
    assert "--update" in res["programs"]["decode"]["detail"]


# ------------------------------------------------------------------ the ledger


def test_committed_ledger_validates_and_floors_hold():
    ledger = L.load_ledger(CONTRACTS)
    assert ledger is not None, "contracts/ledger.json must be committed"
    assert L.validate_ledger(ledger) == []
    # both flagship levers tracked, still staged until a TPU A/B lands
    assert L.feature_state(ledger, "twoseg") == "staged"
    assert L.feature_state(ledger, "overlap") == "staged"
    assert L.default_on_features(ledger) == ()
    # the committed BENCH artifacts meet their own pinned floors
    assert L.check_bench_floors(ledger, REPO) == []


def test_ledger_state_machine():
    ledger = {
        "schema_version": 1,
        "features": {
            "f": {"state": "staged",
                  "history": [{"state": "staged", "reason": "landed"}]}
        },
    }
    with pytest.raises(ValueError, match="illegal transition"):
        L.advance(ledger, "f", "default_on", reason="skipping measured")
    with pytest.raises(ValueError, match="reason"):
        L.advance(ledger, "f", "measured", reason="")

    measured = L.advance(ledger, "f", "measured", reason="BENCH_r07 A/B +9%",
                         evidence={"bench": "BENCH_r07"})
    on = L.advance(measured, "f", "default_on", reason="winner flipped on")
    assert L.feature_state(on, "f") == "default_on"
    assert L.default_on_features(on) == ("f",)
    # demotion jumps backward but must be reasoned (validated by advance)
    demoted = L.advance(on, "f", "staged", reason="regression found on v6e")
    assert L.feature_state(demoted, "f") == "staged"
    assert L.validate_ledger(demoted) == []


def test_ledger_validation_catches_bad_history():
    skip = {
        "schema_version": 1,
        "features": {"f": {"state": "default_on", "history": [
            {"state": "staged", "reason": "x"},
            {"state": "default_on", "reason": "jumped"},
        ]}},
    }
    assert any("illegal transition" in p for p in L.validate_ledger(skip))
    unreasoned = {
        "schema_version": 1,
        "features": {"f": {"state": "staged", "history": [{"state": "staged", "reason": " "}]}},
    }
    assert any("reason" in p for p in L.validate_ledger(unreasoned))
    mismatch = {
        "schema_version": 1,
        "features": {"f": {"state": "measured",
                           "history": [{"state": "staged", "reason": "x"}]}},
    }
    assert any("last history state" in p for p in L.validate_ledger(mismatch))


def test_bench_floor_failure_detected(tmp_path):
    ledger = {
        "schema_version": 1,
        "features": {},
        "floors": {
            "train": {"artifact": "BENCH_r*.json", "key": "parsed.vs_baseline", "min": 99.0},
            "ghost": {"artifact": "NO_SUCH_r*.json", "key": "parsed.value", "min": 0.0},
        },
    }
    failures = L.check_bench_floors(ledger, REPO)
    assert any("below floor 99.0" in f for f in failures)
    assert any("no artifact matches" in f for f in failures)


def test_floor_match_clause_selects_latest_matching_round(tmp_path):
    """Mode-aware floors: one artifact family holds rounds of several modes
    (LOAD_r01 sequential-closed, r02 engine-closed, r03 engine-open) —
    a floor's ``match`` clause must pin it to the latest round of ITS mode,
    not whatever mode committed last. ``"*"`` means present-and-non-null."""
    import json as _json

    for n, doc in (
        (1, {"mode": "closed", "summary": {"v": 10.0}}),
        (2, {"mode": "closed", "summary": {"v": 9.0, "engine": {"slots": 8}}}),
        (3, {"mode": "open", "summary": {"v": 3.0, "engine": {"slots": 8}}}),
    ):
        (tmp_path / f"LOAD_r{n:02d}.json").write_text(_json.dumps(doc))
    ledger = {
        "schema_version": 1,
        "features": {},
        "floors": {
            "closed_engine": {"artifact": "LOAD_r*.json", "key": "summary.v", "min": 5.0,
                              "match": {"mode": "closed", "summary.engine": "*"}},
            "open_rate": {"artifact": "LOAD_r*.json", "key": "summary.v", "min": 5.0,
                          "match": {"mode": "open"}},
            "any_latest": {"artifact": "LOAD_r*.json", "key": "summary.v", "min": 5.0},
            "no_such_mode": {"artifact": "LOAD_r*.json", "key": "summary.v", "min": 0.0,
                             "match": {"mode": "chaotic"}},
        },
    }
    failures = L.check_bench_floors(ledger, str(tmp_path))
    # closed_engine reads r02 (9.0 >= 5.0) even though r03 committed later
    assert not any(f.startswith("closed_engine") for f in failures), failures
    # open_rate reads r03 (3.0 < 5.0) and names the round it read
    assert any(f.startswith("open_rate") and "LOAD_r03" in f for f in failures), failures
    # an unmatched floor keeps plain latest-round-wins (r03: 3.0 < 5.0)
    assert any(f.startswith("any_latest") and "LOAD_r03" in f for f in failures), failures
    # a clause nothing satisfies is a loud gap, not a silent pass
    assert any(f.startswith("no_such_mode") and "no artifact" in f for f in failures), failures
    # the committed ledger's LOAD floors carry the clauses this test pins
    committed = L.load_ledger(CONTRACTS)
    assert committed["floors"]["engine_open_achieved_rps"]["match"]["mode"] == "open"
    assert committed["floors"]["engine_throughput_tok_s"]["match"]["mode"] == "closed"


# --------------------------------------------------------- bench.py telemetry


def test_graphcheck_telemetry_block_shape():
    """The `telemetry.graphcheck` block bench results carry: never raises,
    records the contract verdict for the two cheapest programs."""
    from perceiver_io_tpu.analysis.fingerprint import graphcheck_telemetry

    block = graphcheck_telemetry()
    assert block["status"] in ("passed", "regressed", "stale", "missing", "error")
    assert block["status"] == "passed", block  # contracts are committed + clean
    assert set(block["programs"]) == {"train_flat", "decode"}


def test_bench_telemetry_records_graphcheck_status():
    import bench

    t = bench.telemetry_fields(None, 0.01)["telemetry"]
    assert "graphcheck" not in t  # unresolved outside main()
    old = bench._GRAPHCHECK_STATUS
    try:
        bench._GRAPHCHECK_STATUS = {"status": "skipped"}
        t = bench.telemetry_fields(None, 0.01)["telemetry"]
        assert t["graphcheck"] == {"status": "skipped"}
    finally:
        bench._GRAPHCHECK_STATUS = old


# ------------------------------------------------- graphlint CLI exit semantics


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_tool_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _canned_reports(violations):
    from perceiver_io_tpu.analysis.check import Report

    return {
        "train": Report(
            name="train_step", backend="cpu", n_ops=3,
            rules_run=("hot-concat",), rules_skipped=(),
            violations=violations, allowed=[],
        )
    }


def test_graphlint_cli_exit_codes(monkeypatch, tmp_path):
    """0 = clean, 1 = violations at/above --fail-on, 3 = the linter itself
    crashed — CI must never read a rule error as either verdict."""
    from perceiver_io_tpu.analysis import flagship
    from perceiver_io_tpu.analysis.rules import Violation

    gl = _load_tool("graphlint")

    monkeypatch.setattr(flagship, "lint_flagship", lambda **kw: _canned_reports([]))
    out = str(tmp_path / "clean.json")
    assert gl.main(["--targets", "train", "--json", out]) == 0
    assert json.load(open(out))["train"]["clean"] is True

    bad = [Violation(rule="hot-concat", severity="error", scope="s", message="planted")]
    monkeypatch.setattr(flagship, "lint_flagship", lambda **kw: _canned_reports(bad))
    out = str(tmp_path / "bad.json")
    assert gl.main(["--targets", "train", "--fail-on", "error", "--json", out]) == 1
    assert json.load(open(out))["train"]["counts"]["error"] == 1
    # verdict severity below the bar: violations exist but the gate passes
    assert gl.main(["--targets", "train", "--fail-on", "none"]) == 0

    def boom(**kw):
        raise RuntimeError("rule exploded")

    monkeypatch.setattr(flagship, "lint_flagship", boom)
    assert gl.main(["--targets", "train"]) == 3


def test_graphlint_cli_unknown_rule_is_usage_error(capsys):
    """A typo'd --rules name must exit with the argparse USAGE code (2) and
    list the registered rules — not silently skip the rule (the old
    behavior) and not crash as exit 3."""
    import pytest

    gl = _load_tool("graphlint")
    with pytest.raises(SystemExit) as e:
        gl.main(["--rules", "no-such-rule,hot-concat"])
    assert e.value.code == 2
    err = capsys.readouterr().err
    assert "no-such-rule" in err and "registered rules" in err
    assert "hot-concat" in err and "rng-key-reuse" in err

    # same contract for --programs
    with pytest.raises(SystemExit) as e2:
        gl.main(["--programs", "bogus"])
    assert e2.value.code == 2
    assert "train_overlap" in capsys.readouterr().err
