"""Packed (slots-major) flash kernels: parity with the heads-major path and
the dense reference, values and gradients (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_packed,
    set_default_flash,
)

pytestmark = pytest.mark.slow

B, H, DQK, DV = 2, 4, 16, 16


@pytest.fixture(autouse=True)
def _force_flash():
    set_default_flash(True)
    yield
    set_default_flash(None)


def _data(nq, nkv, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, nq, H * DQK)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, nkv, H * DQK)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, nkv, H * DV)), jnp.float32)
    return q, k, v


def _to_heads(x, d):
    b, n, _ = x.shape
    return x.reshape(b, n, H, d).transpose(0, 2, 1, 3)


def _from_heads(x):
    b, h, n, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, n, h * d)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("nq,nkv", [(256, 256), (128, 384), (256, 640)])
def test_packed_matches_heads_major(causal, nq, nkv):
    q, k, v = _data(nq, nkv)
    pad = jnp.zeros((B, nkv), bool).at[:, :3].set(True)
    ref = flash_attention(
        _to_heads(q, DQK), _to_heads(k, DQK), _to_heads(v, DV),
        pad_mask=pad, causal=causal, block_q=128, block_kv=128,
    )
    got = flash_attention_packed(
        q, k, v, num_heads=H, pad_mask=pad, causal=causal, block_q=128, block_kv=128
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(_from_heads(ref)), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_packed_grads_match_heads_major(causal):
    nq, nkv = 128, 384
    q, k, v = _data(nq, nkv, seed=1)
    pad = jnp.zeros((B, nkv), bool).at[:, :2].set(True)

    def loss_packed(q_, k_, v_):
        o = flash_attention_packed(
            q_, k_, v_, num_heads=H, pad_mask=pad, causal=causal, block_q=128, block_kv=128
        )
        return jnp.sum(o**2)

    def loss_ref(q_, k_, v_):
        o = flash_attention(
            _to_heads(q_, DQK), _to_heads(k_, DQK), _to_heads(v_, DV),
            pad_mask=pad, causal=causal, block_q=128, block_kv=128,
        )
        return jnp.sum(o**2)

    g_p = jax.grad(loss_packed, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_p, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-4)


def test_packed_single_head_wide():
    # 1-head configs (vision-style) with d multiple of 8
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(1, 128, 136)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 256, 136)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 256, 136)), jnp.float32)
    got = flash_attention_packed(q, k, v, num_heads=1, block_q=128, block_kv=128)
    ref = flash_attention(q.reshape(1, 1, 128, 136),
                          k.reshape(1, 1, 256, 136), v.reshape(1, 1, 256, 136),
                          block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref[0].transpose(1, 0, 2).reshape(1, 128, 136)), atol=2e-5)
