"""Fused LayerNorm kernels vs flax.linen.LayerNorm: values and gradients
(kernels run in Pallas interpret mode on CPU, forced via
set_default_fused_ln — the flash-kernel test pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from perceiver_io_tpu.ops.layernorm import (
    FusedLayerNorm,
    layer_norm,
    set_default_fused_ln,
)


@pytest.fixture(autouse=True)
def _force_fused():
    set_default_fused_ln(True)
    yield
    set_default_fused_ln(None)


@pytest.mark.parametrize("shape", [(4, 32, 128), (2, 24, 256), (96, 128)])
def test_matches_flax_layernorm(rng, shape):
    c = shape[-1]
    x = jnp.asarray(rng.normal(size=shape), jnp.float32) * 3 + 1
    scale = jnp.asarray(rng.normal(size=(c,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(c,)), jnp.float32)

    ref_mod = nn.LayerNorm(epsilon=1e-5)
    ref = ref_mod.apply({"params": {"scale": scale, "bias": bias}}, x)
    got = layer_norm(x, scale, bias, eps=1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_gradients_match_fallback(rng):
    shape, c = (4, 32, 128), 128
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    scale = jnp.asarray(1 + 0.1 * rng.normal(size=(c,)), jnp.float32)
    bias = jnp.asarray(0.1 * rng.normal(size=(c,)), jnp.float32)
    w = jnp.asarray(rng.normal(size=shape), jnp.float32)

    def loss_fused(x, scale, bias):
        return jnp.sum(layer_norm(x, scale, bias) * w)

    def loss_ref(x, scale, bias):
        ref = nn.LayerNorm(epsilon=1e-5).apply({"params": {"scale": scale, "bias": bias}}, x)
        return jnp.sum(ref * w)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(x, scale, bias)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
    for name, a, b in zip(("dx", "dscale", "dbias"), g_fused, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4, err_msg=name
        )


def test_module_param_naming_matches_nn_layernorm(rng):
    x = jnp.asarray(rng.normal(size=(2, 16, 128)), jnp.float32)
    params = FusedLayerNorm(epsilon=1e-5).init(jax.random.PRNGKey(0), x)
    assert set(params["params"]) == {"scale", "bias"}
    ref_params = nn.LayerNorm(epsilon=1e-5).init(jax.random.PRNGKey(0), x)
    assert jax.tree.map(lambda a: a.shape, params) == jax.tree.map(lambda a: a.shape, ref_params)


def test_bf16_io_f32_stats(rng):
    x = jnp.asarray(rng.normal(size=(4, 16, 128)), jnp.bfloat16)
    scale = jnp.ones((128,), jnp.float32)
    bias = jnp.zeros((128,), jnp.float32)
    got = layer_norm(x, scale, bias, dtype=jnp.bfloat16)
    assert got.dtype == jnp.bfloat16
    ref = nn.LayerNorm(epsilon=1e-5, dtype=jnp.bfloat16).apply(
        {"params": {"scale": scale, "bias": bias}}, x
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), atol=2e-2, rtol=2e-2
    )


def test_f32_input_bf16_dtype_keeps_f32_stats(rng):
    """A bf16-dtype module receiving f32 activations must compute stats from
    the UNROUNDED input (flax semantics) — kernel and fallback must agree."""
    x = jnp.asarray(rng.normal(size=(4, 32, 128)), jnp.float32) * 2 + 0.5
    scale = jnp.asarray(1 + 0.1 * rng.normal(size=(128,)), jnp.float32)
    bias = jnp.asarray(0.1 * rng.normal(size=(128,)), jnp.float32)

    got = layer_norm(x, scale, bias, dtype=jnp.bfloat16)
    set_default_fused_ln(False)
    ref = layer_norm(x, scale, bias, dtype=jnp.bfloat16)
    set_default_fused_ln(True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), atol=1e-2, rtol=1e-2
    )


def test_odd_width_falls_back(rng):
    # 96 % 128 != 0: fallback path, still exact vs flax
    x = jnp.asarray(rng.normal(size=(3, 8, 96)), jnp.float32)
    scale = jnp.ones((96,), jnp.float32)
    bias = jnp.zeros((96,), jnp.float32)
    ref = nn.LayerNorm(epsilon=1e-5).apply({"params": {"scale": scale, "bias": bias}}, x)
    np.testing.assert_allclose(
        np.asarray(layer_norm(x, scale, bias)), np.asarray(ref), atol=1e-6
    )
