"""analysis/ (graphlint): each rule against a synthetic graph with a known
planted violation (positive) and a clean twin (negative), allowlist
behavior, the report/JSON surface, the trainer's ``graphlint`` event, and
a smoke lint of the real flagship step functions on CPU."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu import analysis
from perceiver_io_tpu.analysis import LintPolicy


# ---------------------------------------------------------------- dtype-drift


def test_dtype_drift_fires_on_f32_matmul_in_bf16_scope():
    def planted(x):
        with jax.named_scope("block"):
            return x.astype(jnp.float32) @ jnp.ones((8, 8), jnp.float32)

    report = analysis.check(
        planted,
        (jnp.ones((4, 8), jnp.bfloat16),),
        rules=("dtype-drift",),
        policy=LintPolicy(bf16_scopes=("*block*",)),
    )
    assert [v.rule for v in report.violations] == ["dtype-drift"]
    assert report.violations[0].scope == "block"
    assert not report.ok()


def test_dtype_drift_clean_on_bf16_matmul_and_undeclared_scope():
    def clean(x):
        with jax.named_scope("block"):
            return x @ jnp.ones((8, 8), jnp.bfloat16)

    policy = LintPolicy(bf16_scopes=("*block*",))
    x = jnp.ones((4, 8), jnp.bfloat16)
    assert analysis.check(clean, (x,), rules=("dtype-drift",), policy=policy).clean

    def f32_elsewhere(x):  # f32 matmul OUTSIDE the declared scope: fine
        return x.astype(jnp.float32) @ jnp.ones((8, 8), jnp.float32)

    assert analysis.check(f32_elsewhere, (x,), rules=("dtype-drift",), policy=policy).clean


# -------------------------------------------------------------- const-capture


def test_const_capture_fires_on_closed_over_weight():
    big = np.ones((256, 256), np.float32)  # 256 KB >= the 64 KB default

    def planted(x):
        return x @ big

    report = analysis.check(planted, (jnp.ones((4, 256)),), rules=("const-capture",))
    assert [v.rule for v in report.violations] == ["const-capture"]
    assert "256x256" in report.violations[0].message


def test_const_capture_clean_below_threshold_and_for_arguments():
    small = np.ones((16, 16), np.float32)  # 1 KB

    def clean(x):
        return x @ small

    assert analysis.check(clean, (jnp.ones((4, 16)),), rules=("const-capture",)).clean

    def weights_as_args(x, w):  # the fix the rule demands
        return x @ w

    big = jnp.ones((256, 256))
    assert analysis.check(
        weights_as_args, (jnp.ones((4, 256)), big), rules=("const-capture",)
    ).clean


# ----------------------------------------------------------------- hot-concat


def _seq_concat_in(scope_name):
    def fn(a, b):
        with jax.named_scope(scope_name):
            kv = jnp.concatenate([a, b], axis=1)  # (B, Np+Nq, C) seq-axis build
            return kv.sum()

    return fn


_A, _B = jnp.ones((2, 200, 32)), jnp.ones((2, 128, 32))


def test_hot_concat_fires_in_attention_scope():
    report = analysis.check(
        _seq_concat_in("cross_attend"), (_A, _B), rules=("hot-concat",)
    )
    assert [v.rule for v in report.violations] == ["hot-concat"]
    assert report.violations[0].op == "concatenate"
    assert "cross_attend" in report.violations[0].scope


def test_hot_concat_clean_outside_hot_scope_and_for_channel_glue():
    # same concat, cold scope: no violation
    assert analysis.check(
        _seq_concat_in("embed"), (_A, _B), rules=("hot-concat",)
    ).clean

    # RoPE-style channel-axis glue inside a hot scope: the concatenated
    # axis is short, the structural filter keeps it out
    def rotate_half(x):
        with jax.named_scope("cross_attend"):
            x1, x2 = jnp.split(x, 2, axis=-1)
            return jnp.concatenate([-x2, x1], axis=-1).sum()

    assert analysis.check(
        rotate_half, (jnp.ones((2, 512, 32)),), rules=("hot-concat",)
    ).clean


def test_hot_concat_forbidden_dim_fires_anywhere():
    """The twoseg-style guarantee: a concat producing a tensor with the
    forbidden kv-length dimension ON THE CONCATENATED AXIS fires regardless
    of scope."""
    n_kv = _A.shape[1] + _B.shape[1]
    report = analysis.check(
        _seq_concat_in("embed"),  # cold scope — only the dim trigger applies
        (_A, _B),
        rules=("hot-concat",),
        policy=LintPolicy(concat_dim_sizes=(n_kv,)),
    )
    assert len(report.violations) == 1
    assert "forbidden dimension" in report.violations[0].message


def test_hot_concat_forbidden_dim_ignores_untouched_axes():
    """An axis that merely COINCIDES with the forbidden size must not fire:
    a channel-axis rotate-half concat on a (B, n_kv, C) tensor joins the
    last axis — the untouched seq axis equaling n_kv is not a kv build."""
    def rotate_half(x):
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([-x2, x1], axis=-1).sum()

    report = analysis.check(
        rotate_half,
        (jnp.ones((2, 48, 8)),),
        rules=("hot-concat",),
        policy=LintPolicy(concat_dim_sizes=(48,)),
    )
    assert report.clean, report.format()


def test_hot_gather_fires_on_unsorted_gather_in_attention_scope():
    idx = jnp.asarray(np.random.default_rng(0).integers(0, 512, size=(2048,)))

    def planted(table):
        with jax.named_scope("self_attend"):
            return jnp.take(table, idx, axis=0).sum()

    report = analysis.check(planted, (jnp.ones((512, 64)),), rules=("hot-concat",))
    assert [v.op for v in report.violations] == ["gather"]

    def cold(table):  # same gather outside the attention scopes: clean
        return jnp.take(table, idx, axis=0).sum()

    assert analysis.check(cold, (jnp.ones((512, 64)),), rules=("hot-concat",)).clean


# ------------------------------------------------------------ callback-in-jit


def test_callback_in_jit_fires_on_debug_print():
    def planted(x):
        with jax.named_scope("decode"):
            jax.debug.print("x={}", x.sum())
        return x * 2

    report = analysis.check(planted, (jnp.ones((4,)),), rules=("callback-in-jit",))
    assert [v.rule for v in report.violations] == ["callback-in-jit"]
    assert "decode" in report.violations[0].scope

    def clean(x):
        return x * 2

    assert analysis.check(clean, (jnp.ones((4,)),), rules=("callback-in-jit",)).clean


# ----------------------------------------------------------- donation-dropped


def test_donation_dropped_fires_when_donation_unusable():
    # the donated f32 buffer cannot back the bf16 output — jax drops the
    # donation at lowering and the compiled module carries no alias
    fn = jax.jit(lambda s: (s * 2).astype(jnp.bfloat16), donate_argnums=(0,))
    report = analysis.check(
        fn,
        (jnp.ones((64, 64), jnp.float32),),
        rules=("donation-dropped",),
        policy=LintPolicy(expect_donation=True),
    )
    assert [v.rule for v in report.violations] == ["donation-dropped"]
    # on CPU the drop is an environment limitation, downgraded to warn
    # (utils/compat.donation_safe documents why donation is off there)
    assert report.violations[0].severity == ("warn" if jax.default_backend() == "cpu" else "error")
    assert not report.clean


def test_donation_rule_skipped_without_declared_donation():
    report = analysis.check(
        lambda x: x * 2, (jnp.ones((4,)),), rules=("donation-dropped",)
    )
    assert report.rules_skipped == ("donation-dropped",)
    assert report.clean


def test_donation_detected_from_lowered_module_with_compiled_true():
    """pjit hides donate_argnums attributes (jax 0.4.37), but with
    compiled=True the rule reads the lowered args_info — a donating jitted
    fn whose donation is dropped fires with NO policy hints."""
    fn = jax.jit(lambda s: (s * 2).astype(jnp.bfloat16), donate_argnums=(0,))
    report = analysis.check(
        fn, (jnp.ones((64, 64), jnp.float32),),
        rules=("donation-dropped",), compiled=True,
    )
    assert [v.rule for v in report.violations] == ["donation-dropped"]


def test_donation_committed_is_clean():
    # same-shape same-dtype donation: XLA commits the alias even on CPU
    fn = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
    report = analysis.check(
        fn,
        (jnp.ones((64, 64)), jnp.ones((64, 64))),
        rules=("donation-dropped",),
        policy=LintPolicy(expect_donation=True),
    )
    assert report.clean


# ---------------------------------------------------------- collective-budget


def _psum_fn():
    from jax.sharding import Mesh, PartitionSpec as P

    from perceiver_io_tpu.utils.compat import shard_map

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("x",))
    fn = shard_map(
        lambda x: jax.lax.psum(x, "x"), mesh=mesh, in_specs=P("x"), out_specs=P()
    )
    return jax.jit(fn), (jnp.ones((len(jax.devices()), 4)),)


def test_collective_budget_fires_over_budget():
    fn, args = _psum_fn()
    report = analysis.check(
        fn,
        args,
        rules=("collective-budget",),
        policy=LintPolicy(collective_budget={"all-reduce": 0}),
    )
    assert [v.op for v in report.violations] == ["all-reduce"]
    assert not report.ok()


def test_collective_budget_clean_within_budget_and_total_form():
    fn, args = _psum_fn()
    assert analysis.check(
        fn, args, rules=("collective-budget",),
        policy=LintPolicy(collective_budget={"all-reduce": 4}),
    ).clean
    report = analysis.check(
        fn, args, rules=("collective-budget",),
        policy=LintPolicy(collective_budget={"total": 0}),
    )
    assert len(report.violations) == 1 and "total budget" in report.violations[0].message


# --------------------------------------------------------- peak-memory-budget


def test_peak_memory_budget_fires_over_budget():
    def planted(x):
        return (x @ x.T).sum()  # 512x512 f32 temp = 1 MB

    x = jnp.ones((512, 128))
    report = analysis.check(
        planted, (x,), rules=("peak-memory-budget",),
        policy=LintPolicy(peak_memory_budget_bytes=64 << 10),
    )
    assert [v.rule for v in report.violations] == ["peak-memory-budget"]
    assert "MB" in report.violations[0].message and not report.ok()


def test_peak_memory_budget_clean_within_budget_and_skipped_undeclared():
    def fn(x):
        return (x @ x.T).sum()

    x = jnp.ones((512, 128))
    assert analysis.check(
        fn, (x,), rules=("peak-memory-budget",),
        policy=LintPolicy(peak_memory_budget_bytes=64 << 20),
    ).clean
    report = analysis.check(fn, (x,), rules=("peak-memory-budget",))
    assert report.rules_skipped == ("peak-memory-budget",)


# ----------------------------------------------------- replicated-large-tensor


def _mesh_2x4():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "fsdp"))


def _partitioned_matmul(w_spec):
    """x @ a with ``a`` placed by ``w_spec`` over a data x fsdp mesh — the
    compiled module is partitioned (num_partitions=8), so replication of
    ``a`` is a real per-device HBM choice."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_2x4()
    a = jax.device_put(jnp.ones((512, 512)), NamedSharding(mesh, w_spec))  # 1 MB f32
    x = jax.device_put(jnp.ones((8, 512)), NamedSharding(mesh, P("data")))
    return jax.jit(lambda x, a: (x @ a).sum()), (x, a)


def test_replicated_large_tensor_fires_on_replicated_weight():
    from jax.sharding import PartitionSpec as P

    fn, args = _partitioned_matmul(P())  # fully replicated
    report = analysis.check(
        fn, args, rules=("replicated-large-tensor",),
        policy=LintPolicy(replicated_bytes_limit=1 << 20),
    )
    assert [v.rule for v in report.violations] == ["replicated-large-tensor"]
    assert "replicated" in report.violations[0].message


def test_replicated_large_tensor_clean_when_sharded_or_small_or_unpartitioned():
    from jax.sharding import PartitionSpec as P

    fn, args = _partitioned_matmul(P("fsdp"))  # sharded over fsdp: fine
    policy = LintPolicy(replicated_bytes_limit=1 << 20)
    assert analysis.check(fn, args, rules=("replicated-large-tensor",), policy=policy).clean

    fn, args = _partitioned_matmul(P())  # replicated but UNDER the limit
    assert analysis.check(
        fn, args, rules=("replicated-large-tensor",),
        policy=LintPolicy(replicated_bytes_limit=16 << 20),
    ).clean

    # single-device module: replication is not a choice — never fires
    plain = jax.jit(lambda x: (x @ jnp.ones((512, 512))).sum())
    assert analysis.check(
        plain, (jnp.ones((8, 512)),), rules=("replicated-large-tensor",), policy=policy
    ).clean


# ------------------------------------------------------------ implicit-reshard


def _ppermute_fn():
    from jax.sharding import Mesh, PartitionSpec as P

    from perceiver_io_tpu.utils.compat import shard_map

    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("x",))
    fn = shard_map(
        lambda x: jax.lax.ppermute(x, "x", [(i, (i + 1) % n) for i in range(n)]),
        mesh=mesh, in_specs=P("x"), out_specs=P("x"),
    )
    return jax.jit(fn), (jnp.ones((n, 4)),)


def test_implicit_reshard_fires_on_unbudgeted_permute():
    fn, args = _ppermute_fn()
    report = analysis.check(
        fn, args, rules=("implicit-reshard",), policy=LintPolicy(reshard_budget={})
    )
    assert [v.op for v in report.violations] == ["collective-permute"]
    assert "reshard" in report.violations[0].message


def test_implicit_reshard_clean_within_budget_and_skipped_undeclared():
    fn, args = _ppermute_fn()
    assert analysis.check(
        fn, args, rules=("implicit-reshard",),
        policy=LintPolicy(reshard_budget={"collective-permute": 8}),
    ).clean
    report = analysis.check(fn, args, rules=("implicit-reshard",))
    assert report.rules_skipped == ("implicit-reshard",)


# -------------------------------------------------------------- rng-key-reuse


def test_rng_key_reuse_fires_on_double_draw():
    def planted(key):
        k1, _ = jax.random.split(key)
        return jax.random.uniform(k1, (4,)) + jax.random.uniform(k1, (4,))

    report = analysis.check(
        planted, (jax.random.PRNGKey(0),), rules=("rng-key-reuse",),
        policy=LintPolicy(check_rng=True),
    )
    assert [v.rule for v in report.violations] == ["rng-key-reuse"]
    assert "split" in report.violations[0].message and not report.ok()


def test_rng_key_reuse_clean_when_split_and_skipped_undeclared():
    def clean(key):
        k1, k2 = jax.random.split(key)
        return jax.random.uniform(k1, (4,)) + jax.random.uniform(k2, (4,))

    assert analysis.check(
        clean, (jax.random.PRNGKey(0),), rules=("rng-key-reuse",),
        policy=LintPolicy(check_rng=True),
    ).clean

    report = analysis.check(clean, (jax.random.PRNGKey(0),), rules=("rng-key-reuse",))
    assert report.rules_skipped == ("rng-key-reuse",)


def _shard_map_draw(fold_device_index: bool):
    from jax.sharding import Mesh, PartitionSpec as P

    from perceiver_io_tpu.utils.compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(-1), ("data",))

    def body(x, key):
        if fold_device_index:
            key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        return x * jax.random.uniform(key, x.shape)

    fn = shard_map(
        body, mesh=mesh, in_specs=(P("data"), P()), out_specs=P("data"),
        check_rep=False,
    )
    return fn, (jnp.ones((8, 4)), jax.random.PRNGKey(0))


def test_rng_key_reuse_fires_on_replicated_key_in_shard_map():
    fn, args = _shard_map_draw(fold_device_index=False)
    report = analysis.check(
        fn, args, rules=("rng-key-reuse",), policy=LintPolicy(check_rng=True)
    )
    assert [v.rule for v in report.violations] == ["rng-key-reuse"]
    assert "REPLICATED" in report.violations[0].message


def test_rng_key_reuse_clean_with_device_index_fold():
    fn, args = _shard_map_draw(fold_device_index=True)
    assert analysis.check(
        fn, args, rules=("rng-key-reuse",), policy=LintPolicy(check_rng=True)
    ).clean


def test_rng_key_reuse_catches_the_pr4_unfolded_overlap_key():
    """The PR-4 regression, replayed statically: the REAL overlap step built
    with its device-index fold_in stripped (the shipped bug) must be caught
    by rng-key-reuse; the shipped step must lint clean. The runtime
    draw-variance test (tests/test_overlap.py) pins the behavior; this pins
    that the bug class can no longer reach runtime."""
    from unittest import mock

    from perceiver_io_tpu.parallel import make_mesh, shard_batch
    from perceiver_io_tpu.parallel.overlap import OverlapConfig, make_overlap_train_step
    from perceiver_io_tpu.training import TrainState, make_optimizer
    from perceiver_io_tpu.training.loop import shard_train_state

    def rng_loss(params, batch, rng):
        u = jax.random.uniform(rng, ())  # the in-graph draw (dropout stand-in)
        loss = jnp.mean(batch["x"]) * sum(jnp.sum(v) for v in jax.tree.leaves(params))
        return loss * 0.0 + u, {"loss": u}

    rng_loss.uniform_weighting = True

    mesh = make_mesh(data=2, fsdp=4)
    cfg = OverlapConfig(mesh=mesh, bucket_bytes=1 << 14, min_weight_size=32)
    state = shard_train_state(
        TrainState.create(
            lambda *a, **k: None, {"w": jnp.ones((16, 8))},
            make_optimizer(1e-2, optimizer="sgd"), jax.random.PRNGKey(1),
        ),
        mesh, min_weight_size=32,
    )
    batch = shard_batch({"x": jnp.ones((16, 8), jnp.float32)}, mesh)
    policy = LintPolicy(check_rng=True)

    shipped = make_overlap_train_step(rng_loss, cfg, microbatch=2, donate=False)
    assert analysis.check(
        shipped, (state, batch), rules=("rng-key-reuse",), policy=policy
    ).clean

    # strip the fold at trace time: exactly the code PR 4 shipped with
    with mock.patch.object(jax.random, "fold_in", lambda key, data: key):
        bugged = make_overlap_train_step(rng_loss, cfg, microbatch=2, donate=False)
        report = analysis.check(
            bugged, (state, batch), rules=("rng-key-reuse",), policy=policy
        )
    assert not report.ok(), "the PR-4 replicated-key bug must be caught statically"
    assert all(v.rule == "rng-key-reuse" for v in report.violations)
    assert "REPLICATED" in report.violations[0].message


# --------------------------------------------------------------- dead-compute


def test_dead_compute_weights_matmul_error_reshape_info():
    def planted(x):
        dead_mm = x @ x.T  # noqa: F841 — 33 MFLOP of dead compute
        dead_rs = jnp.reshape(x, (-1,))  # noqa: F841 — dead data movement
        return jnp.tanh(x).sum()

    report = analysis.check(
        planted, (jnp.ones((256, 256)),), rules=("dead-compute",),
        policy=LintPolicy(dead_compute_min_flops=1 << 20),
    )
    errors = [v for v in report.violations if v.severity == "error"]
    assert [v.op for v in errors] == ["dot_general"]
    assert "MFLOP" in errors[0].message and not report.ok()
    infos = [v for v in report.violations if v.severity == "info"]
    assert infos and "data-movement" in infos[0].message


def test_dead_compute_clean_and_skipped_undeclared():
    def clean(x):
        return (x @ x.T).sum()

    policy = LintPolicy(dead_compute_min_flops=1 << 20)
    assert analysis.check(
        clean, (jnp.ones((128, 128)),), rules=("dead-compute",), policy=policy
    ).clean
    report = analysis.check(clean, (jnp.ones((128, 128)),), rules=("dead-compute",))
    assert report.rules_skipped == ("dead-compute",)


# -------------------------------------------------------------- sharding-flow


def test_sharding_flow_predicts_reshard_points():
    from jax.sharding import PartitionSpec as P

    def planted(x, y):
        a = x[0:2]  # slice along the data-sharded batch dim
        return a.sum() + (x + y).sum()  # and a data-vs-fsdp elementwise join

    report = analysis.check(
        planted,
        (jnp.ones((4, 4)), jnp.ones((4, 4))),
        rules=("sharding-flow",),
        policy=LintPolicy(sharding_flow=(P("data"), P("fsdp"))),
    )
    kinds = sorted(v.message.split(" ")[1] for v in report.violations)
    assert kinds == ["mismatched-operands", "sliced-sharded-dim"]
    assert all("chain:" in v.message for v in report.violations)


def test_sharding_flow_clean_when_aligned_and_skipped_undeclared():
    from jax.sharding import PartitionSpec as P

    def clean(x, w):
        return jnp.tanh(x @ w).sum()

    args = (jnp.ones((8, 16)), jnp.ones((16, 4)))
    assert analysis.check(
        clean, args, rules=("sharding-flow",),
        policy=LintPolicy(sharding_flow=(P("data"), P(None, "fsdp"))),
    ).clean
    report = analysis.check(clean, args, rules=("sharding-flow",))
    assert report.rules_skipped == ("sharding-flow",)


def test_sharding_flow_agrees_with_compiled_reshard_contracts():
    """The acceptance pin: sharding-flow's pre-compile predictions must
    agree with the compiled-HLO reshard findings recorded in the committed
    contracts — train_sharded (GSPMD microbatch chunk slices along the
    data-sharded batch axis) compiles with collective-permutes and must be
    predicted; train_overlap (explicit shard_map, per-shard chunking) has
    none and must predict none."""
    from perceiver_io_tpu.analysis.flagship import build_programs

    contracts_dir = os.path.join(os.path.dirname(os.path.dirname(__file__)), "contracts")
    for name in ("train_sharded", "train_overlap"):
        target = build_programs((name,))[name]
        report = analysis.check(
            target.fn, target.args, rules=("sharding-flow",),
            policy=target.policy, compiled=False, name=name,
        )
        with open(os.path.join(contracts_dir, f"{name}.json")) as f:
            coll = json.load(f)["fingerprint"].get("collectives", {})
        compiled_reshards = sum(
            coll.get(k, {}).get("count", 0) for k in ("all-to-all", "collective-permute")
        )
        predicted = len(report.violations)
        assert (predicted > 0) == (compiled_reshards > 0), (
            f"{name}: predicted {predicted} reshard point(s) vs "
            f"{compiled_reshards} compiled reshard collective(s)\n{report.format()}"
        )


# -------------------------------------------------- cross-program-consistency


def _cache_pair(loop_steps=0, bad_index=False, loop_dtype=None):
    """A toy prefill/decode pair with labeled cache appends: the prompt
    phase writes the prompt at offset 0, the decode loop appends one slot
    at the carried length (or, planted, at a CONSTANT slot / wrong dtype)."""
    from jax import lax

    def prog(x):
        dtype = jnp.dtype(loop_dtype) if loop_dtype else x.dtype
        cache = jnp.zeros((2, 16, 4), dtype)
        with jax.named_scope("prefill"), jax.named_scope("kv_cache_append"):
            cache = lax.dynamic_update_slice(cache, x.astype(dtype), (0, 0, 0))
        if loop_steps == 0:
            return cache.sum()
        length = jnp.asarray(x.shape[1], jnp.int32)

        def step(carry, _):
            cache, length = carry
            upd = jnp.ones((2, 1, 4), dtype)
            idx = jnp.zeros((), jnp.int32) if bad_index else length
            with jax.named_scope("decode"), jax.named_scope("kv_cache_append"):
                cache = lax.dynamic_update_slice(cache, upd, (0, idx, 0))
            return (cache, length + 1), cache.sum()

        (_, _), ys = lax.scan(step, (cache, length), None, length=loop_steps)
        return ys.sum()

    return prog


def test_cross_program_consistency_clean_on_agreeing_pair():
    from perceiver_io_tpu.analysis import CompanionProgram

    x = jnp.ones((2, 4, 4))
    report = analysis.check(
        _cache_pair(loop_steps=3), (x,),
        rules=("cross-program-consistency",),
        policy=LintPolicy(
            companion=CompanionProgram("prefill", _cache_pair(loop_steps=0), (x,))
        ),
    )
    assert report.clean, report.format()


def test_cross_program_consistency_fires_on_static_append_index():
    from perceiver_io_tpu.analysis import CompanionProgram

    x = jnp.ones((2, 4, 4))
    report = analysis.check(
        _cache_pair(loop_steps=3, bad_index=True), (x,),
        rules=("cross-program-consistency",),
        policy=LintPolicy(
            companion=CompanionProgram("prefill", _cache_pair(loop_steps=0), (x,))
        ),
    )
    assert not report.ok()
    assert any("provenance" in v.message for v in report.violations)


def test_cross_program_consistency_fires_on_dtype_mismatch():
    from perceiver_io_tpu.analysis import CompanionProgram

    x = jnp.ones((2, 4, 4))
    report = analysis.check(
        _cache_pair(loop_steps=3, loop_dtype=jnp.bfloat16), (x,),
        rules=("cross-program-consistency",),
        policy=LintPolicy(
            companion=CompanionProgram("prefill", _cache_pair(loop_steps=0), (x,))
        ),
    )
    assert not report.ok()
    assert any("layout/dtype" in v.message for v in report.violations)


def test_cross_program_consistency_skipped_without_companion():
    report = analysis.check(
        _cache_pair(), (jnp.ones((2, 4, 4)),), rules=("cross-program-consistency",)
    )
    assert report.rules_skipped == ("cross-program-consistency",)


# ------------------------------------------------- ledger-derived allowlist


def test_default_allow_derives_from_ledger(tmp_path):
    from perceiver_io_tpu.analysis import ledger as L
    from perceiver_io_tpu.analysis.flagship import DEFAULT_ALLOW, default_allow

    # no ledger: the full static defaults
    assert default_allow(str(tmp_path)) == DEFAULT_ALLOW
    led = {
        "schema_version": 1,
        "features": {
            "twoseg": {"state": "staged",
                       "history": [{"state": "staged", "reason": "seed"}]}
        },
        "floors": {},
    }
    L.save_ledger(str(tmp_path), led)
    assert default_allow(str(tmp_path)) == DEFAULT_ALLOW  # staged: entry stays

    led = L.advance(led, "twoseg", "measured", "A/B ran", evidence={"ab": "BENCH_rX"})
    led = L.advance(led, "twoseg", "default_on", "graduated")
    L.save_ledger(str(tmp_path), led)
    flipped = default_allow(str(tmp_path))
    assert not any("kv_concat" in a for a in flipped), (
        "graduating twoseg must drop the kv_concat allowlist entry"
    )
    assert any("perceiver_ar._attend" in a for a in flipped)

    # today's repo ledger has twoseg staged, so the entry is still live
    assert any("kv_concat" in a for a in default_allow())


# ----------------------------------------------------- allowlist + report API


def test_allowlist_by_rule_and_by_scope_key():
    fn, args = _seq_concat_in("cross_attend"), (_A, _B)
    by_rule = analysis.check(fn, args, rules=("hot-concat",), allow=("hot-concat",))
    assert by_rule.ok() and by_rule.clean and len(by_rule.allowed) == 1

    by_key = analysis.check(
        fn, args, rules=("hot-concat",), allow=("hot-concat:*cross_attend*",)
    )
    assert by_key.clean and len(by_key.allowed) == 1

    miss = analysis.check(
        fn, args, rules=("hot-concat",), allow=("hot-concat:*decode*",)
    )
    assert not miss.clean and not miss.allowed


def test_allowlist_scope_separator_patterns():
    """fnmatch '*' crosses '/' — a pattern anchored at a scope-path TAIL
    (``*/kv_concat``-style) matches the site at any nesting depth, while a
    tail mismatch stays a violation (the DEFAULT_ALLOW entries in
    analysis/flagship.py rely on exactly this)."""

    def nested(a, b):
        with jax.named_scope("cross_attend"):
            with jax.named_scope("kv_concat"):
                return jnp.concatenate([a, b], axis=1).sum()

    args = (_A, _B)
    report = analysis.check(nested, args, rules=("hot-concat",))
    assert [v.scope for v in report.violations] == ["cross_attend/kv_concat"]

    # tail-anchored: any nesting above the labeled site
    tail = analysis.check(nested, args, rules=("hot-concat",), allow=("*/kv_concat",))
    assert tail.clean and len(tail.allowed) == 1

    # rule-qualified with a separator inside the scope part
    qualified = analysis.check(
        nested, args, rules=("hot-concat",), allow=("hot-concat:*/kv_concat",)
    )
    assert qualified.clean and len(qualified.allowed) == 1

    # a DIFFERENT tail does not match — the separator is load-bearing
    miss = analysis.check(nested, args, rules=("hot-concat",), allow=("*/q_concat",))
    assert not miss.clean and not miss.allowed

    # the site WITHOUT an enclosing scope: '*/kv_concat' requires a parent
    def flat(a, b):
        with jax.named_scope("kv_concat"):
            return jnp.concatenate([a, b], axis=1).sum()

    top = analysis.check(flat, args, rules=("hot-concat",), allow=("*/kv_concat",))
    assert not top.clean, "tail pattern must not match a parentless scope"
    assert analysis.check(
        flat, args, rules=("hot-concat",), allow=("*kv_concat",)
    ).clean


def test_unknown_rule_raises():
    with pytest.raises(ValueError, match="unknown rule"):
        analysis.check(lambda x: x, (jnp.ones(1),), rules=("no-such-rule",))


def test_report_surface():
    report = analysis.check(
        _seq_concat_in("cross_attend"), (_A, _B), rules=("hot-concat",)
    )
    d = json.loads(report.to_json())
    assert d["counts"]["error"] == 1 and d["violations"][0]["rule"] == "hot-concat"
    assert "hot-concat" in report.format()
    with pytest.raises(analysis.GraphLintError):
        report.raise_if("error")
    report.raise_if("none")  # no-op


def test_invalid_severity_override_rejected_at_config_time():
    with pytest.raises(ValueError, match="invalid severity"):
        analysis.check(
            lambda x: x, (jnp.ones(1),),
            policy=LintPolicy(severity_overrides={"hot-concat": "warning"}),
        )


def test_severity_override_respected():
    report = analysis.check(
        _seq_concat_in("cross_attend"),
        (_A, _B),
        rules=("hot-concat",),
        policy=LintPolicy(severity_overrides={"hot-concat": "info"}),
    )
    assert report.ok() and report.count("info") == 1


# -------------------------------------------------- trainer graphlint event


def test_trainer_emits_graphlint_event_with_planted_const(tmp_path):
    from perceiver_io_tpu.training.metrics import MetricsLogger
    from perceiver_io_tpu.training.optim import make_optimizer
    from perceiver_io_tpu.training.state import TrainState
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    baked = np.ones((200, 200), np.float32)  # 160 KB closed-over "weight"

    def apply_fn(p, x):
        return (x @ p["w"]) @ baked

    def loss_fn(p, batch, rng):
        out = apply_fn(p, batch["x"])
        return jnp.mean(out**2), {"loss": jnp.mean(out**2)}

    state = TrainState.create(
        apply_fn, {"w": jnp.ones((8, 200))}, make_optimizer(1e-3), jax.random.PRNGKey(0)
    )
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(loss_fn, config=TrainerConfig(max_steps=2, log_interval=1), logger=logger)

    def batches():
        while True:
            yield {"x": jnp.ones((2, 8))}

    state = trainer.fit(state, batches())
    assert int(state.step) == 2
    events = [json.loads(l) for l in open(os.path.join(str(tmp_path), "events.jsonl"))]
    gl = [e for e in events if e["event"] == "graphlint"]
    assert len(gl) == 1, "exactly one graphlint event per fit"
    assert gl[0]["ok"] is False and gl[0]["counts"]["error"] >= 1
    assert any(v["rule"] == "const-capture" for v in gl[0]["violations"])
    # the trace-level fingerprint rides alongside as a graphcheck event —
    # the planted 160 KB const shows up in its captured-const bytes
    gc = [e for e in events if e["event"] == "graphcheck"]
    assert len(gc) == 1, "exactly one graphcheck event per fit"
    assert gc[0]["captured_const_bytes"] >= 160_000
    assert gc[0]["n_ops"] >= 1 and "dtype_histogram" in gc[0]


def test_trainer_graphlint_off_emits_nothing(tmp_path):
    from perceiver_io_tpu.training.metrics import MetricsLogger
    from perceiver_io_tpu.training.optim import make_optimizer
    from perceiver_io_tpu.training.state import TrainState
    from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

    def loss_fn(p, batch, rng):
        out = batch["x"] @ p["w"]
        return jnp.mean(out**2), {"loss": jnp.mean(out**2)}

    state = TrainState.create(
        None, {"w": jnp.ones((8, 8))}, make_optimizer(1e-3), jax.random.PRNGKey(0)
    )
    logger = MetricsLogger(str(tmp_path), use_tensorboard=False)
    trainer = Trainer(
        loss_fn,
        config=TrainerConfig(max_steps=1, log_interval=1, graphlint=False, graphcheck=False),
        logger=logger,
    )
    trainer.fit(state, iter([{"x": jnp.ones((2, 8))}] * 2))
    events = [json.loads(l) for l in open(os.path.join(str(tmp_path), "events.jsonl"))]
    assert not [e for e in events if e["event"] in ("graphlint", "graphcheck")]


# ------------------------------------------------------- flagship smoke (CPU)


def test_flagship_micro_lint_is_clean():
    """The real flagship train/prefill/decode graphs lint clean at micro
    geometry with the documented default allowlist — the gate bench.py and
    `tasks.py graphlint` run."""
    from perceiver_io_tpu.analysis.flagship import lint_flagship

    reports = lint_flagship(geometry="micro")
    assert set(reports) == {"train", "prefill", "decode"}
    for name, report in reports.items():
        assert report.ok(), f"{name}:\n{report.format()}"
        # the default-route kv concat is allowlisted, not silently absent
    assert any("kv_concat" in v.key for v in reports["train"].allowed)


def test_flagship_twoseg_feature_removes_kv_concat():
    """Linting under features=('twoseg',) the kv_concat scope disappears
    from the trace entirely — the PR 2 guarantee at flagship level."""
    from perceiver_io_tpu.analysis.flagship import lint_flagship

    off = lint_flagship(geometry="micro", targets=("train",), features=())["train"]
    on = lint_flagship(geometry="micro", targets=("train",), features=("twoseg",))["train"]
    assert any("kv_concat" in v.key for v in off.allowed)
    assert not any("kv_concat" in v.key for v in on.allowed + on.violations)
    assert on.ok()


def test_graphlint_telemetry_block_shape():
    from perceiver_io_tpu.analysis.flagship import graphlint_telemetry

    block = graphlint_telemetry()
    assert block["status"] in ("passed", "failed")
    assert set(block["targets"]) == {"train", "decode"}
    for t in block["targets"].values():
        assert {"errors", "warnings", "allowed", "violations"} <= set(t)
