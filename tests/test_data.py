"""Data pipeline tests, modeled on the reference's data-module tests
(reference: tests/text_data_module_test.py:15-271, symbolic audio + optical
flow processors)."""

import numpy as np
import pytest

from perceiver_io_tpu.data.audio.midi import VOCAB_SIZE, Note, decode_events, encode_notes
from perceiver_io_tpu.data.audio.symbolic import (
    EXAMPLE_SEPARATOR,
    SymbolicAudioCollator,
    SymbolicAudioNumpyDataset,
)
from perceiver_io_tpu.data.loader import Batches, shard_indices_for_process
from perceiver_io_tpu.data.text.collators import TokenMaskingCollator, WordMaskingCollator
from perceiver_io_tpu.data.text.datamodule import TextDataModule
from perceiver_io_tpu.data.text.streaming import StreamingTextDataModule, shard_stream, shuffle_window
from perceiver_io_tpu.data.text.tokenizer import ByteTokenizer
from perceiver_io_tpu.data.vision.mnist import MNISTDataModule
from perceiver_io_tpu.data.vision.optical_flow import OpticalFlowProcessor
from perceiver_io_tpu.training.losses import IGNORE_INDEX

CORPUS = [
    "The quick brown fox jumps over the lazy dog. " * 20,
    "Perceiver IO is a general-purpose architecture. " * 20,
    "TPUs multiply matrices very quickly indeed. " * 20,
]


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    assert tok.vocab_size == 262
    text = "Hello, TPU! ünïcödé"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    ids_special = tok.encode(text, add_special_tokens=True)
    assert ids_special[0] == tok.cls_token_id and ids_special[-1] == tok.sep_token_id
    assert tok.decode(ids_special) == text
    assert tok.decode(ids_special, skip_special_tokens=False).startswith("[CLS]")


def test_byte_tokenizer_word_ids():
    tok = ByteTokenizer()
    ids = tok.encode("ab cd")
    # "ab" -> word 0, " " starts word 1, "cd" -> word 1
    assert tok.word_ids(ids) == [0, 0, 1, 1, 1]
    ids = [tok.cls_token_id] + tok.encode("x y") + [tok.sep_token_id]
    wids = tok.word_ids(ids)
    assert wids[0] is None and wids[-1] is None


def test_pad_sequences_sides():
    tok = ByteTokenizer()
    seqs = [[10, 11, 12], [20]]
    ids, mask = tok.pad_sequences(seqs, padding_side="left")
    np.testing.assert_array_equal(ids[1], [0, 0, 20])
    np.testing.assert_array_equal(mask[1], [True, True, False])
    ids, mask = tok.pad_sequences(seqs, padding_side="right", max_length=2)
    np.testing.assert_array_equal(ids[0], [10, 11])


def test_word_masking_collator():
    tok = ByteTokenizer()
    text = "the quick brown fox jumps over the lazy dog " * 30
    ids = tok.encode(text)
    examples = [{"input_ids": ids, "word_ids": tok.word_ids(ids)}]
    collator = WordMaskingCollator(tok, mask_prob=0.3, seed=0)
    batch = collator(examples)
    masked_frac = (batch["labels"] != IGNORE_INDEX).mean()
    assert 0.1 < masked_frac < 0.6
    # masked positions carry original ids as labels
    sel = batch["labels"] != IGNORE_INDEX
    orig = np.asarray(ids)
    assert (batch["labels"][0][sel[0]] == orig[sel[0]]).all()


def test_token_masking_collator():
    tok = ByteTokenizer()
    ids = tok.encode("abcdefgh " * 100)
    batch = TokenMaskingCollator(tok, mask_prob=0.15, seed=0)([{"input_ids": ids}])
    frac = (batch["labels"] != IGNORE_INDEX).mean()
    assert 0.08 < frac < 0.25
    assert (batch["input_ids"] == tok.mask_token_id).sum() > 0


def test_clm_datamodule_shift():
    dm = TextDataModule(task="clm", max_seq_len=64, batch_size=2, train_texts=CORPUS, valid_texts=CORPUS[:1])
    batches = list(dm.valid_batches())
    assert len(batches) >= 1
    b = batches[0]
    assert b["input_ids"].shape == (2, 64)
    # next-token contract
    np.testing.assert_array_equal(b["labels"][:, :-1], b["input_ids"][:, 1:])
    # stream windows are full: the collator reports pad-free batches as None
    # (selects the scatter-free position-embedding path in the model)
    assert b["pad_mask"] is None


def test_clm_random_truncate():
    dm = TextDataModule(
        task="clm", max_seq_len=64, batch_size=2, random_min_seq_len=32,
        train_texts=CORPUS, valid_texts=CORPUS[:1],
    )
    lens = {next(iter(dm.train_batches()))["input_ids"].shape[1] for _ in range(5)}
    assert all(32 <= n <= 64 for n in lens)


def test_mlm_datamodule():
    dm = TextDataModule(task="mlm", max_seq_len=64, batch_size=2, train_texts=CORPUS, valid_texts=CORPUS[:1])
    b = next(iter(dm.train_batches()))
    assert b["input_ids"].shape[1] <= 64
    assert (b["labels"] != IGNORE_INDEX).sum() > 0


def test_clf_datamodule():
    labeled = [(t, i % 2) for i, t in enumerate(CORPUS)]
    dm = TextDataModule(task="clf", max_seq_len=128, batch_size=3, train_texts=labeled, valid_texts=labeled)
    b = next(iter(dm.valid_batches()))
    assert b["input_ids"].shape == (3, 128)
    assert b["label"].shape == (3,)


def test_datamodule_cache(tmp_path):
    dm = TextDataModule(
        task="clm", max_seq_len=32, batch_size=1, train_texts=CORPUS, valid_texts=CORPUS[:1],
        cache_dir=str(tmp_path),
    )
    dm.prepare()
    files = list(tmp_path.glob("preproc-*.npz"))
    assert len(files) == 1
    # same source -> cache hit, identical stream, native int dtype
    dm2 = TextDataModule(
        task="clm", max_seq_len=32, batch_size=1, train_texts=CORPUS, valid_texts=CORPUS[:1],
        cache_dir=str(tmp_path),
    )
    dm2.prepare()
    np.testing.assert_array_equal(dm._prepared["train_stream"], dm2._prepared["train_stream"])
    assert np.asarray(dm2._prepared["train_stream"]).dtype != object

    # different source -> different cache entry, no silent collision
    dm3 = TextDataModule(
        task="clm", max_seq_len=32, batch_size=1,
        train_texts=["completely different corpus " * 30], valid_texts=CORPUS[:1],
        cache_dir=str(tmp_path),
    )
    dm3.prepare()
    assert len(list(tmp_path.glob("preproc-*.npz"))) == 2
    assert len(dm3._prepared["train_stream"]) != len(dm._prepared["train_stream"])


def test_static_masking():
    dm = TextDataModule(
        task="mlm", max_seq_len=64, batch_size=2, static_masking=True,
        train_texts=CORPUS, valid_texts=CORPUS[:1],
    )
    b1 = next(iter(dm.train_batches()))
    b2 = next(iter(dm.train_batches()))
    assert (b1["labels"] != IGNORE_INDEX).sum() > 0
    # static: identical masking across epochs
    np.testing.assert_array_equal(b1["input_ids"], b2["input_ids"])
    np.testing.assert_array_equal(b1["labels"], b2["labels"])


def test_clm_rejects_right_padding():
    with pytest.raises(ValueError, match="padding_side='left'"):
        TextDataModule(task="clm", padding_side="right", train_texts=CORPUS)


def test_clf_rejects_mixed_labels():
    dm = TextDataModule(
        task="clf", max_seq_len=32, batch_size=1,
        train_texts=["unlabeled", ("labeled", 1)], valid_texts=[("a", 0)],
    )
    with pytest.raises(ValueError, match="every item to be a"):
        dm.prepare()


def test_streaming_module():
    dm = StreamingTextDataModule(
        lambda: iter(CORPUS * 5), max_seq_len=64, min_seq_len=32, batch_size=2,
        shuffle_window_size=4, shard_for_processes=False,
    )
    batches = list(dm.batches(train=True))
    assert len(batches) > 3
    for b in batches:
        assert 32 <= b["input_ids"].shape[1] <= 64
        np.testing.assert_array_equal(b["labels"][:, :-1], b["input_ids"][:, 1:])


def test_stream_sharding():
    items = list(range(10))
    assert list(shard_stream(iter(items), 0, 2)) == [0, 2, 4, 6, 8]
    assert list(shard_stream(iter(items), 1, 2)) == [1, 3, 5, 7, 9]
    shuffled = list(shuffle_window(iter(items), window_size=4, seed=0))
    assert sorted(shuffled) == items
    np.testing.assert_array_equal(shard_indices_for_process(10, 1, 2), [5, 6, 7, 8, 9])


def test_midi_codec_roundtrip():
    notes = [
        Note(velocity=64, pitch=60, start=0.0, end=0.5),
        Note(velocity=80, pitch=64, start=0.25, end=1.0),
        Note(velocity=80, pitch=67, start=1.5, end=2.5),
    ]
    ids = encode_notes(notes)
    assert all(0 <= i < VOCAB_SIZE - 1 for i in ids)
    decoded = decode_events(ids)
    assert len(decoded) == 3
    for orig, dec in zip(sorted(notes, key=lambda n: n.start), decoded):
        assert dec.pitch == orig.pitch
        assert dec.start == pytest.approx(orig.start, abs=0.011)
        assert dec.end == pytest.approx(orig.end, abs=0.011)
        assert abs(dec.velocity - orig.velocity) < 4


def test_symbolic_audio_dataset_and_collator():
    rng = np.random.default_rng(0)
    pieces = [rng.integers(0, 388, size=n).astype(np.int16) for n in (50, 200, 120)]
    flat = np.concatenate([np.append(p, [EXAMPLE_SEPARATOR]) for p in pieces])
    ds = SymbolicAudioNumpyDataset(flat, max_seq_len=65, seed=0)
    for i in range(5):
        ex = ds[i]["input_ids"]
        assert EXAMPLE_SEPARATOR not in ex
        assert len(ex) <= 65

    collator = SymbolicAudioCollator(max_seq_len=65, padding_side="left")
    batch = collator([ds[0], ds[1]])
    assert batch["input_ids"].shape == (2, 64)
    np.testing.assert_array_equal(batch["labels"][:, :-1], batch["input_ids"][:, 1:])
    # left padding -> pads at the start
    row_pad = batch["pad_mask"][0]
    if row_pad.any():
        first_real = np.argmin(row_pad)
        assert not row_pad[first_real:].any()


def test_optical_flow_processor():
    proc = OpticalFlowProcessor(patch_size=(16, 24), patch_min_overlap=4)
    grid = proc.compute_patch_grid_indices((20, 30))
    assert grid[-1] == (4, 6)  # right-aligned last patch

    rng = np.random.default_rng(0)
    img1 = rng.integers(0, 255, size=(20, 30, 3), dtype=np.uint8)
    img2 = rng.integers(0, 255, size=(20, 30, 3), dtype=np.uint8)
    feats = proc.preprocess((img1, img2))
    assert feats.shape == (len(grid), 2, 16, 24, 27)
    assert -1.0 <= feats.min() and feats.max() <= 1.0
    # center 9 channels (ky=1,kx=1) reproduce the normalized pixel values
    np.testing.assert_allclose(
        feats[0, 0, 1:-1, 1:-1, 12:15],
        (img1.astype(np.float32) / 255 * 2 - 1)[1:15, 1:23],
        atol=1e-6,
    )

    # constant patch predictions blend back to the constant
    preds = np.full((len(grid), 16, 24, 2), 0.05, np.float32)
    flow = proc.postprocess(preds, (20, 30))
    np.testing.assert_allclose(flow, 0.05 * proc.flow_scale_factor, rtol=1e-5)


def test_optical_flow_processor_validation():
    proc = OpticalFlowProcessor(patch_size=(16, 24), patch_min_overlap=4)
    with pytest.raises(ValueError, match="below the .*patch"):
        proc.preprocess((np.zeros((8, 30, 3)), np.zeros((8, 30, 3))))
    with pytest.raises(ValueError, match="mismatched shapes"):
        proc.preprocess((np.zeros((20, 30, 3)), np.zeros((20, 32, 3))))
    with pytest.raises(ValueError, match="must be smaller than"):
        OpticalFlowProcessor(patch_size=(16, 24), patch_min_overlap=16)


def test_mnist_synthetic():
    dm = MNISTDataModule(synthetic=True, batch_size=16, random_crop=24)
    assert dm.image_shape == (24, 24, 1)
    b = next(iter(dm.train_batches()))
    assert b["image"].shape == (16, 24, 24, 1)
    assert -1.0 <= b["image"].min() and b["image"].max() <= 1.0
    bv = next(iter(dm.valid_batches()))
    assert bv["image"].shape == (16, 24, 24, 1)


def test_batches_drop_last_and_shuffle():
    data = [{"x": np.asarray([i])} for i in range(10)]

    class DS:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    b = Batches(DS(), batch_size=3, shuffle=True, seed=1)
    batches = list(b)
    assert len(batches) == 3
    seen_first = {tuple(x["x"].ravel()) for x in batches}
    batches2 = list(b)  # epoch advances -> different order
    seen_second = {tuple(x["x"].ravel()) for x in batches2}
    assert seen_first != seen_second or True  # order may coincide; just smoke


def test_byte_tokenizer_matches_hf_perceiver_tokenizer():
    """Cross-framework tokenizer parity (SURVEY §4 category 4, offline): our
    self-contained ByteTokenizer must produce the exact ids of the HF
    PerceiverTokenizer the reference trains with (UTF-8 bytes + 6 specials,
    byte b -> b + 6)."""
    pytest.importorskip("transformers")
    from transformers.models.perceiver.tokenization_perceiver import PerceiverTokenizer

    hf = PerceiverTokenizer()  # instantiates offline: no vocab file needed
    ours = ByteTokenizer()
    assert ours.vocab_size == len(hf) == 262

    for text in ["Hello, Perceiver!", "naïve café — 中文 😀", "", "a\nb\tc"]:
        hf_ids = hf(text, add_special_tokens=False)["input_ids"]
        assert ours.encode(text) == hf_ids
        # with specials: reference wraps [CLS] ... [SEP]
        hf_special = hf(text, add_special_tokens=True)["input_ids"]
        assert ours.encode(text, add_special_tokens=True) == hf_special
        assert ours.decode(hf_ids) == hf.decode(hf_ids)

    # special-token id layout parity
    assert ours.pad_token_id == hf.pad_token_id
    assert ours.mask_token_id == hf.mask_token_id
    assert ours.cls_token_id == hf.cls_token_id
    assert ours.sep_token_id == hf.sep_token_id


def test_streaming_chunks_match_naive_construction():
    """The parts-list chunk assembly must be byte-identical to the naive
    rolling-list construction (concat docs with EOS, cut fixed windows)."""
    tok = ByteTokenizer()
    docs = [f"document number {i} with some text. " * (i % 7 + 1) for i in range(200)]
    dm = StreamingTextDataModule(
        lambda: iter(docs), max_seq_len=64, batch_size=2,
        shuffle_window_size=1, shard_for_processes=False,
    )
    chunks = list(dm._chunks(randomize_len=False))

    buf = []
    for t in docs:  # shuffle window of 1 preserves order
        buf.extend(tok.encode(t))
        buf.append(tok.eos_token_id)
    naive = [buf[i : i + 65] for i in range(0, len(buf) - 64, 65)]

    assert len(chunks) == len(naive)
    for c, n in zip(chunks, naive):
        np.testing.assert_array_equal(np.asarray(c), np.asarray(n))


def test_prefetch_iterator_order_exceptions_and_close():
    from perceiver_io_tpu.data.loader import PrefetchIterator

    # order preserved over a finite iterator, StopIteration surfaces
    it = PrefetchIterator(iter(range(7)), depth=3)
    assert list(it) == list(range(7))

    # producer exceptions re-raise in the consumer after the good items
    def gen():
        yield 1
        yield 2
        raise RuntimeError("producer boom")

    it = PrefetchIterator(gen(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="producer boom"):
        next(it)

    # exhaustion is sticky: next() after StopIteration raises again, never hangs
    it2 = PrefetchIterator(iter([1]), depth=2)
    assert list(it2) == [1]
    assert next(it2, "default") == "default"

    # close() stops an infinite producer (the thread is a daemon either way)
    import itertools

    it = PrefetchIterator(itertools.count(), depth=2)
    assert next(it) == 0
    it.close()

    # dropping the wrapper without close() lets GC stop the producer (the
    # thread holds no reference to the wrapper)
    import gc

    it3 = PrefetchIterator(itertools.count(), depth=1)
    thread = it3._thread
    stop = it3._stop
    del it3
    gc.collect()
    assert stop.is_set()
    thread.join(timeout=2)
    assert not thread.is_alive()
