"""Serving-observability layer (ISSUE 11, Loadline): the deterministic load
generator over the instrumented decode path, the flight recorder's
trigger→dump→``flight.dump``-event contract, the stdlib scrape server, the
LOAD-artifact diff's comparability-first classification, and the
per-request queue→prefill→decode→compile tail attribution."""

import json
import os
import signal
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from perceiver_io_tpu.obs import EventLog
from perceiver_io_tpu.obs.events import merged_events, validate_events
from perceiver_io_tpu.obs.flightrec import FlightRecorder, SLOBounds
from perceiver_io_tpu.obs.loadgen import (
    WorkloadSpec,
    arrival_schedule,
    build_load_doc,
    diff_load,
    format_load_diff,
    run_load,
    summarize_load,
)
from perceiver_io_tpu.obs.metrics import MetricsRegistry
from perceiver_io_tpu.obs.slo import build_slo_report, request_breakdowns


def tiny_model():
    from perceiver_io_tpu.models.text import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )

    config = CausalLanguageModelConfig(
        vocab_size=50, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    ids = np.random.default_rng(0).integers(0, 50, size=(1, 12))
    import jax.numpy as jnp

    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids), prefix_len=8)
    return model, params


# one compiled geometry for the whole module: prompt_len 10, 4 new tokens
SPEC = WorkloadSpec(seed=5, prompt_lens=(10,), max_new_tokens=(4,))


# ------------------------------------------------------------ workload spec


def test_workload_spec_deterministic_and_validated():
    spec = WorkloadSpec(seed=3, prompt_lens=(8, 12), max_new_tokens=(4, 6), batch=2)
    a, b = spec.draw(6, 64), spec.draw(6, 64)
    assert [(r.prompt_len, r.max_new_tokens, r.rng_seed) for r in a] == [
        (r.prompt_len, r.max_new_tokens, r.rng_seed) for r in b
    ]
    assert all((x.input_ids == y.input_ids).all() for x, y in zip(a, b))
    assert all(r.input_ids.shape == (2, r.prompt_len) for r in a)
    # prefix-stable: the first n requests do not depend on how many you draw
    assert [r.rng_seed for r in spec.draw(3, 64)] == [r.rng_seed for r in a[:3]]
    # a different seed is a different stream
    assert [r.rng_seed for r in WorkloadSpec(seed=4).draw(3, 64)] != [
        r.rng_seed for r in WorkloadSpec(seed=3).draw(3, 64)
    ]
    with pytest.raises(ValueError):
        WorkloadSpec(prompt_lens=())
    with pytest.raises(ValueError):
        WorkloadSpec(batch=0)
    round_trip = WorkloadSpec(**{**spec.to_dict(),
                                 "prompt_lens": tuple(spec.prompt_lens),
                                 "max_new_tokens": tuple(spec.max_new_tokens)})
    assert round_trip.to_dict() == spec.to_dict()


def test_arrival_schedule_seeded_monotone():
    a = arrival_schedule(200, rate_rps=50.0, seed=7)
    assert a == arrival_schedule(200, rate_rps=50.0, seed=7)
    assert a != arrival_schedule(200, rate_rps=50.0, seed=8)
    assert all(x < y for x, y in zip(a, a[1:]))  # strictly increasing
    # mean inter-arrival ~ 1/rate (law of large numbers, loose bound)
    assert a[-1] / 200 == pytest.approx(1 / 50.0, rel=0.5)
    with pytest.raises(ValueError):
        arrival_schedule(5, rate_rps=0.0)


# ------------------------------------------------------------- end to end


def test_closed_loop_end_to_end(tmp_path):
    """The acceptance path in miniature: a closed-loop run over the
    instrumented fns lands queue-wait-stamped request events, a
    load.summary row, registry histograms, SLO queue-wait percentiles and
    a renderable per-request breakdown — and the stream validates."""
    model, params = tiny_model()
    events = EventLog(str(tmp_path), main_process=True)
    registry = MetricsRegistry()
    report = run_load(
        model, params, SPEC, mode="closed", n_requests=6, concurrency=2,
        num_latents=4, events=events, registry=registry, snapshot_interval_s=0.0,
    )
    assert len(report.records) == 6
    assert all(r.outcome == "ok" for r in report.records)
    assert all(r.tokens_out == 4 for r in report.records)
    # concurrency 2: every request after the first queued behind another
    assert max(r.queue_wait_s for r in report.records) > 0

    s = report.summary
    assert s["mode"] == "closed" and s["n_requests"] == 6 and s["error_rate"] == 0.0
    assert s["achieved_rps"] > 0 and s["throughput_tok_s"] > 0
    assert {"p50", "p99"} <= set(s["ttft_s"]) and {"p50", "p99"} <= set(s["queue_wait_s"])
    # 3 decode-step samples per request, minus the one step that compiled
    # (warm-only by construction — the registry histogram skips it)
    assert s["tpot_s"]["n"] == 6 * 3 - 1
    assert {"queue_wait", "prefill", "decode"} <= set(s["breakdown_ms"])

    # the stream: schema-valid, no unknown kinds, queue-wait on every row
    warnings_out = []
    assert validate_events(str(tmp_path), warnings_out=warnings_out) == []
    assert warnings_out == []
    stream = merged_events(str(tmp_path))
    reqs = [e for e in stream if e.get("event") == "request"]
    assert len(reqs) == 6
    assert all(e.get("queue_wait_s") is not None for e in reqs)
    summaries = [e for e in stream if e.get("event") == "load.summary"]
    assert len(summaries) == 1 and summaries[0]["n_requests"] == 6
    assert registry.histogram("generate_queue_wait_s").n == 6

    # SLO report picks up the queue-wait family
    slo = build_slo_report(stream)
    assert "queue_wait_s" in slo and slo["queue_wait_s"]["n"] >= 1

    # tail attribution: compile joined onto the cold request's span
    bd = request_breakdowns(stream)
    assert bd["n"] == 6
    cold = [r for r in bd["requests"] if r["compiled"]]
    assert cold and all(r["compile_ms"] > 0 for r in cold)
    warm = [r for r in bd["requests"] if not r["compiled"]]
    assert all(r["compile_ms"] == 0 for r in warm)
    assert all(r["total_ms"] >= r["service_ms"] for r in bd["requests"])
    for key in ("queue_wait_ms", "prefill_ms", "decode_ms", "service_ms", "total_ms"):
        assert key in bd["medians"]

    # obs_report renders the breakdown section
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec_ = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(repo, "tools", "obs_report.py")
    )
    obs_report = importlib.util.module_from_spec(spec_)
    spec_.loader.exec_module(obs_report)
    text = obs_report.render(str(tmp_path))
    assert "request breakdown" in text and "queue_wait" in text
    assert "queue_wait_s:" in text  # SLO queue-wait line in the requests section


def test_open_loop_measures_queue_growth(tmp_path):
    """Open loop at an unsustainable rate: arrivals outpace the worker, so
    queue-wait grows monotonically with arrival index — the overload signal
    closed-loop self-throttling hides."""
    model, params = tiny_model()
    events = EventLog(str(tmp_path), main_process=True)
    report = run_load(
        model, params, SPEC, mode="open", n_requests=5, rate_rps=1e5,
        num_latents=4, events=events,
    )
    qws = [r.queue_wait_s for r in report.records]
    assert all(b >= a for a, b in zip(qws[1:], qws[2:]))  # monotone past warmup
    assert qws[-1] > qws[1]
    assert report.summary["target_rps"] == 1e5
    with pytest.raises(ValueError):
        run_load(model, params, SPEC, mode="open", n_requests=1)  # no rate
    with pytest.raises(ValueError):
        run_load(model, params, SPEC, mode="nope", n_requests=1)


# -------------------------------------------------------- flight recorder


def _request_row(span_id, ttft=0.01, tpot99=0.001, outcome="ok", request_id="req1"):
    return dict(
        request_id=request_id, span_id=span_id, batch=1, prompt_len=8,
        ttft_s=ttft, tpot_p99_s=tpot99, outcome=outcome, tokens_out=4,
    )


def test_flight_recorder_triggers_dump_and_event(tmp_path):
    events = EventLog(str(tmp_path), main_process=True)
    rec = FlightRecorder(events, slo=SLOBounds(ttft_s=0.1, tpot_p99_s=0.05))
    assert rec.out_dir == str(tmp_path)  # defaults to the sink's log_dir
    rec.emit_rows("span", [
        {"name": "request", "span_id": "aaa", "t_start": 1.0, "t_end": 2.0,
         "dur_ms": 1000.0, "process_index": 0, "attrs": {}},
        {"name": "request", "span_id": "bbb", "t_start": 2.0, "t_end": 3.0,
         "dur_ms": 1000.0, "process_index": 0, "attrs": {}},
    ])
    rec.emit("request", **_request_row("aaa"))  # within bounds: no dump
    assert rec.dumps == []
    rec.emit("request", **_request_row("bbb", ttft=0.5, request_id="req2"))  # breach
    assert len(rec.dumps) == 1
    path = rec.dumps[0]
    assert os.path.basename(path) == "flight-slo_ttft-1.json"
    dump = json.load(open(path))
    assert dump["trigger"] == "slo_ttft"
    assert dump["trigger_span_id"] == "bbb"  # names the breaching span
    assert dump["trigger_request_id"] == "req2"
    assert dump["n_events"] == len(dump["events"]) >= 3  # spans + both requests
    assert not os.path.exists(path + ".tmp")  # atomic: no torn tmp left

    # the stream carries the flight.dump row, and it validates
    stream = merged_events(str(tmp_path))
    dumps = [e for e in stream if e.get("event") == "flight.dump"]
    assert len(dumps) == 1 and dumps[0]["trigger_span_id"] == "bbb"
    assert validate_events(str(tmp_path)) == []

    # error outcome and tpot-p99 breach are independent triggers
    rec.emit("request", **_request_row("aaa", outcome="error"))
    rec.emit("request", **_request_row("aaa", tpot99=0.2))
    names = [os.path.basename(p) for p in rec.dumps]
    assert names[1:] == ["flight-error-2.json", "flight-slo_tpot-3.json"]


def test_flight_recorder_blast_sentinel_sigusr1_and_cap(tmp_path):
    events = EventLog(str(tmp_path), main_process=True)
    rec = FlightRecorder(events, max_dumps=3)
    rec.emit("probe", step=1, scopes={"000:layer": {"rms": 1.0}})
    rec.emit("probe.blast", trigger="nonfinite_loss", scope="layer", step=1, affected=["layer"])
    assert [os.path.basename(p) for p in rec.dumps] == ["flight-blast-1.json"]
    dump = json.load(open(rec.dumps[0]))
    assert dump["probe_snapshot"]["scopes"] == {"000:layer": {"rms": 1.0}}

    rec.emit("fault.spike", step=2, loss=9.9)
    assert os.path.basename(rec.dumps[1]) == "flight-sentinel-2.json"

    prev = rec.install_signal_handler()
    try:
        signal.raise_signal(signal.SIGUSR1)
    finally:
        signal.signal(signal.SIGUSR1, prev)
    assert os.path.basename(rec.dumps[2]) == "flight-sigusr1-3.json"

    # capped: the 4th trigger records the event but writes no dump
    rec.emit("fault.halt", step=3)
    assert len(rec.dumps) == 3
    kinds = [e["event"] for e in merged_events(str(tmp_path))]
    assert kinds.count("flight.dump") == 3 and "fault.halt" in kinds


def test_flight_recorder_ring_bounded_and_passthrough(tmp_path):
    events = EventLog(str(tmp_path), main_process=True)
    rec = FlightRecorder(events, capacity=4)
    for i in range(10):
        rec.emit("log", step=i)
    ring = rec.ring()
    assert [r["step"] for r in ring] == [6, 7, 8, 9]  # bounded, oldest dropped
    # everything still reached the wrapped sink
    assert len([e for e in merged_events(str(tmp_path)) if e["event"] == "log"]) == 10


# ----------------------------------------------------------------- server


def test_obs_server_endpoints(tmp_path):
    from perceiver_io_tpu.obs.server import ObsServer

    events = EventLog(str(tmp_path), main_process=True)
    events.emit(
        "request", request_id="r1", batch=1, prompt_len=8, ttft_s=0.01,
        outcome="ok", tokens_out=4, tokens_per_sec=400.0,
        tpot_hist={"0": 3}, queue_wait_s=0.002,
    )
    registry = MetricsRegistry()
    registry.counter("gen_requests").inc(1)
    registry.histogram("lat_s").record(0.01)

    def get(path):
        with urllib.request.urlopen(server.url + path, timeout=10) as r:
            return r.status, r.read().decode(), r.headers.get("Content-Type", "")

    with ObsServer(registry=registry, run_dir=str(tmp_path)) as server:
        assert server.port != 0  # ephemeral port bound
        status, body, ctype = get("/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        assert "gen_requests 1" in body and 'lat_s_bucket{le="+Inf"} 1' in body
        status, body, _ = get("/healthz")
        health = json.loads(body)
        assert status == 200 and health["status"] == "ok" and health["n_metrics"] == 2
        status, body, ctype = get("/slo")
        slo = json.loads(body)
        assert status == 200 and ctype.startswith("application/json")
        assert slo["n_requests"] == 1 and "queue_wait_s" in slo
        # incremental ingestion: a row appended AFTER the first scrape is
        # picked up by the next one (only the tail is parsed, not the file)
        events.emit(
            "request", request_id="r2", batch=1, prompt_len=8, ttft_s=0.02,
            outcome="ok", tokens_out=4, tokens_per_sec=200.0, tpot_hist={"0": 3},
        )
        assert json.loads(get("/slo")[1])["n_requests"] == 2
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/nope")
        assert e.value.code == 404

    # /slo without a run_dir is a 404, not a crash
    with ObsServer(registry=registry) as server:
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/slo")
        assert e.value.code == 404


def test_prometheus_scrape_concurrent_with_recording():
    """The wiring this PR introduces — a scrape thread exporting while the
    serving thread records — must never see the counts dict mutate under
    iteration, and every scrape must satisfy the histogram invariants
    (cumulative buckets <= +Inf == _count)."""
    import re
    import threading

    reg = MetricsRegistry()
    h = reg.histogram("busy_s")
    stop = threading.Event()
    errors = []

    def record_loop():
        i = 0
        while not stop.is_set():
            h.record(10.0 ** ((i % 1200) / 100.0 - 6))  # a new bucket often
            i += 1

    t = threading.Thread(target=record_loop, daemon=True)
    t.start()
    try:
        for _ in range(300):
            try:
                text = reg.to_prometheus()
            except RuntimeError as e:  # dict changed size during iteration
                errors.append(repr(e))
                break
            pairs = re.findall(r'busy_s_bucket\{le="([^"}]+)"\} (\d+)', text)
            cums = [int(c) for _, c in pairs]
            count = int(re.search(r"busy_s_count (\d+)", text).group(1))
            if cums != sorted(cums) or (cums and cums[-1] != count):
                errors.append(f"invariant broken: cums={cums[-3:]} count={count}")
                break
            reg.snapshot()  # the event-row exporter shares the same contract
    finally:
        stop.set()
        t.join(timeout=5)
    assert errors == []
    assert h.n > 0


# ----------------------------------------------------------- LOAD diffing


def _doc(**overrides):
    summary = {
        "mode": "closed", "n_requests": 200, "concurrency": 4, "target_rps": None,
        "duration_s": 10.0, "achieved_rps": 20.0, "throughput_tok_s": 500.0,
        "tokens_out": 5000, "errors": 0, "error_rate": 0.0, "ok_rate": 1.0,
        "n_cold": 4, "warm_only": True, "n_latency_requests": 196,
        "ttft_s": {"p50": 0.01, "p90": 0.02, "p99": 0.05, "n": 196.0, "mean": 0.012},
        "tpot_s": {"p50": 0.001, "p90": 0.002, "p99": 0.004, "n": 900},
        "queue_wait_s": {"p50": 0.1, "p90": 0.2, "p99": 0.5, "n": 196.0, "mean": 0.12},
        "breakdown_ms": {"queue_wait": 100.0, "prefill": 10.0, "decode": 40.0},
    }
    summary.update(overrides.pop("summary", {}))
    doc = build_load_doc(
        1, summary, WorkloadSpec(seed=0),
        manifest={"backend": "cpu", "device_kind": "cpu", "device_count": 1,
                  "process_count": 1, "jax_version": "0.4.37", "mesh": None,
                  "config_hash": "abc"},
    )
    doc.update(overrides)
    return doc


def test_diff_load_self_clean_and_classification():
    doc = _doc()
    self_diff = diff_load(doc, doc)
    assert self_diff["comparable"] and self_diff["ok"]
    assert all(d["kind"] == "neutral" for d in self_diff["deltas"])

    # a 2x tpot p99 under a 25% tolerance is a regression; 2x throughput an
    # improvement; error_rate is zero-tolerance
    worse = _doc(summary={
        "tpot_s": {"p50": 0.001, "p90": 0.002, "p99": 0.008, "n": 900},
        "throughput_tok_s": 1000.0,
        "error_rate": 0.01, "ok_rate": 0.99, "errors": 2,
    })
    diff = diff_load(doc, worse)
    kinds = {d["metric"]: d["kind"] for d in diff["deltas"]}
    assert kinds["tpot_s_p99"] == "regression"
    assert kinds["throughput_tok_s"] == "improvement"
    assert kinds["error_rate"] == "regression"
    assert not diff["ok"]
    assert "regression" in format_load_diff(diff)

    # low_n families classify neutral, never regression
    low = _doc(summary={"tpot_s": {"p50": 0.01, "p99": 0.08, "n": 3, "low_n": True}})
    kinds = {d["metric"]: d["kind"] for d in diff_load(low, low)["deltas"]}
    assert kinds["tpot_s_p99"] == "neutral"


def test_diff_load_refuses_incomparable():
    doc = _doc()
    other_mode = _doc()
    other_mode["mode"] = "open"
    other_mode["summary"]["mode"] = "open"
    d = diff_load(doc, other_mode)
    assert not d["comparable"] and "mode" in d["reason"]
    assert "NOT COMPARABLE" in format_load_diff(d)

    other_dev = _doc()
    other_dev["manifest"]["device_kind"] = "TPU v5e"
    assert not diff_load(doc, other_dev)["comparable"]

    other_n = _doc()
    other_n["workload"]["n_requests"] = 100
    assert not diff_load(doc, other_n)["comparable"]


def test_summarize_load_warm_only_fallback():
    from perceiver_io_tpu.obs.loadgen import RequestRecord

    cold = [
        RequestRecord(index=i, prompt_len=8, max_new_tokens=4, batch=1,
                      queue_wait_s=0.1, compiled=True, ttft_s=1.0, decode_s=0.5,
                      tokens_out=4)
        for i in range(3)
    ]
    s = summarize_load(cold, duration_s=2.0)
    assert s["warm_only"] is False and s["n_cold"] == 3
    assert s["ttft_s"]["low_n"] is True
    err = RequestRecord(index=3, prompt_len=8, max_new_tokens=4, batch=1,
                        queue_wait_s=0.0, outcome="error", error="boom")
    s = summarize_load(cold + [err], duration_s=2.0)
    assert s["errors"] == 1 and s["error_rate"] == 0.25 and s["ok_rate"] == 0.75
    with pytest.raises(ValueError):
        summarize_load([], 1.0)


# ----------------------------------------------------------- the CLI gate


def _load_cli():
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "loadgen_cli", os.path.join(repo, "tools", "loadgen.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_loadgen_cli_gate_and_diff(tmp_path):
    """`tasks.py load --smoke` in miniature: the gate runs clean end to end
    (stream validates, planted breach -> exactly one flight dump naming the
    breaching span, /metrics+/slo answer, self-diff clean, LOAD floors
    hold), and the --diff mode round-trips a committed artifact."""
    cli = _load_cli()
    out = tmp_path / "run"
    rc = cli.main(["--smoke", "--requests", "6", "--out", str(out)])
    assert rc == 0
    dumps = [p for p in os.listdir(out) if p.startswith("flight-")]
    assert dumps == ["flight-slo_ttft-1.json"]
    dump = json.load(open(out / dumps[0]))
    stream = merged_events(str(out))
    breach = [e for e in stream if e.get("event") == "request"][-1]
    assert dump["trigger_span_id"] == breach["span_id"]
    assert os.path.exists(out / "slo_report.json")

    # --diff: the committed artifact vs itself is clean (exit 0); a
    # different-workload doc refuses comparison (exit 2)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    committed = os.path.join(repo, "LOAD_r01.json")
    assert cli.main(["--diff", committed, committed]) == 0
    other = json.load(open(committed))
    other["workload"]["n_requests"] = 7
    other_path = tmp_path / "other.json"
    other_path.write_text(json.dumps(other))
    assert cli.main(["--diff", committed, str(other_path)]) == 2


# ------------------------------------------------- breakdown join (no jax)


def test_request_breakdowns_joins_compile_by_span():
    events = [
        {"event": "span", "span_id": "s1", "name": "request", "dur_ms": 1200.0},
        {"event": "span", "span_id": "s2", "name": "request", "dur_ms": 50.0},
        {"event": "compile", "fn": "generate_prefill", "wall_s": 1.0,
         "n_compiles": 1, "span_id": "s1"},
        {"event": "request", "request_id": "r1", "span_id": "s1", "batch": 1,
         "prompt_len": 8, "ttft_s": 1.05, "decode_s": 0.1, "outcome": "ok",
         "tokens_out": 4, "compiled": True, "queue_wait_s": 0.0},
        {"event": "request", "request_id": "r2", "span_id": "s2", "batch": 1,
         "prompt_len": 8, "ttft_s": 0.01, "decode_s": 0.03, "outcome": "ok",
         "tokens_out": 4, "compiled": False, "queue_wait_s": 0.2},
    ]
    bd = request_breakdowns(events)
    assert bd["n"] == 2 and bd["warm_only"] is True
    r1, r2 = bd["requests"]
    assert r1["compile_ms"] == 1000.0 and r1["service_ms"] == 1200.0
    assert r2["compile_ms"] == 0.0 and r2["total_ms"] == pytest.approx(250.0)
    # medians are warm-only: r2 alone defines them
    assert bd["medians"]["queue_wait_ms"] == 200.0
    assert bd["medians"]["prefill_ms"] == 10.0
    # the cold compile median is reported separately
    assert bd["medians"]["compile_ms_cold"] == 1000.0
    assert request_breakdowns([{"event": "log"}]) is None
