"""Training loop: loss decreases on a learnable toy task; FSDP/data-parallel
sharding compiles and runs on a virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.core.config import ClassificationDecoderConfig
from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.models.vision import ImageClassifier, ImageClassifierConfig, ImageEncoderConfig
from perceiver_io_tpu.parallel import fsdp_param_shardings, make_mesh, shard_batch
from perceiver_io_tpu.training import (
    TrainState,
    classification_loss_fn,
    clm_loss_fn,
    constant_with_warmup,
    cosine_with_warmup,
    make_optimizer,
)
from perceiver_io_tpu.training.loop import make_train_step, shard_train_state


def small_classifier():
    config = ImageClassifierConfig(
        encoder=ImageEncoderConfig(
            image_shape=(8, 8, 1),
            num_frequency_bands=4,
            num_cross_attention_heads=1,
            num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=ClassificationDecoderConfig(
            num_classes=2, num_output_query_channels=16, num_cross_attention_heads=1
        ),
        num_latents=4,
        num_latent_channels=16,
    )
    return ImageClassifier(config)


def toy_batch(n=32):
    """Learnable task: label = whether the mean pixel is positive."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 8, 8, 1)).astype(np.float32)
    x += rng.choice([-1.0, 1.0], size=(n, 1, 1, 1))
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return {"image": jnp.asarray(x), "label": jnp.asarray(y)}


def test_schedules():
    cos = cosine_with_warmup(1.0, training_steps=100, warmup_steps=10, min_fraction=0.1)
    assert float(cos(0)) == 0.0
    assert float(cos(5)) == pytest.approx(0.5)
    assert float(cos(10)) == pytest.approx(1.0)
    assert float(cos(100)) == pytest.approx(0.1, abs=1e-6)
    const = constant_with_warmup(2.0, warmup_steps=4)
    assert float(const(2)) == pytest.approx(1.0)
    assert float(const(50)) == pytest.approx(2.0)


@pytest.mark.slow
def test_classifier_learns():
    model = small_classifier()
    batch = toy_batch()
    params = model.init(jax.random.PRNGKey(0), batch["image"])
    tx = make_optimizer(3e-3, gradient_clip=1.0)
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
    step = make_train_step(classification_loss_fn(model.apply))

    first_loss = None
    for _ in range(40):
        state, metrics = step(state, batch)
        if first_loss is None:
            first_loss = float(metrics["loss"])
    assert float(metrics["loss"]) < first_loss * 0.1
    assert float(metrics["acc"]) > 0.9
    assert int(state.step) == 40


@pytest.mark.slow
def test_clm_train_step_runs():
    config = CausalLanguageModelConfig(
        vocab_size=50, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    rng = np.random.default_rng(0)
    t = rng.integers(0, 50, size=(4, 25))
    x = jnp.asarray(t[:, :-1])
    pad = jnp.zeros((4, 24), bool)
    batch = {"labels": jnp.asarray(t[:, 1:]), "input_ids": x, "pad_mask": pad}
    params = model.init(jax.random.PRNGKey(0), x, prefix_len=16)
    tx = make_optimizer(1e-3)
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
    step = make_train_step(clm_loss_fn(model.apply, max_latents=8))
    state, metrics = step(state, batch)
    loss0 = float(metrics["loss"])
    state, metrics = step(state, batch)
    assert np.isfinite(loss0) and np.isfinite(float(metrics["loss"]))
    # near-uniform init: loss ~ log(vocab)
    assert loss0 == pytest.approx(np.log(50), rel=0.3)


def test_clm_rejects_short_sequences():
    config = CausalLanguageModelConfig(
        vocab_size=50, max_seq_len=24, max_latents=16, num_channels=32,
        num_heads=4, num_self_attention_layers=1,
    )
    model = CausalLanguageModel(config)
    loss = clm_loss_fn(model.apply, max_latents=16)
    batch = {
        "labels": jnp.zeros((1, 8), jnp.int32),
        "input_ids": jnp.zeros((1, 8), jnp.int32),
        "pad_mask": jnp.zeros((1, 8), bool),
    }
    with pytest.raises(ValueError, match="at least 16"):
        loss(None, batch, jax.random.PRNGKey(0))


@pytest.mark.parametrize("mesh_shape", [{"data": 8}, {"data": 2, "fsdp": 4}, {"fsdp": 8}])
@pytest.mark.slow
def test_sharded_training(mesh_shape):
    """DDP / FSDP / hybrid parity: one SPMD program over an 8-device mesh
    (replaces reference DDPStrategy + FSDPStrategy, SURVEY §2.7 P1-P2)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = make_mesh(**mesh_shape)

    model = small_classifier()
    batch = toy_batch(n=16)
    params = model.init(jax.random.PRNGKey(0), batch["image"])
    tx = make_optimizer(1e-3, gradient_clip=1.0)
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
    state = shard_train_state(state, mesh, min_weight_size=0)
    batch = shard_batch(batch, mesh)

    step = make_train_step(classification_loss_fn(model.apply))
    state, metrics = step(state, batch)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))

    if mesh.shape["fsdp"] > 1:
        # at least one parameter is actually sharded over fsdp
        shardings = jax.tree.leaves(fsdp_param_shardings(state.params, mesh, min_weight_size=0))
        assert any("fsdp" in str(s.spec) for s in shardings)
        placed = [p.sharding for p in jax.tree.leaves(state.params)]
        assert any("fsdp" in str(s.spec) for s in placed if hasattr(s, "spec"))


@pytest.mark.slow
def test_gradient_accumulation():
    model = small_classifier()
    batch = toy_batch(n=8)
    params = model.init(jax.random.PRNGKey(0), batch["image"])
    tx = make_optimizer(1e-3, accumulate_grad_batches=4)
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
    step = make_train_step(classification_loss_fn(model.apply))
    p0 = jax.tree.leaves(state.params)[0].copy()
    for i in range(3):
        state, _ = step(state, batch)
    # parameters unchanged until the 4th micro-step
    np.testing.assert_array_equal(np.asarray(jax.tree.leaves(state.params)[0]), np.asarray(p0))
    state, _ = step(state, batch)
    assert not np.array_equal(np.asarray(jax.tree.leaves(state.params)[0]), np.asarray(p0))


@pytest.mark.slow
def test_mlm_memorizes_fixed_batch():
    """End-to-end MLM gradient flow: a fixed masked batch is driven well
    below the output-marginal plateau (~2.8 nats on this corpus) — the
    contextual-learning escape that streaming smoke runs only reach with
    longer budgets (docs/results/RESULTS.md)."""
    from perceiver_io_tpu.core.config import PerceiverIOConfig
    from perceiver_io_tpu.data.text import SyntheticTextDataModule
    from perceiver_io_tpu.models.text import MaskedLanguageModel, TextDecoderConfig, TextEncoderConfig
    from perceiver_io_tpu.training.losses import masked_lm_loss_fn

    dm = SyntheticTextDataModule(task="mlm", max_seq_len=128, batch_size=16, cache_dir=None)
    batch = next(iter(dm.train_batches()))
    config = PerceiverIOConfig(
        encoder=TextEncoderConfig(vocab_size=dm.vocab_size, max_seq_len=128),
        decoder=TextDecoderConfig(vocab_size=dm.vocab_size, max_seq_len=128),
        num_latents=64,
        num_latent_channels=64,
    )
    model = MaskedLanguageModel(config)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 128), np.int32))
    state = TrainState.create(model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1))
    step = make_train_step(masked_lm_loss_fn(model.apply))
    first = None
    for _ in range(300):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert first > 4.0  # starts near uniform ln(262) ~ 5.6
    assert float(metrics["loss"]) < 2.0  # breaks the ~2.8 marginal plateau


def test_microbatched_step_matches_full_batch():
    """microbatch=k chunking inside the step is the full-batch step: same
    gradients (fp reassociation tolerance) and same loss for a
    deterministic-loss model (prefix dropout off — chunks draw different
    dropout keys by design)."""
    import numpy as np

    from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step

    config = CausalLanguageModelConfig(
        vocab_size=64, max_seq_len=32, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=1, cross_attention_dropout=0.0,
    )
    model = CausalLanguageModel(config)
    rng = np.random.default_rng(0)
    t = rng.integers(0, 64, size=(4, 33))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"][:, :9], prefix_len=1)
    loss_fn = clm_loss_fn(model.apply, max_latents=8)

    def state():
        tx = make_optimizer(1e-2, gradient_clip=1.0)
        return TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))

    s_full, m_full = make_train_step(loss_fn, donate=False)(state(), batch)
    s_mb, m_mb = make_train_step(loss_fn, donate=False, microbatch=2)(state(), batch)

    np.testing.assert_allclose(float(m_mb["loss"]), float(m_full["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s_mb.params), jax.tree.leaves(s_full.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)

    with pytest.raises(ValueError, match="does not divide"):
        make_train_step(loss_fn, donate=False, microbatch=3)(state(), batch)


def test_compact_adam_matches_optax_adam():
    """scale_by_adam_compact at f32 storage IS optax.adam; at bf16 storage it
    tracks it to moment-storage precision (the HBM-diet optimizer,
    docs/performance.md round-4)."""
    import optax

    from perceiver_io_tpu.training.optim import scale_by_adam_compact

    params = {"w": jnp.linspace(-1.0, 1.0, 32).reshape(4, 8), "b": jnp.ones((8,))}
    grads = [
        {"w": jnp.sin(jnp.arange(32.0)).reshape(4, 8) * 0.1, "b": jnp.cos(jnp.arange(8.0))},
        {"w": jnp.full((4, 8), -0.05), "b": jnp.arange(8.0) * 0.01},
        {"w": jnp.ones((4, 8)) * 0.2, "b": -jnp.ones((8,)) * 0.3},
    ]

    ref = optax.scale_by_adam()
    f32 = scale_by_adam_compact(moment_dtype="float32")
    b16 = scale_by_adam_compact(moment_dtype="bfloat16")
    s_ref, s_f32, s_b16 = ref.init(params), f32.init(params), b16.init(params)
    for g in grads:
        u_ref, s_ref = ref.update(g, s_ref)
        u_f32, s_f32 = f32.update(g, s_f32)
        u_b16, s_b16 = b16.update(g, s_b16)
        for a, b in zip(jax.tree.leaves(u_ref), jax.tree.leaves(u_f32)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
        for a, b in zip(jax.tree.leaves(u_ref), jax.tree.leaves(u_b16)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0.05, atol=0.05)
    # storage dtype honored (the point of the transform)
    assert all(m.dtype == jnp.bfloat16 for m in jax.tree.leaves(s_b16.mu))
    assert all(v.dtype == jnp.bfloat16 for v in jax.tree.leaves(s_b16.nu))


def test_make_optimizer_moment_dtype():
    from perceiver_io_tpu.training.optim import make_optimizer as mk

    params = {"w": jnp.ones((4, 4))}
    tx = mk(1e-3, moment_dtype="bfloat16")
    state = tx.init(params)
    moments = [x for x in jax.tree.leaves(state) if hasattr(x, "dtype") and x.shape == (4, 4)]
    assert moments and all(m.dtype == jnp.bfloat16 for m in moments)
    # a full update runs and changes params in the right direction
    u, _ = tx.update({"w": jnp.ones((4, 4))}, state, params)
    assert float(jax.tree.leaves(u)[0].sum()) < 0
    with pytest.raises(ValueError, match="moment_dtype"):
        mk(1e-3, optimizer="sgd", moment_dtype="bfloat16")


def test_microbatch_loss_weighting_declarations():
    """masked-LM (count-normalized) is rejected at build time for
    microbatch>1; classification (per-example mean) declares itself uniform
    and is allowed even with a padded batch (ADVICE r3: explicit contract
    instead of pad_mask key sniffing alone)."""
    from perceiver_io_tpu.training import classification_loss_fn, masked_lm_loss_fn, mse_loss_fn

    mlm = masked_lm_loss_fn(lambda *a, **k: None)
    assert mlm.uniform_weighting is False
    with pytest.raises(ValueError, match="uniform_weighting=False"):
        make_train_step(mlm, microbatch=2)

    clf_apply_calls = []

    def clf_apply(params, x, **kwargs):
        clf_apply_calls.append(kwargs.get("pad_mask") is not None)
        return jnp.zeros((x.shape[0], 4))

    clf = classification_loss_fn(clf_apply)
    assert clf.uniform_weighting is True
    step = make_train_step(clf, microbatch=2, donate=False)
    params = {"w": jnp.zeros((2,))}
    tx = make_optimizer(1e-2)
    state = TrainState.create(None, params, tx, jax.random.PRNGKey(0))
    batch = {
        "x": jnp.zeros((4, 8)),
        "label": jnp.zeros((4,), jnp.int32),
        "pad_mask": jnp.zeros((4, 8), bool),  # padded batch: still allowed
    }
    _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert mse_loss_fn(lambda *a, **k: jnp.zeros((2, 2))).uniform_weighting is True
