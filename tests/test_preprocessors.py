"""Inference-side preprocessor tests (reference: TextPreprocessor in
perceiver/data/text/common.py, ImagePreprocessor/ImageNetPreprocessor in
perceiver/data/vision/{common,imagenet}.py) and the C4 streaming module's
offline surface."""

import numpy as np
import pytest

from perceiver_io_tpu.data.text.preprocessor import TextPreprocessor
from perceiver_io_tpu.data.vision.preprocessor import (
    ImageNetPreprocessor,
    ImagePreprocessor,
    center_crop,
)


class TestTextPreprocessor:
    def test_batch_padding_and_mask(self):
        pre = TextPreprocessor(max_seq_len=16)
        ids, pad = pre.preprocess_batch(["abc", "abcdef"])
        assert ids.shape == pad.shape == (2, 6)
        assert not pad[1].any()
        assert pad[0, 3:].all() and not pad[0, :3].any()

    def test_max_len_cap(self):
        pre = TextPreprocessor(max_seq_len=4)
        ids, pad = pre.preprocess("abcdefgh")
        assert ids.shape == (1, 4)

    def test_left_padding(self):
        pre = TextPreprocessor(padding_side="left")
        ids, pad = pre.preprocess_batch(["ab", "abcd"])
        assert pad[0, :2].all() and not pad[0, 2:].any()


class TestImagePreprocessor:
    def test_imagenet_val_transform_shape(self):
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, size=(300, 400, 3), dtype=np.uint8)
        out = ImageNetPreprocessor().preprocess(img)
        assert out.shape == (224, 224, 3)
        # normalized to roughly [-1, 1]
        assert -1.01 <= out.min() and out.max() <= 1.01

    def test_resize_shortest_side(self):
        img = np.zeros((100, 200, 3), np.float32)
        out = ImagePreprocessor(size=50, crop_size=None, image_mean=0.0, image_std=1.0).preprocess(img)
        assert out.shape == (50, 100, 3)

    def test_channels_first_input_and_output(self):
        img = np.zeros((3, 64, 80), np.float32)
        out = ImagePreprocessor(size=None, crop_size=32, channels_last=False).preprocess(img)
        assert out.shape == (3, 32, 32)

    def test_center_crop_values(self):
        img = np.arange(16, dtype=np.float32).reshape(4, 4, 1)
        out = center_crop(img, 2, 2)
        np.testing.assert_array_equal(out[..., 0], [[5, 6], [9, 10]])

    def test_crop_larger_than_image_rejected(self):
        with pytest.raises(ValueError, match="smaller than crop"):
            center_crop(np.zeros((4, 4, 1)), 8, 8)

    def test_resize_preserves_constant_images(self):
        img = np.full((30, 40, 3), 0.25, np.float32)
        out = ImagePreprocessor(size=64, crop_size=None, image_mean=0.0, image_std=1.0).preprocess(img)
        np.testing.assert_allclose(out, 0.25, atol=1e-6)


class TestC4DataModule:
    def test_offline_construction_and_pipeline(self):
        """The module builds without network; the streaming machinery is
        exercised by swapping in a local text iterator."""
        from perceiver_io_tpu.data.text.c4 import C4DataModule

        dm = C4DataModule(max_seq_len=16, min_seq_len=8, batch_size=2, shard_for_processes=False)
        assert dm.vocab_size == 262
        # substitute the (network) source with local text to drive the path
        dm.text_iter_fn = lambda: iter(["hello world " * 8] * 20)
        batch = next(iter(dm.batches(train=True)))
        assert batch["input_ids"].shape[0] == 2
        assert set(batch) == {"labels", "input_ids", "pad_mask"}
