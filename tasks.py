"""Dev task runner (reference: tasks.py:7-101). The reference uses `invoke`;
that package isn't a framework dependency, so this is a dependency-free
equivalent with the same task names:

    python tasks.py test [--cov]
    python tasks.py test-fast          # the sub-2-minute subset (-m "not slow")
    python tasks.py code-check         # ruff lint over the package + tests
    python tasks.py clean              # caches + test + build artifacts
    python tasks.py build              # sdist/wheel via pyproject
    python tasks.py docker [--tag TAG]
    python tasks.py bench [...args]    # the driver benchmark (real chip)
    python tasks.py graphlint [...]    # static-analysis gate (compiled graphs)
    python tasks.py perf [...]         # perf CI: graphcheck contracts + graphlint + bench floors + obs gate
    python tasks.py obs [...]          # observability gate (spans/requests/SLO + obs_diff self-check)
    python tasks.py load [...]         # serving load gate (closed-loop loadgen + flight recorder + /metrics)
    python tasks.py sim [...]          # discrete-event scale gate (multi-tenant sim of the real engine)
    python tasks.py dryrun [...]       # 8-virtual-device multichip certification
    python tasks.py chaos [...]        # fault-injection gate (preempt/NaN/torn-save/elastic resume/serving)
"""

from __future__ import annotations

import argparse
import os
import re
import shutil
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).parent

TASKS = {}


def task(fn):
    TASKS[fn.__name__.replace("_", "-")] = fn
    return fn


def run(*cmd: str, env: dict | None = None) -> None:
    print("+", " ".join(cmd))
    subprocess.run(cmd, cwd=ROOT, check=True, env=env)


@task
def test(args):
    cmd = [sys.executable, "-m", "pytest", "tests", "--durations=25", "-q"]
    if args.cov:
        cmd += ["--cov=perceiver_io_tpu", "--cov-report=term"]
    if args.rest:
        cmd += args.rest
    run(*cmd)


@task
def test_fast(args):
    run(sys.executable, "-m", "pytest", "tests", "-q", "-m", "not slow", *args.rest)


@task
def code_check(args):
    run(sys.executable, "-m", "ruff", "check", "perceiver_io_tpu", "tests", "examples", *args.rest)


@task
def clean_cache(args=None):
    for pattern in ("**/__pycache__", "**/*.pyc", "**/*.pyo"):
        for p in ROOT.glob(pattern):
            if ".git" in p.parts:
                continue
            shutil.rmtree(p, ignore_errors=True) if p.is_dir() else p.unlink(missing_ok=True)
    shutil.rmtree(ROOT / ".mypy_cache", ignore_errors=True)


@task
def clean_test(args=None):
    for name in (".pytest_cache", "htmlcov"):
        shutil.rmtree(ROOT / name, ignore_errors=True)
    (ROOT / ".coverage").unlink(missing_ok=True)


@task
def clean_preproc(args=None):
    shutil.rmtree(ROOT / ".cache", ignore_errors=True)


@task
def clean_build(args=None):
    shutil.rmtree(ROOT / "dist", ignore_errors=True)


@task
def clean(args=None):
    clean_cache()
    clean_test()
    clean_build()


@task
def build(args):
    clean()
    run(sys.executable, "-m", "build", "--sdist", "--wheel")


@task
def docker(args):
    run("docker", "build", "-t", "perceiver-io-tpu", ".")
    if args.tag:
        run("docker", "tag", "perceiver-io-tpu", f"perceiver-io-tpu:{args.tag}")


@task
def bench(args):
    run(sys.executable, "bench.py", *args.rest)


@task
def dryrun(args):
    """Multichip certification gate: the forced-8-device dryrun (every mesh
    kind, the ring strategy, the overlap-scheduled step, sharded decode) plus
    the distributed test suites — which otherwise only run when someone
    remembers to. Extra args go to pytest (e.g. ``-k overlap``)."""
    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "", env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    # dryrun_multichip provisions its own virtual devices (subprocess respawn)
    run(sys.executable, "-c", "import __graft_entry__; __graft_entry__.dryrun_multichip(8)")
    run(
        sys.executable, "-m", "pytest",
        "tests/test_overlap.py", "tests/test_distributed.py",
        "tests/test_seq_parallel_step.py", "tests/test_ring_attention.py",
        "-q", *args.rest,
        env=env,
    )


@task
def chaos(args):
    """Fault-injection gate (tools/chaos.py; docs/robustness.md): SIGTERM
    preemption + auto-resume equivalence (unsharded AND data x fsdp mesh),
    loader fetch retries, NaN-grad sentinel skip/rollback, torn-save
    quarantine, the four mesh-ELASTIC resume scenarios (elastic_shrink
    8->4, elastic_grow 4->8, flat_to_mesh, mesh_to_flat — kill and resume
    run on different virtual-device topologies, trajectory must match
    <= 1e-6 with a span-attributed resume.reshard event and a clean
    graphlint pass on the new mesh), and the SERVING scenarios
    (serve_overload / serve_kill_mid_decode / serve_deadline / serve_drain
    / serve_breaker / the engine + speculative kill scenarios — the
    Shedline front end and Pageline engine under injected failures, clean
    books certified, docs/robustness.md#serving-hardening — plus the
    Evictline pair: serve_evict_storm, page-pressure preemption with
    token-exact resume, and serve_crash_recover, a journal-backed engine
    restart with books balanced across it,
    docs/robustness.md#engine-eviction-and-recovery — and the Shareline
    storm: serve_prefix_storm, N same-prefix requests served off ONE
    prefill of the shared run, token-exact vs the unshared reference with
    refcounts balanced at drain, docs/serving.md#prefix-sharing). Extra
    args go to tools/chaos.py; ``--scenarios`` takes names or fnmatch
    globs (e.g. ``--scenarios 'serve_*'``)."""
    run(sys.executable, "tools/chaos.py", *args.rest)


@task
def graphlint(args):
    """Static-analysis gate over the flagship compiled graphs
    (tools/graphlint.py; docs/static-analysis.md)."""
    run(sys.executable, "tools/graphlint.py", "--fail-on", "error", *args.rest)


@task
def hostlint(args):
    """Static protocol analysis of the host-side serving stack
    (tools/hostlint.py; docs/static-analysis.md#hostlint): CFG/call-graph
    rules — books-exactness, shared-state-race, clock-discipline,
    grant-pairing, event-schema — over perceiver_io_tpu/serving/ + obs/
    with the committed reasoned allowlist. Pure-AST: no JAX, no compile,
    sub-second. Gates at warn — an unsuppressed warn is a finding that
    never got triaged."""
    run(sys.executable, "tools/hostlint.py", "--fail-on", "warn", *args.rest)


@task
def obs(args):
    """Observability gate (tools/obs_gate.py; docs/observability.md): a
    10-step synthetic fit + instrumented generate requests, event-stream
    schema/span validation, obs_report render, obs_diff run-vs-itself
    (must be clean). Extra args pass through (e.g. ``--baseline DIR``,
    ``--out DIR --keep`` to record a new baseline)."""
    run(sys.executable, "tools/obs_gate.py", *args.rest)


@task
def load(args):
    """Serving-observability gate (tools/loadgen.py; docs/observability.md#
    serving-observability-loadline): a 200-request closed-loop load run
    through the instrumented decode path with the flight recorder and the
    /metrics///slo scrape server live — validates the event stream, asserts
    a planted SLO breach produces exactly one flight dump naming the
    breaching span, run-vs-itself comparability diff must be clean, and the
    ledger's LOAD_r*.json floors must hold. Extra args pass through (e.g.
    ``--smoke``, ``--write-artifact``, ``--mode open --rate 20``)."""
    run(sys.executable, "tools/loadgen.py", *args.rest)


@task
def sim(args):
    """Discrete-event scale gate (tools/sim.py; docs/serving.md#
    multi-tenant-telemetry): drives a seeded multi-tenant workload through
    the REAL engine front end — admission, page allocator, Evictline,
    breaker, books — under a ManualClock with service times sampled from
    the committed LOAD artifact, at thousands of simulated req/s in
    seconds of host time. Asserts books balanced + allocator audits clean,
    per-tenant /metrics series and /slo?tenant= live, Jain's-fairness /
    starvation SIM floors, and a run-vs-itself diff_sim clean. Extra args
    pass through (e.g. ``--smoke``, ``--write-artifact``,
    ``--diff OLD NEW``)."""
    run(sys.executable, "tools/sim.py", *args.rest)


@task
def perf(args):
    """The standing perf-CI gate (docs/static-analysis.md): graphcheck —
    compiled-graph contracts vs contracts/, graduation-ledger validation,
    committed-bench floors — then the graphlint rule gate, then the
    dataflow rules (rng-key-reuse, dead-compute, sharding-flow,
    cross-program-consistency) over all five flagship programs, then the
    observability gate — the RUNTIME leg: with ``OBS_BASELINE_RUN`` set to
    a recorded baseline run directory (``tasks.py obs --out DIR --keep``),
    obs_diff classifies MFU/goodput/step-p99/SLO drift against it under
    declared tolerances (stale = not comparable ≠ regression) — and
    then the serving-load smoke gate (``tools/loadgen.py --smoke``:
    closed-loop load telemetry + flight recorder + LOAD floors), then the
    spec-decode smoke (``tools/spec_smoke.py``: speculative draft/verify
    token-exactness + rng-chain alignment + acceptance sanity on the tiny
    gate model), and
    finally the serve-chaos smoke (``tools/chaos.py --scenarios
    serve_kill_mid_decode,serve_crash_recover --smoke``: a mid-decode kill
    through the hardened front end with the clean-books audit, plus an
    engine crash recovered token-exactly from the write-ahead journal with
    books balanced across the restart), the fleet-chaos smoke
    (``tools/chaos.py --scenarios serve_fleet_failover --smoke``: a
    replica killed mid-decode behind the FleetRouter, its journal
    replayed token-exactly onto the survivor with fleet books balanced),
    and the simulation smoke
    (``tools/sim.py --smoke``: the Simline multi-tenant discrete-event
    gate over the real engine control plane — fairness + books + SIM
    floors + per-tenant scrape surface). Extra args go to
    tools/graphcheck.py (e.g. ``--programs train_flat,decode``)."""
    # hostlint first: the cheapest leg (pure AST, no compile) fails fast
    # on a serving-protocol regression before anything compiles a graph
    run(sys.executable, "tools/hostlint.py", "--fail-on", "warn")
    run(sys.executable, "tools/graphcheck.py", *args.rest)
    run(sys.executable, "tools/graphlint.py", "--fail-on", "error")
    # trace-only on purpose: graphcheck just compiled the same five
    # programs; the dataflow rules need only the jaxpr
    run(sys.executable, "tools/graphlint.py", "--programs", "all",
        "--no-compiled", "--fail-on", "error")
    obs_cmd = [sys.executable, "tools/obs_gate.py"]
    baseline = os.environ.get("OBS_BASELINE_RUN")
    if baseline:
        obs_cmd += ["--baseline", baseline]
    run(*obs_cmd)
    # serving-load leg (CI-fast): a small closed-loop run through the
    # instrumented path — events validate, planted breach -> one flight
    # dump, run-vs-itself diff clean, LOAD_r* ledger floors hold
    run(sys.executable, "tools/loadgen.py", "--smoke")
    # engine leg (Pageline, docs/serving.md): the same closed loop through
    # the continuous-batching paged-KV engine — books + page-allocator
    # audits, a planted mid-decode kill inside a live batch, engine gauges
    # on /metrics, and the engine throughput/p99-TPOT ledger floors
    run(sys.executable, "tools/loadgen.py", "--smoke", "--engine")
    # prefix-sharing leg (Shareline, docs/serving.md#prefix-sharing): the
    # shared-vs-unshared two-leg A/B in smoke size on the wide gate model —
    # legs token-bit-exact, refcounts/index drained clean, sharing counters
    # on /metrics (the full-size measured round is `tasks.py load --prefix`)
    run(sys.executable, "tools/loadgen.py", "--smoke", "--prefix")
    # spec-decode smoke leg (Specline): greedy token-exactness + rng-chain
    # alignment + acceptance-rate sanity of the speculative draft/verify
    # pair on the tiny gate model (tools/spec_smoke.py)
    run(sys.executable, "tools/spec_smoke.py")
    # serve-chaos smoke leg: kill a request mid-decode through the hardened
    # front end and audit the books, tear the ENGINE down mid-decode and
    # recover it token-exactly from the write-ahead journal (Evictline),
    # and serve a same-prefix storm off ONE shared prefill with refcounts
    # balanced at drain (Shareline; --smoke keeps the legs greedy-only/
    # CI-fast — the full serve_* family runs under `tasks.py chaos`)
    run(sys.executable, "tools/chaos.py", "--scenarios",
        "serve_kill_mid_decode,serve_crash_recover,serve_prefix_storm",
        "--smoke")
    # fleet-chaos smoke leg (Fleetline, docs/serving.md#fleet): kill a
    # REPLICA mid-decode behind the FleetRouter — the survivor replays its
    # write-ahead journal token-exactly, the fleet books balance across
    # the handoff, one flight dump names the dead replica (the full
    # serve_fleet_*/sim_fleet family runs under `tasks.py chaos`)
    run(sys.executable, "tools/chaos.py", "--scenarios",
        "serve_fleet_failover", "--smoke")
    # simulation smoke leg (Simline): two tenants at ~1k simulated req/s
    # through the REAL engine front end under a ManualClock — books +
    # fairness + per-tenant /metrics///slo + self-diff, SIM ledger floors
    # (the full-size 3-tenant 10k req/s run is `tasks.py sim`)
    run(sys.executable, "tools/sim.py", "--smoke")


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("task", choices=sorted(TASKS))
    parser.add_argument("--cov", action="store_true", help="coverage (test)")
    parser.add_argument("--tag", help="docker image tag")
    parser.add_argument("rest", nargs="*", help="extra args passed through")
    # unknown flags flow through to the task's tool (`tasks.py load --smoke`,
    # `tasks.py chaos --scenarios preempt`) instead of dying in argparse
    args, unknown = parser.parse_known_args(argv)
    args.rest = args.rest + unknown
    TASKS[args.task](args)


if __name__ == "__main__":
    main()
