# TPU-ready image for perceiver-io-tpu (reference: Dockerfile — pytorch/cuda
# runtime + poetry; here a JAX TPU runtime + pip install).
FROM python:3.12-slim

WORKDIR /app
COPY pyproject.toml README.md ./
COPY perceiver_io_tpu ./perceiver_io_tpu

# On a TPU VM replace the first line with:
#   pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
RUN pip install --no-cache-dir jax \
    && pip install --no-cache-dir .[text,vision,audio,test]

ENTRYPOINT ["python", "-m"]
CMD ["perceiver_io_tpu.scripts.text.clm", "--help"]
