"""Run every task family's --smoke preset to completion (fully offline,
synthetic/local data) and export the loss curves — the convergence evidence
standing in for BASELINE.md's network-blocked real-data runs.

    python tools/convergence_runs.py [--out docs/results] [--tasks clm mlm ...]

Each run uses the task CLI's own --smoke preset (same entry a user runs);
metrics.csv is copied to <out>/<task>.csv and a summary line is printed.
"""

from __future__ import annotations

import argparse
import csv
import json
import math
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TASKS = {
    # module, extra args, metric of interest
    "clm": ("perceiver_io_tpu.scripts.text.clm", [], "val_loss"),
    "mlm": ("perceiver_io_tpu.scripts.text.mlm", [], "val_loss"),
    "txt_clf": ("perceiver_io_tpu.scripts.text.classifier", [], "val_acc"),
    "img_clf": ("perceiver_io_tpu.scripts.vision.image_classifier", [], "val_acc"),
    "sam": ("perceiver_io_tpu.scripts.audio.symbolic", [], "val_loss"),
    "timeseries": ("perceiver_io_tpu.scripts.timeseries", [], "val_loss"),
}

RUNNER = """
import jax, sys
jax.config.update("jax_platforms", "{platform}")
import importlib
mod = importlib.import_module("{module}")
mod.main({argv!r})
"""


def run_task(name: str, out_dir: str, platform: str) -> dict:
    module, extra, metric = TASKS[name]
    root = tempfile.mkdtemp(prefix=f"smoke_{name}_")
    try:
        argv = [
            "fit",
            "--smoke",
            f"--trainer.default_root_dir={root}",
            f"--trainer.name={name}",
            "--trainer.checkpoint=false",
        ] + extra
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, "-c", RUNNER.format(platform=platform, module=module, argv=argv)],
            cwd=REPO,
            capture_output=True,
            text=True,
        )
        wall = time.time() - t0
        if proc.returncode != 0:
            raise RuntimeError(f"{name} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
        src = os.path.join(root, name, "metrics.csv")
        dst = os.path.join(out_dir, f"{name}.csv")
        shutil.copy(src, dst)

        with open(dst) as f:
            rows = list(csv.DictReader(f))
        series = [(int(r["step"]), float(r[metric])) for r in rows if r.get(metric)]
        if not series:
            raise RuntimeError(
                f"{name}: no '{metric}' values in metrics.csv "
                f"(columns: {list(rows[0]) if rows else 'none'}) — did validation run?"
            )
        first, last = series[0], series[-1]
        summary = {
            "task": name,
            "metric": metric,
            "first": {"step": first[0], "value": round(first[1], 4)},
            "final": {"step": last[0], "value": round(last[1], 4)},
            "minutes": round(wall / 60, 1),
        }
        if metric == "val_loss" and name in ("clm", "mlm", "sam"):
            summary["final_bits_per_token"] = round(last[1] / math.log(2), 3)
        return summary
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="docs/results")
    p.add_argument("--tasks", nargs="*", default=list(TASKS))
    p.add_argument("--platform", default="cpu", help="cpu keeps the TPU free; smoke sizes are CPU-sized")
    args = p.parse_args()

    out_dir = os.path.join(REPO, args.out)
    os.makedirs(out_dir, exist_ok=True)
    summaries = []
    for name in args.tasks:
        print(f"=== {name} ===", flush=True)
        s = run_task(name, out_dir, args.platform)
        print(json.dumps(s), flush=True)
        summaries.append(s)
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summaries, f, indent=2)


if __name__ == "__main__":
    main()
