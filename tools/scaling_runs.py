"""Offline multi-model scaling runs to convergence (VERDICT r3 item 8).

Round 3 proved the approach-1 fit MECHANICS on 600-step smoke curves over the
template corpus; that corpus is memorizable (the 256ch model reached val 0.16),
so converged curves there carry no scaling physics. This driver:

1. generates a deterministic HIGH-ENTROPY corpus (seeded order-1 Markov chain
   over a zipfian word vocabulary — enough entropy that the model grid stays
   capacity-limited, with a nonzero irreducible loss),
2. trains the three study model sizes to convergence (val_loss plateau) for
   each requested seed via the real CLM CLI on ``TextFileDataModule``,
3. exports curves to ``examples/scaling/clm/data/offline_runs/seed<k>/`` and
   runs the free-exponent approach-1 fit per seed
   (``scaling_study.py fit-demo --free-exponents``), reporting exponent
   stability across seeds.

    python tools/scaling_runs.py [--seeds 0 1] [--steps 2000] [--platform cpu]
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "examples", "scaling", "clm", "data", "offline_runs")

# (num_channels, num_self_attention_layers) — total layers incl. the hybrid
# cross-attention layer is sa+1, matching the study grid labels 3l/4l/5l
GRID = [(128, 2), (192, 3), (256, 4)]


def make_corpus(path: str, n_words: int = 2_000_000, vocab: int = 2048, seed: int = 7) -> None:
    """Seeded order-1 Markov word stream (state = previous word) over a zipfian vocabulary.

    Entropy is controlled by the per-state successor fan-out (8): an ideal
    model's loss floor is ~log(8)/avg_word_len nats/byte > 0, and word
    statistics give mid-sized models something real to learn — unlike the
    template corpus, bigger models cannot simply memorize their way to ~0.
    """
    rng = np.random.default_rng(seed)
    words = np.array([f"w{i}" for i in range(vocab)])
    # zipfian unigram draw for successor tables: common words are common
    ranks = np.arange(1, vocab + 1)
    p = 1.0 / ranks
    p /= p.sum()
    fanout = 8
    succ = rng.choice(vocab, size=(vocab, fanout), p=p)
    state = 0
    out = []
    for _ in range(n_words):
        state = int(succ[state, rng.integers(fanout)])
        out.append(words[state])
    text = " ".join(out)
    with open(path, "w") as f:
        f.write(text)


def corpus_valid(path: str, min_bytes: float = 30e6) -> bool:
    """True iff ``path`` is a complete seed-7 ``make_corpus`` stream: size
    plus the chain's deterministic first words. /tmp is world-shared — a
    foreign or truncated file would silently detach a run from the corpus's
    analytic entropy floor. Shared by flagship_convergence and the int8
    trained probe so the guard and the generator stay in one file."""
    try:
        if os.path.getsize(path) < min_bytes:
            return False
        with open(path) as f:
            return f.read(16).startswith("w725 w3 w1037 ")
    except OSError:
        return False


def run_one(channels: int, sa_layers: int, seed: int, steps: int, corpus: str,
            out_csv: str, platform: str) -> None:
    root = tempfile.mkdtemp(prefix=f"scaling_{channels}ch_s{seed}_")
    # platform "default" leaves backend selection to JAX (i.e. the real
    # accelerator when one is attached); a named platform pins it
    select = "" if platform in ("", "default") else (
        f"import jax; jax.config.update('jax_platforms', '{platform}')\n"
    )
    code = (
        select
        + f"import sys; sys.path.insert(0, {REPO!r})\n"
        "from perceiver_io_tpu.scripts.text.clm import main\n"
        f"main({_argv(channels, sa_layers, seed, steps, corpus, root)!r})\n"
    )
    env = dict(os.environ)
    env.setdefault("XLA_FLAGS", "")
    t = subprocess.run([sys.executable, "-c", code], env=env, capture_output=True, text=True)
    if t.returncode != 0:
        raise RuntimeError(f"run {channels}ch seed {seed} failed:\n{t.stderr[-3000:]}")
    src = os.path.join(root, "logs", "run", "metrics.csv")
    os.makedirs(os.path.dirname(out_csv), exist_ok=True)
    shutil.copy(src, out_csv)
    shutil.rmtree(root, ignore_errors=True)


def _argv(channels, sa_layers, seed, steps, corpus, root):
    return [
        "fit",
        "--data.dataset=textfile",
        f"--data.train_file={corpus}",
        "--data.max_seq_len=1024",
        "--data.batch_size=8",
        f"--data.cache_dir={root}/cache",
        "--model.max_latents=256",
        f"--model.num_channels={channels}",
        f"--model.num_self_attention_layers={sa_layers}",
        "--model.num_heads=8",
        f"--trainer.max_steps={steps}",
        "--trainer.val_interval=200",
        "--trainer.log_interval=100",
        "--trainer.devices=1",
        "--trainer.checkpoint=false",
        f"--trainer.seed={seed}",
        f"--trainer.default_root_dir={root}/logs",
        "--trainer.name=run",
        "--optimizer.lr=6e-4",
        "--optimizer.warmup_steps=100",
    ]


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", type=int, nargs="*", default=[0, 1])
    p.add_argument("--steps", type=int, default=2000)
    p.add_argument("--platform", default="cpu")
    p.add_argument("--corpus", default=None, help="existing corpus file (default: generate)")
    p.add_argument("--jobs", type=int, default=3, help="parallel runs")
    args = p.parse_args(argv)

    corpus = args.corpus
    if corpus is None:
        corpus = os.path.join(tempfile.gettempdir(), "scaling_corpus_markov1.txt")
        if not os.path.exists(corpus):
            print("generating corpus ...", flush=True)
            make_corpus(corpus)
    print(f"corpus: {corpus} ({os.path.getsize(corpus)/1e6:.1f} MB)")

    from concurrent.futures import ThreadPoolExecutor

    jobs = []
    with ThreadPoolExecutor(max_workers=args.jobs) as ex:
        for seed in args.seeds:
            for channels, sa in GRID:
                out_csv = os.path.join(OUT, f"seed{seed}", f"clm_{channels}ch_{sa + 1}l.csv")
                jobs.append(
                    (out_csv,
                     ex.submit(run_one, channels, sa, seed, args.steps, corpus, out_csv,
                               args.platform))
                )
        for out_csv, fut in jobs:
            fut.result()
            print(f"done: {out_csv}", flush=True)

    print("\nper-seed free-exponent fits:")
    for seed in args.seeds:
        runspecs = []
        for c, l in GRID:
            runspecs += [
                "--run",
                os.path.join(OUT, f"seed{seed}", f"clm_{c}ch_{l + 1}l.csv") + f":{c}:{l + 1}",
            ]
        # NOTE: no PYTHONPATH override — it would drop the axon site dir this
        # environment injects; the package import works installed or via cwd
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "examples", "scaling", "scaling_study.py"),
             "fit-demo", "--free-exponents", *runspecs],
            capture_output=True, text=True, cwd=REPO,
        )
        if r.returncode != 0:
            raise RuntimeError(f"fit for seed {seed} failed:\n{r.stderr[-2000:]}")
        print(f"--- seed {seed} ---")
        print(r.stdout)


if __name__ == "__main__":
    main()
