"""Quantify int8-cache decode error on TRAINED weights (at random init the
observed max logit delta is ~0.004 — docs/performance.md; the contract test
tests/test_int8_cache.py asserts a looser <0.05 bound — but trained
activations have outliers the per-token scales must absorb, which neither
random-init number speaks to).

Trains the flagship-small geometry ~1000 steps on the Markov corpus
(tools/scaling_runs.make_corpus generates it if missing), then compares
incremental cached decode against the exact forward for BOTH cache dtypes —
the f32-cache control isolates kernel-path noise (different flash/einsum
routes between the one-shot forward and the chunked prompt+decode) from the
quantization itself.

Measured on v5e (2026-08-01): int8 max|dlogit| 0.158 / mean 0.0071 against
an f32-control path-noise floor of 0.084; top-1 agreement 99.62%;
teacher-forced CE: exact forward 0.70410, f32-cache decode 0.70439,
int8-cache decode 0.70437 — quantization adds NOTHING beyond the cached
route's own kernel-path noise.

    python tools/int8_trained_probe.py
"""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np

from perceiver_io_tpu.core.config import CausalSequenceModelConfig
from perceiver_io_tpu.core.modules import CausalSequenceModel
from perceiver_io_tpu.data.text.datamodule import TextFileDataModule
from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
from perceiver_io_tpu.training.loop import make_train_step

SEQ, LAT = 1024, 256
cfg = CausalSequenceModelConfig(
    vocab_size=262, max_seq_len=SEQ, max_latents=LAT, num_channels=512,
    num_self_attention_layers=8, num_self_attention_rotary_layers=-1, output_norm=True)
model = CausalSequenceModel(cfg, dtype=jnp.bfloat16)

corpus = "/tmp/flagship_corpus_markov1.txt"


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from scaling_runs import corpus_valid, make_corpus  # tools/ sibling

if not corpus_valid(corpus):
    make_corpus(corpus, n_words=8_000_000)
# cache key: TextFileDataModule's fingerprint does not cover file content,
# so derive the preproc cache dir from the corpus bytes themselves
import hashlib

tag = hashlib.md5(open(corpus, "rb").read(1 << 20)).hexdigest()[:10]
dm = TextFileDataModule(train_file=corpus, cache_dir=f"/tmp/int8probe_cache_{tag}",
                        max_seq_len=SEQ, batch_size=8)
dm.prepare()
def stream():
    while True:
        for b in dm.train_batches():
            yield b
it = stream()
b0 = next(it)
x0 = jnp.asarray(b0["input_ids"])
params = model.init(jax.random.PRNGKey(0), x0, prefix_len=SEQ - LAT)
tx = make_optimizer(6e-4, gradient_clip=1.0, moment_dtype="bfloat16")
state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
step = make_train_step(clm_loss_fn(model.apply, max_latents=LAT))
for i in range(1000):
    batch = next(it)
    state, m = step(state, {"input_ids": jnp.asarray(batch["input_ids"]),
                            "labels": jnp.asarray(batch["labels"]), "pad_mask": None})
    if i % 200 == 0:
        print(f"step {i} loss {float(m['loss']):.3f}", flush=True)
print(f"final loss {float(m['loss']):.3f}")

# trained params -> decode comparison on fresh sequences (f32 eval)
model32 = CausalSequenceModel(cfg)
p = jax.tree.map(lambda a: a.astype(jnp.float32), state.params)
batch = next(it)
x = jnp.asarray(batch["input_ids"])[:4]
prefix = SEQ - LAT
exact = model32.apply(p, x, prefix_len=prefix).logits

N_DEC = 64  # decode steps compared (one small jitted step, host loop)
prompt_fn = jax.jit(lambda p, xs, cache: model32.apply(
    p, xs, prefix_len=prefix, kv_cache=cache))
step_fn = jax.jit(lambda p, tok, cache: model32.apply(
    p, tok, prefix_len=prefix, kv_cache=cache, decode=True))

def cached_decode(dtype, pp=p):
    cache = CausalSequenceModel.init_cache(cfg, 4, dtype=dtype)
    out = prompt_fn(pp, x[:, : prefix + 2], cache)
    logits, c = [out.logits], out.kv_cache
    for i in range(2, 2 + N_DEC):
        o = step_fn(pp, x[:, prefix + i : prefix + i + 1], c)
        logits.append(o.logits); c = o.kv_cache
    return jnp.concatenate(logits, 1)

q = cached_decode(jnp.int8)
f = cached_decode(jnp.float32)

# weight-only int8 on TRAINED kernels (ops/quant.py): per-output-channel
# scales must absorb trained-weight outliers the random-init contract test
# never sees — reported alongside the cache numbers below
from perceiver_io_tpu.ops.quant import dequantize_weights, quantize_weights  # noqa: E402

pq = dequantize_weights(quantize_weights(p), jnp.float32)
w = cached_decode(jnp.float32, pq)  # int8 weights, f32 cache
wq = cached_decode(jnp.int8, pq)  # int8 weights + int8 cache
sl = exact[:, : 2 + N_DEC]
err = np.abs(np.asarray(q, np.float32) - np.asarray(sl, np.float32))
err_f = np.abs(np.asarray(f, np.float32) - np.asarray(sl, np.float32))
agree = (np.argmax(np.asarray(q), -1) == np.argmax(np.asarray(sl), -1)).mean()
labels = np.asarray(batch["labels"])[:4, -LAT:][:, : 2 + N_DEC]

def ce(lg):
    lp = jax.nn.log_softmax(jnp.asarray(lg))
    return float(-jnp.take_along_axis(lp, jnp.asarray(labels)[..., None], -1).mean())

err_w = np.abs(np.asarray(w, np.float32) - np.asarray(sl, np.float32))
agree_w = (np.argmax(np.asarray(w), -1) == np.argmax(np.asarray(sl), -1)).mean()

print(f"trained-weights decode vs exact: int8 max|dlogit|={err.max():.4f} "
      f"mean={err.mean():.5f} (f32-cache control max={err_f.max():.2e}) "
      f"top1-agree={agree:.4f} CE exact={ce(sl):.5f} CE f32cache={ce(f):.5f} "
      f"CE int8={ce(q):.5f}", flush=True)
print(f"trained-weights int8 WEIGHTS vs exact: max|dlogit|={err_w.max():.4f} "
      f"mean={err_w.mean():.5f} top1-agree={agree_w:.4f} "
      f"CE int8w={ce(w):.5f} CE int8w+int8kv={ce(wq):.5f}", flush=True)
