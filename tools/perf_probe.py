"""Decompose the flagship train step's time on the real chip.

Measures, with bench.py's hardened scan-slope methodology, the sustained
per-iteration time of:

  fwd         loss value only
  fwd_nodrop  loss value, deterministic (no prefix-dropout gather)
  grad        value_and_grad (fwd + bwd)
  grad_nodrop value_and_grad, deterministic
  step        full train step (grad + clip + adamw update)
  opt         optimizer update alone (fixed grads)

Usage: python tools/perf_probe.py [--seq-len 16384] [--latents 1024] ...
"""

from __future__ import annotations

import argparse
import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, robust_slope, train_step_flops

# persistent compile cache: probe iterations re-run the same programs;
# recompiling them through the tunnel costs minutes per case
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def scan_time(fn, carry_init, steps, *, n_short=2, extract=None):
    """Sustained per-iteration time of ``carry = fn(carry, i)`` via the
    two-chain-length slope (fixed dispatch costs cancel).

    ``extract(carry)`` must return a scalar whose value depends on the whole
    per-iteration computation — XLA dead-code-eliminates everything that
    doesn't feed the fetched value (a step-counter leaf makes the probe
    report dispatch latency, not compute)."""
    if extract is None:
        extract = lambda c: jax.tree.leaves(c)[0].reshape(-1)[0]

    @functools.partial(jax.jit, static_argnums=1)
    def run(carry, k):
        def body(c, i):
            c = fn(c, i)
            return c, ()

        c, _ = jax.lax.scan(body, carry, jnp.arange(k))
        return extract(c)

    return robust_slope(lambda k: float(run(carry_init, k)), n_short, n_short + steps)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=16384)
    p.add_argument("--latents", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--only", nargs="*", default=None)
    p.add_argument(
        "--sa-einsum",
        action="store_true",
        help="A/B: self-attention (nq==nkv) on the einsum path, CA stays flash",
    )
    p.add_argument("--no-flash", action="store_true", help="A/B: einsum everywhere")
    p.add_argument("--block-q", type=int, default=None, help="A/B: flash block_q override")
    p.add_argument("--block-kv", type=int, default=None, help="A/B: flash block_kv override")
    args = p.parse_args()

    if args.block_q or args.block_kv:
        import functools as _ft

        from perceiver_io_tpu.core import attention as _attn2
        from perceiver_io_tpu.ops.flash_attention import flash_attention as _fa
        from perceiver_io_tpu.ops.flash_attention import flash_attention_packed as _fap

        kw = {}
        if args.block_q:
            kw["block_q"] = args.block_q
        if args.block_kv:
            kw["block_kv"] = args.block_kv
        # patch BOTH entries: supported shapes route through the packed path
        _attn2.flash_attention = _ft.partial(_fa, **kw)
        _attn2.flash_attention_packed = _ft.partial(_fap, **kw)

    if args.sa_einsum:
        from perceiver_io_tpu.core import attention as _attn

        orig_supported = _attn.flash_supported
        _attn.flash_supported = (
            lambda nq, nkv, dqk, dv, drop: False if nq == nkv else orig_supported(nq, nkv, dqk, dv, drop)
        )
    if args.no_flash:
        from perceiver_io_tpu.ops.flash_attention import set_default_flash

        set_default_flash(False)

    from perceiver_io_tpu.models.text import CausalLanguageModel
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step

    config = flagship_config(args.seq_len, args.latents)
    model = CausalLanguageModel(config, dtype=jnp.bfloat16)
    import dataclasses

    det_model = CausalLanguageModel(
        dataclasses.replace(config, cross_attention_dropout=0.0), dtype=jnp.bfloat16
    )

    b, n = args.batch_size, args.seq_len
    rng = np.random.default_rng(0)
    t = rng.integers(0, config.vocab_size, size=(b, n + 1))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"][:, : args.latents + 1], prefix_len=1)
    tx = make_optimizer(1e-3, gradient_clip=1.0)
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))

    loss_fn = clm_loss_fn(model.apply, max_latents=args.latents)
    det_loss_fn = clm_loss_fn(det_model.apply, max_latents=args.latents)
    step = make_train_step(loss_fn, jit=False)

    flops = train_step_flops(config, b, prefix_dropout_keep=0.5)

    def fwd_iter(lf):
        def it(carry, i):
            l, r = carry
            r, sr = jax.random.split(r)
            loss, _ = lf(state.params, batch, sr)
            return (l + loss, r), None

        def fn(c, i):
            return it(c, i)[0]

        return fn

    def grad_iter(lf):
        grad_fn = jax.value_and_grad(lf, has_aux=True)

        def fn(carry, i):
            l, r = carry
            r, sr = jax.random.split(r)
            (loss, _), grads = grad_fn(state.params, batch, sr)
            # fold EVERY grad leaf into the carry: keeping only one leaf lets
            # XLA dead-code-eliminate the other leaves' weight-gradient outer
            # products (measured ~0.7 ms/step too fast at the 16k flagship)
            g = sum(x.reshape(-1)[0].astype(jnp.float32) for x in jax.tree.leaves(grads))
            return (l + loss + g, r)

        return fn

    def step_fn(carry, i):
        l, s = carry
        s, metrics = step(s, batch)
        return (l + metrics["loss"], s)

    (_, _), grads0 = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params, batch, jax.random.PRNGKey(2)
    )

    def opt_fn(s, i):
        return s.apply_gradients(grads0)

    def param_leaf(s):
        # a parameter value is live through every optimizer update
        return jax.tree.leaves(s.params)[0].reshape(-1)[0].astype(jnp.float32)

    cases = {
        "fwd": lambda: scan_time(fwd_iter(loss_fn), (jnp.float32(0), jax.random.PRNGKey(3)), args.steps),
        "fwd_nodrop": lambda: scan_time(fwd_iter(det_loss_fn), (jnp.float32(0), jax.random.PRNGKey(3)), args.steps),
        "grad": lambda: scan_time(grad_iter(loss_fn), (jnp.float32(0), jax.random.PRNGKey(3)), args.steps),
        "grad_nodrop": lambda: scan_time(grad_iter(det_loss_fn), (jnp.float32(0), jax.random.PRNGKey(3)), args.steps),
        "step": lambda: scan_time(step_fn, (jnp.float32(0), state), args.steps),
        "opt": lambda: scan_time(opt_fn, state, args.steps, extract=param_leaf),
    }
    names = args.only or list(cases)
    print(f"{'case':<12} {'ms':>8} {'tok/s':>12} {'TFLOPS':>8}")
    for name in names:
        ms = cases[name]() * 1e3
        tfl = flops / 1e12 / (ms / 1e3)
        print(f"{name:<12} {ms:8.3f} {b * n / (ms / 1e3):12.0f} {tfl:8.1f}")


if __name__ == "__main__":
    main()
