"""Spec-decode smoke — the ``tasks.py perf`` speculative leg (ISSUE 14).

A CI-fast certification of the speculative draft/verify pair on the tiny
gate model (the same ``build_workload`` geometry ``tasks.py load`` and the
``serve_*`` chaos scenarios run, loaded from tools/loadgen.py so the gates
cannot desynchronize):

1. **token-exactness** — the greedy speculative stream is bit-exact to the
   sequential ``make_decode_fns`` stream for k ∈ {1, 2}, and the rng chain
   state at every span boundary equals the sequential chain after the same
   emitted-token count (seeds reproduce);
2. **acceptance-rate sanity** — acceptance lands in [0, 1], the serial-step
   multiple (tokens per verify step) is >= 1.0, and at least one span
   emitted more than one token OR the drafter disagreed at least once (a
   vacuous run — zero spans — fails);
3. **temperature determinism** — same seed twice gives the same sampled
   stream through the speculative path.

Exit codes: 0 clean, 1 failure, 3 internal error.

    python tools/spec_smoke.py            # the gate
    python tools/spec_smoke.py --tokens 16 --k 4 --depth 1
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _gate_model():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "loadgen_cli", os.path.join(_REPO, "tools", "loadgen.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.build_workload()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    # the gate model's latent window is 8 with 4 initial latents — budget 4
    # is the largest no-slide speculative budget it admits
    p.add_argument("--tokens", type=int, default=4, help="decode budget per stream")
    p.add_argument("--k", type=int, default=None,
                   help="single k to check (default: both 1 and 2)")
    p.add_argument("--depth", type=int, default=1, help="drafter depth")
    args = p.parse_args(argv)

    try:
        import jax
        import jax.numpy as jnp  # noqa: F401
        import numpy as np

        from perceiver_io_tpu.generation import (
            GenerationConfig,
            make_decode_fns,
            make_speculative_decode_fns,
        )

        model, params, config = _gate_model()
        num_latents = 4
        n_new = args.tokens
        prompt = jnp.asarray(
            np.random.default_rng(3).integers(0, config.vocab_size, size=(1, 12))
        )
        problems = []

        def sequential(cfg, seed, extra=0):
            import dataclasses

            run_cfg = dataclasses.replace(cfg, max_new_tokens=cfg.max_new_tokens + extra)
            prefill, step = make_decode_fns(model, num_latents, run_cfg)
            tok, state = prefill(params, prompt, None, jax.random.PRNGKey(seed))
            out, rngs = [int(tok[0])], [np.asarray(state["rng"])]
            for _ in range(run_cfg.max_new_tokens - 1):
                state, tok = step(state)
                out.append(int(tok[0]))
                rngs.append(np.asarray(state["rng"]))
            return out, rngs

        def speculative(cfg, k, seed):
            prefill, step = make_speculative_decode_fns(
                model, num_latents, cfg, k=k, draft_depth=args.depth
            )
            tok, state = prefill(params, prompt, None, jax.random.PRNGKey(seed))
            out, bounds, spans, accepted = [int(tok[0])], [], 0, 0
            while len(out) < cfg.max_new_tokens:
                state, toks, m = step(state)
                m0 = int(m[0])
                spans += 1
                accepted += m0 - 1
                out.extend(int(t) for t in np.asarray(toks[0, :m0]))
                bounds.append((len(out), np.asarray(state["rng"])))
            return out, bounds, spans, accepted

        cfg = GenerationConfig(max_new_tokens=n_new)
        ks = [args.k] if args.k is not None else [1, 2]
        for k in ks:
            seq, rngs = sequential(cfg, seed=7, extra=k)
            out, bounds, spans, accepted = speculative(cfg, k, seed=7)
            if out[:n_new] != seq[:n_new]:
                problems.append(f"k={k}: greedy stream diverged: {out[:n_new]} vs {seq[:n_new]}")
            for emitted, rng_state in bounds:
                if not (rng_state == rngs[emitted - 1]).all():
                    problems.append(f"k={k}: rng chain misaligned after {emitted} tokens")
                    break
            rate = accepted / max(spans * k, 1)
            tps = (n_new - 1) / max(spans, 1)
            if not 0.0 <= rate <= 1.0:
                problems.append(f"k={k}: acceptance rate {rate} outside [0, 1]")
            if tps < 1.0:
                problems.append(f"k={k}: tokens_per_step {tps} < 1.0")
            if spans == 0:
                problems.append(f"k={k}: zero verify spans — the check is vacuous")
            print(f"spec_smoke: k={k} depth={args.depth}: token-exact, "
                  f"acceptance={rate:.2f}, tokens_per_step={tps:.2f} ({spans} spans)")

        cfg_t = GenerationConfig(
            max_new_tokens=n_new, do_sample=True, temperature=0.8, top_k=10
        )
        s1, *_ = speculative(cfg_t, 2, seed=9)
        s2, *_ = speculative(cfg_t, 2, seed=9)
        if s1 != s2:
            problems.append(f"temperature sampling nondeterministic: {s1} vs {s2}")
        else:
            print("spec_smoke: temperature same-seed streams identical")

        if problems:
            print("spec_smoke: FAILED:")
            for pb in problems:
                print(f"  - {pb}")
            return 1
        print("spec_smoke: OK")
        return 0
    except Exception as e:  # noqa: BLE001 — CI must see crash != verdict
        import traceback

        traceback.print_exc()
        print(f"spec_smoke: internal error: {e}", file=sys.stderr)
        return 3


if __name__ == "__main__":
    sys.exit(main())
