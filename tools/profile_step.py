"""Capture a device profile of the flagship train step and print the top
device ops (tools/xplane.py parser — no TensorFlow needed).

    python tools/profile_step.py [--batch-size 4] [--top 40] [--out /tmp/prof]

The per-op durations come from the device plane, so host/tunnel dispatch
jitter does not pollute them; a handful of eagerly dispatched steps inside
the trace window is enough.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def decode_profile(args):
    """Trace a compiled decode scan (full 16k window) — per-op durations are
    the per-TOKEN cost times the scan length."""
    from perceiver_io_tpu.generation import GenerationConfig, make_generate_fn
    from perceiver_io_tpu.models.text import CausalLanguageModel

    config = flagship_config(args.seq_len, args.latents)
    model = CausalLanguageModel(config, dtype=jnp.bfloat16)
    b = args.batch_size
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, config.vocab_size, size=(b, args.seq_len)))
    params = model.init(jax.random.PRNGKey(0), prompt[:, : args.latents + 1], prefix_len=1)
    gen = make_generate_fn(
        model, args.latents,
        GenerationConfig(max_new_tokens=args.steps, do_sample=True, top_k=10),
        cache_dtype=jnp.int8 if args.cache_dtype == "int8" else jnp.bfloat16,
        weight_dtype=jnp.int8 if args.weight_dtype == "int8" else None,
    )
    float(gen(params, prompt)[0, -1])  # compile + warm
    jax.profiler.start_trace(args.out)
    float(gen(params, prompt)[0, -1])
    jax.profiler.stop_trace()


def image_profile(args):
    """Trace the image-classifier train step (the BENCH_extra image workload,
    bench.image_bench config) — the round-4 roofline treatment. Matches the
    bench exactly: microbatch is always 1 on the image workload (the
    --microbatch flag applies to the CLM train mode only)."""
    from perceiver_io_tpu.models.vision.image_classifier import (
        ImageClassifier,
        ImageClassifierConfig,
        ImageEncoderConfig,
    )
    from perceiver_io_tpu.core.config import ClassificationDecoderConfig
    from perceiver_io_tpu.training import TrainState, classification_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step

    config = ImageClassifierConfig(
        encoder=ImageEncoderConfig(
            image_shape=(224, 224, 3),
            num_frequency_bands=64,
            num_cross_attention_heads=1,
            num_self_attention_heads=8,
            num_self_attention_layers_per_block=6,
            num_self_attention_blocks=8,
            first_self_attention_block_shared=True,
        ),
        decoder=ClassificationDecoderConfig(
            num_classes=1000, num_output_query_channels=1024, num_cross_attention_heads=1
        ),
        num_latents=512,
        num_latent_channels=1024,
    )
    model = ImageClassifier(config, dtype=jnp.bfloat16)
    b = args.batch_size
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.normal(size=(b, 224, 224, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 1000, size=(b,))),
    }
    params = model.init(jax.random.PRNGKey(0), batch["image"])
    tx = make_optimizer(1e-3, gradient_clip=1.0)
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
    step = make_train_step(classification_loss_fn(model.apply))

    for _ in range(2):
        state, metrics = step(state, batch)
        float(metrics["loss"])
    jax.profiler.start_trace(args.out)
    for _ in range(args.steps):
        state, metrics = step(state, batch)
        float(metrics["loss"])
    jax.profiler.stop_trace()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=16384)
    p.add_argument("--latents", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--top", type=int, default=40)
    p.add_argument("--out", default="/tmp/prof_step")
    p.add_argument("--mode", choices=["train", "decode", "img"], default="train")
    # match the bench.py round-5 defaults (b32 in 8 chunks of 4) so the
    # profile reflects the step the driver actually measures
    p.add_argument("--microbatch", type=int, default=8)
    p.add_argument("--dropout-sampling", choices=["host", "graph"], default="host")
    p.add_argument("--dropout-mode", choices=["gather", "gather_embed", "mask"], default="gather")
    p.add_argument("--cache-dtype", choices=["model", "int8"], default="model")
    p.add_argument("--weight-dtype", choices=["model", "int8"], default="model")
    p.add_argument("--moment-dtype", choices=["float32", "bfloat16"], default="bfloat16")
    args = p.parse_args()

    if args.mode == "decode":
        decode_profile(args)
        return _summarize(args)
    if args.mode == "img":
        image_profile(args)
        return _summarize(args)

    from perceiver_io_tpu.models.text import CausalLanguageModel
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step

    config = flagship_config(args.seq_len, args.latents)
    config.prefix_dropout_mode = args.dropout_mode
    model = CausalLanguageModel(config, dtype=jnp.bfloat16)
    b, n = args.batch_size, args.seq_len
    rng = np.random.default_rng(0)
    t = rng.integers(0, config.vocab_size, size=(b, n + 1))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    if args.dropout_sampling == "host":
        from perceiver_io_tpu.training.prefix_dropout import sample_prefix_keep_idx

        batch["prefix_keep_idx"] = jnp.asarray(
            sample_prefix_keep_idx(rng, b, n - args.latents, config.cross_attention_dropout)
        )
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"][:, : args.latents + 1], prefix_len=1)
    tx = make_optimizer(
        1e-3,
        gradient_clip=1.0,
        moment_dtype=None if args.moment_dtype == "float32" else args.moment_dtype,
    )
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
    step = make_train_step(
        clm_loss_fn(model.apply, max_latents=args.latents), microbatch=args.microbatch
    )

    # warm up / compile outside the trace
    for _ in range(2):
        state, metrics = step(state, batch)
        float(metrics["loss"])

    jax.profiler.start_trace(args.out)
    for _ in range(args.steps):
        state, metrics = step(state, batch)
        float(metrics["loss"])
    jax.profiler.stop_trace()
    _summarize(args)


def _summarize(args):
    from perceiver_io_tpu.obs.xplane import rollup_planes, summarize

    # raw per-op totals first, then the named-scope rollup (obs/xplane.py)
    # from the SAME parsed planes — the scope view is what answers "which
    # module did the time go to", and the parse dominates on big captures
    planes = summarize(args.out, args.top, "")
    print("\n--- per-scope rollup (jax.named_scope / module path) ---")
    for roll in rollup_planes(planes):
        print(f"\n=== plane: {roll.plane}")
        for scope, dur, count in roll.top(args.top):
            print(f"  {dur/1e9:9.3f} ms {count:6d}x  {scope[:100]}")


if __name__ == "__main__":
    main()
