"""Offline quality-convergence flagship runs (VERDICT r3 item 3): the
closest offline stand-in for the reference's published training numbers
(reference: docs/training-examples.md:143-161 — network-blocked here).

Two runs, both fully offline, seeded, and driven through the real task CLIs:

1. **CLM small (30.7M)** — the reference's WikiText byte-level geometry
   (vocab 262, seq 4096, latents 512, 512ch x 8 SA layers; published
   val_loss 0.876) trained on a deterministic order-1 Markov corpus
   (tools/scaling_runs.make_corpus). The corpus's entropy rate is
   COMPUTABLE (stationary distribution of the word chain / expected word
   length), so convergence quality is judged against an analytic floor —
   stronger evidence than an arbitrary pinned loss: the model must close
   most of the gap from the unigram baseline to the true entropy rate.
2. **MNIST-class image classifier** — the reference's MNIST config
   (published val_acc 0.9816) on the synthetic-digits datamodule.

Curves land in docs/results/ (clm_flagship.csv, img_clf_flagship.csv) with a
JSON summary (flagship_convergence.json); tests/test_results_artifacts.py
pins the committed numbers.

    python tools/flagship_convergence.py [--out docs/results] [--runs clm img]
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from scaling_runs import corpus_valid, make_corpus  # noqa: E402


def corpus_entropy_rate(vocab: int = 2048, fanout: int = 8, seed: int = 7) -> dict:
    """Exact per-byte entropy rate of the make_corpus Markov chain.

    The chain is a deterministic function of its seed: state -> 8 successor
    draws (with possible duplicates, which LOWER the per-state entropy).
    H(word) = sum_s pi(s) * H(successors(s)); bytes/word = E_pi[len(word)+1]
    (the joining space). pi is the stationary distribution (power iteration).
    """
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, vocab + 1)
    p = 1.0 / ranks
    p /= p.sum()
    succ = rng.choice(vocab, size=(vocab, fanout), p=p)

    # transition matrix rows from successor multiplicity
    T = np.zeros((vocab, vocab))
    for s in range(vocab):
        for t in succ[s]:
            T[s, t] += 1.0 / fanout
    pi = np.full(vocab, 1.0 / vocab)
    for _ in range(200):
        pi = pi @ T
    pi /= pi.sum()

    h_words = 0.0
    for s in range(vocab):
        probs = T[s][T[s] > 0]
        h_words += pi[s] * float(-(probs * np.log(probs)).sum())
    word_len = np.array([len(f"w{i}") for i in range(vocab)], float)
    bytes_per_word = float((pi * (word_len + 1.0)).sum())
    # unigram upper baseline: entropy of the stationary word distribution
    h_unigram = float(-(pi[pi > 0] * np.log(pi[pi > 0])).sum())
    return {
        "nats_per_byte_floor": h_words / bytes_per_word,
        "nats_per_byte_unigram": h_unigram / bytes_per_word,
        "bytes_per_word": bytes_per_word,
    }


def run_clm(out_dir: str, steps: int, seed: int) -> dict:
    corpus = os.path.join(tempfile.gettempdir(), "flagship_corpus_markov1.txt")
    # 8M words of the seed-7 chain serialize to ~32.5 MB (guard rationale:
    # scaling_runs.corpus_valid)
    if not corpus_valid(corpus):
        print("generating 8M-word corpus ...", flush=True)
        make_corpus(corpus, n_words=8_000_000)
    root = tempfile.mkdtemp(prefix="flagship_clm_")
    argv = [
        "fit",
        "--data.dataset=textfile",
        f"--data.train_file={corpus}",
        "--data.max_seq_len=4096",
        "--data.batch_size=8",
        f"--data.cache_dir={root}/cache",
        # the reference CLM-small geometry (30.7M params)
        "--model.max_latents=512",
        "--model.num_channels=512",
        "--model.num_self_attention_layers=8",
        "--model.num_heads=8",
        "--model.cross_attention_dropout=0.5",
        f"--trainer.max_steps={steps}",
        "--trainer.val_interval=250",
        "--trainer.log_interval=100",
        "--trainer.devices=1",
        "--trainer.precision=bf16",
        "--trainer.checkpoint=false",
        f"--trainer.seed={seed}",
        f"--trainer.default_root_dir={root}/logs",
        "--trainer.name=run",
        "--optimizer.lr=6e-4",
        "--optimizer.warmup_steps=200",
    ]
    code = (
        f"import sys; sys.path.insert(0, {REPO!r})\n"
        "from perceiver_io_tpu.scripts.text.clm import main\n"
        f"main({argv!r})\n"
    )
    t = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True)
    if t.returncode != 0:
        raise RuntimeError(f"clm flagship run failed:\n{t.stderr[-4000:]}")
    src = os.path.join(root, "logs", "run", "metrics.csv")
    dst = os.path.join(out_dir, "clm_flagship.csv")
    shutil.copy(src, dst)
    final = _final_metric(dst, "val_loss")
    ent = corpus_entropy_rate()
    closed = (ent["nats_per_byte_unigram"] - final) / (
        ent["nats_per_byte_unigram"] - ent["nats_per_byte_floor"]
    )
    shutil.rmtree(root, ignore_errors=True)
    return {
        "final_val_loss": final,
        "entropy_floor": ent["nats_per_byte_floor"],
        "unigram_baseline": ent["nats_per_byte_unigram"],
        "gap_closed": closed,
        "steps": steps,
        "seed": seed,
        "config": "30.7M CLM small (vocab 262, seq 4096, latents 512, 512ch x 8L)",
    }


def run_img(out_dir: str, steps: int, seed: int) -> dict:
    root = tempfile.mkdtemp(prefix="flagship_img_")
    argv = [
        "fit",
        "--data.synthetic=true",
        f"--data.dataset_dir={root}/cache",
        "--data.batch_size=64",
        f"--trainer.max_steps={steps}",
        "--trainer.val_interval=250",
        "--trainer.log_interval=100",
        "--trainer.devices=1",
        "--trainer.checkpoint=false",
        f"--trainer.seed={seed}",
        f"--trainer.default_root_dir={root}/logs",
        "--trainer.name=run",
        "--optimizer.lr=1e-3",
        "--optimizer.warmup_steps=100",
        # at init_scale 0.02 the single-head encoder CA freezes at the
        # label-prior for thousands of steps (reference torch backend too —
        # see scripts/vision/image_classifier.py smoke preset); 0.1 unlocks
        "--model.encoder.init_scale=0.1",
        "--model.decoder.init_scale=0.1",
    ]
    code = (
        f"import sys; sys.path.insert(0, {REPO!r})\n"
        "from perceiver_io_tpu.scripts.vision.image_classifier import main\n"
        f"main({argv!r})\n"
    )
    t = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True)
    if t.returncode != 0:
        raise RuntimeError(f"img flagship run failed:\n{t.stderr[-4000:]}")
    src = os.path.join(root, "logs", "run", "metrics.csv")
    dst = os.path.join(out_dir, "img_clf_flagship.csv")
    shutil.copy(src, dst)
    final = _final_metric(dst, "val_acc")
    shutil.rmtree(root, ignore_errors=True)
    return {"final_val_acc": final, "steps": steps, "seed": seed,
            "config": "MNIST-class Perceiver IO classifier, synthetic digits"}


def _final_metric(path: str, name: str) -> float:
    vals = [float(r[name]) for r in csv.DictReader(open(path)) if r.get(name)]
    if not vals:
        raise RuntimeError(f"no {name} rows in {path}")
    return vals[-1]


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=os.path.join(REPO, "docs", "results"))
    p.add_argument("--runs", nargs="*", default=["clm", "img"])
    p.add_argument("--clm-steps", type=int, default=3000)
    p.add_argument("--img-steps", type=int, default=1500)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    os.makedirs(args.out, exist_ok=True)
    summary_path = os.path.join(args.out, "flagship_convergence.json")
    summary = {}
    if os.path.exists(summary_path):
        try:
            summary = json.load(open(summary_path))
        except (json.JSONDecodeError, OSError):
            print(f"warning: unreadable {summary_path}, starting fresh", flush=True)

    def save():
        # atomic replace: a kill mid-dump must not corrupt the committed,
        # test-pinned artifact
        tmp = summary_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(summary, f, indent=1)
        os.replace(tmp, summary_path)

    if "clm" in args.runs:
        summary["clm"] = run_clm(args.out, args.clm_steps, args.seed)
        print(json.dumps(summary["clm"], indent=1), flush=True)
        save()
    if "img" in args.runs:
        summary["img"] = run_img(args.out, args.img_steps, args.seed)
        print(json.dumps(summary["img"], indent=1), flush=True)
        save()
    print(f"wrote {summary_path}")


if __name__ == "__main__":
    main()
