"""Render a run directory's telemetry (``events.jsonl`` /
``events-p*.jsonl`` shards + ``run_manifest.json``) into a plain-text run
summary.

    python tools/obs_report.py <run_dir> [--max-compile-rows N]

Sections: the manifest (what the run ran on), event counts, compile events
(the recompile audit — a second compile of the same function within one
process is a shape leak; resumed runs legitimately append another first
compile), the latest throughput/MFU/goodput log row, the per-step
host/device breakdown from ``span`` rows (input_wait → dispatch → compute,
the device side joined from an xplane capture when one sits in the run
dir), the goodput breakdown from ``fit_end``, and per-request SLO stats
(TTFT + histogram-derived TPOT percentiles from ``request`` rows).
Stdlib-only: runs anywhere the run directory can be copied to (the shard
merge and percentile math are inlined; the optional device join upgrades
itself through ``perceiver_io_tpu.obs`` when the package is importable).
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
from typing import Dict, List, Optional


def _read_shard(path: str) -> List[Dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # a torn tail line from a killed run is expected
    return events


def load_events(run_dir: str) -> List[Dict]:
    """All shards of the run, merged into one stream. Uses the canonical
    skew-tolerant merge (``obs.events.merged_events``) when the package is
    importable; the stdlib fallback concatenates shards sorted by ``ts``."""
    try:
        from perceiver_io_tpu.obs.events import merged_events

        return merged_events(run_dir)
    except ImportError:
        pass
    paths = []
    single = os.path.join(run_dir, "events.jsonl")
    if os.path.exists(single):
        paths.append(single)
    paths.extend(sorted(glob.glob(os.path.join(run_dir, "events-p*.jsonl"))))
    events = []
    for p in paths:
        events.extend(_read_shard(p))
    if len(paths) > 1:
        events.sort(key=lambda e: float(e.get("ts", 0.0)))
    return events


def _pct(values: List[float], p: float) -> float:
    """Nearest-rank percentile (stdlib; exact order statistic)."""
    s = sorted(values)
    return s[max(int(math.ceil(p / 100.0 * len(s))) - 1, 0)]


def load_manifest(run_dir: str) -> Optional[Dict]:
    path = os.path.join(run_dir, "run_manifest.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _table(rows: List[List[str]], header: List[str]) -> List[str]:
    widths = [max(len(r[i]) for r in [header] + rows) for i in range(len(header))]
    out = ["  ".join(h.ljust(w) for h, w in zip(header, widths))]
    out.append("  ".join("-" * w for w in widths))
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return out


def render(run_dir: str, max_compile_rows: int = 20) -> str:
    """The run summary as one string (the CLI prints it; tests assert on it)."""
    lines: List[str] = [f"run: {os.path.abspath(run_dir)}"]
    manifest = load_manifest(run_dir)
    if manifest is not None:
        lines.append("")
        lines.append("== manifest ==")
        for key in (
            "created_at",
            "jax_version",
            "backend",
            "device_kind",
            "device_count",
            "process_count",
            "mesh",
            "config_hash",
        ):
            if key in manifest:
                lines.append(f"  {key}: {_fmt(manifest[key])}")

    events = load_events(run_dir)
    if not events:
        lines.append("\nno events.jsonl (telemetry off, or the run never logged)")
        return "\n".join(lines)

    counts: Dict[str, int] = {}
    for e in events:
        counts[e.get("event", "?")] = counts.get(e.get("event", "?"), 0) + 1
    lines.append("")
    lines.append("== events ==")
    lines.append("  " + ", ".join(f"{k}: {v}" for k, v in sorted(counts.items())))

    compiles = [e for e in events if e.get("event") == "compile"]
    if compiles:
        lines.append("")
        lines.append("== compiles ==")
        per_fn: Dict[str, List[float]] = {}
        for e in compiles:
            per_fn.setdefault(e.get("fn", "?"), []).append(float(e.get("wall_s", 0.0)))
        rows = [
            [fn, str(len(walls)), f"{sum(walls):.3f}s"]
            for fn, walls in sorted(per_fn.items())
        ]
        lines.extend("  " + r for r in _table(rows[:max_compile_rows], ["fn", "count", "wall"]))
        # shape-leak signal: an event's n_compiles counter > 1 means the SAME
        # process compiled the same fn twice — a raw per-file count would
        # false-positive on resumed runs, whose new process appends its own
        # legitimate first compile to the shared events.jsonl
        leaks = sorted({e.get("fn", "?") for e in compiles if e.get("n_compiles", 1) > 1})
        if leaks:
            lines.append(f"  WARNING: recompiles after the first on: {', '.join(leaks)}")

    logs = [e for e in events if e.get("event") == "log"]
    if logs:
        last = logs[-1]
        lines.append("")
        lines.append(f"== latest log row (step {last.get('step')}) ==")
        for key in sorted(last):
            if key in ("ts", "event", "step"):
                continue
            lines.append(f"  {key}: {_fmt(last[key])}")

    spans = [e for e in events if e.get("event") == "span"]
    steps = [s for s in spans if s.get("name") == "step"]
    if steps:
        lines.append("")
        lines.append(f"== step breakdown ({len(steps)} step spans) ==")
        durs = [float(s["dur_ms"]) for s in steps]
        low = "  (low_n: exact order statistics)" if len(durs) < 5 else ""
        lines.append(
            f"  step_ms: p50 {_pct(durs, 50):.4g}  p99 {_pct(durs, 99):.4g}  "
            f"mean {sum(durs)/len(durs):.4g}{low}"
        )
        for attr in ("input_wait_ms", "dispatch_ms"):
            vals = [
                float(s["attrs"][attr])
                for s in steps
                if isinstance(s.get("attrs"), dict) and attr in s["attrs"]
            ]
            if vals:
                lines.append(f"  {attr}: mean {sum(vals)/len(vals):.4g}")
        for phase in ("checkpoint", "eval"):
            rows = [s for s in spans if s.get("name") == phase]
            if rows:
                total = sum(float(s["dur_ms"]) for s in rows)
                lines.append(f"  {phase}: {len(rows)}x, total {total:.4g} ms")
        # device side of the join: an xplane capture in the run dir rolls up
        # by named scope (needs the package; silently host-only without it)
        pbs = glob.glob(os.path.join(run_dir, "**", "*.xplane.pb"), recursive=True)
        if pbs:
            try:
                from perceiver_io_tpu.obs.trace import host_device_breakdown
                from perceiver_io_tpu.obs.xplane import rollup

                bd = host_device_breakdown(spans, rollup(sorted(pbs)[-1]))
                dev = bd.get("device")
                if dev:
                    lines.append(
                        f"  device: {dev['total_ms']:.4g} ms total, "
                        f"{dev['per_step_ms']:.4g} ms/step"
                    )
                    for sc in dev["top_scopes"][:5]:
                        lines.append(f"    {sc['ms']:9.3f} ms  {sc['scope'][:80]}")
            except ImportError:
                lines.append("  (xplane capture present; install the package for the device join)")

    # Probeline per-scope trends (probe events: one snapshot per log
    # boundary, scopes keyed "NNN:name" — sorted == topological order) and
    # blast-radius reports. Non-finite stats arrive as JSON null (the
    # strict-JSON NaN policy), so None in a stat column means NONFINITE.
    probe_rows = [e for e in events if e.get("event") == "probe"]
    if probe_rows:
        series: Dict[str, List] = {}
        for e in probe_rows:
            for k, st in (e.get("scopes") or {}).items():
                if isinstance(st, dict):
                    series.setdefault(k, []).append(st)
        lines.append("")
        lines.append(
            f"== probes ({len(probe_rows)} snapshots, {len(series)} scopes) =="
        )

        def _bare(key):
            # must track obs.probes.scope_of — inlined because this renderer
            # stays stdlib-only (same pattern as the GROWTH fallback below)
            head, sep, tail = key.partition(":")
            return tail if sep and head.isdigit() else key

        def _spaced(vals, n=5):
            if len(vals) <= n:
                return vals
            idx = [round(i * (len(vals) - 1) / (n - 1)) for i in range(n)]
            return [vals[i] for i in idx]

        rows = []
        for k in sorted(series)[:48]:
            pts = series[k]
            main_key = "rms" if "rms" in pts[-1] else ("l2" if "l2" in pts[-1] else "ratio")
            vals = [s.get(main_key) for s in pts]
            bad = any(v is None for v in vals) or any(
                (s.get("nonfinite_frac") or 0) > 0 for s in pts
            )
            trend = " -> ".join("nan" if v is None else f"{v:.3g}" for v in _spaced(vals))
            rows.append([_bare(k), f"{main_key}: {trend}", "NONFINITE" if bad else ""])
        lines.extend("  " + r for r in _table(rows, ["scope", "trend (first -> last)", ""]))

    for b in (e for e in events if e.get("event") == "probe.blast"):
        lines.append(
            f"  BLAST [{b.get('trigger')}] step {b.get('step')}: first non-finite scope "
            f"{b.get('scope')!r} ({b.get('n_affected')}/{b.get('n_scopes')} scopes affected)"
        )

    ends = [e for e in events if e.get("event") == "fit_end"]
    if ends:
        end = ends[-1]
        lines.append("")
        lines.append("== goodput (fit_end) ==")
        for key in sorted(end):
            if key in ("ts", "event"):
                continue
            lines.append(f"  {key}: {_fmt(end[key])}")

    # per-request SLO stats; "generate" is the pre-request-event legacy kind
    reqs = [e for e in events if e.get("event") in ("request", "generate")]
    if reqs:
        lines.append("")
        outcomes: Dict[str, int] = {}
        for r in reqs:
            o = str(r.get("outcome", "ok"))
            outcomes[o] = outcomes.get(o, 0) + 1
        lines.append(
            f"== requests ({len(reqs)}: "
            + ", ".join(f"{k} {v}" for k, v in sorted(outcomes.items()))
            + ") =="
        )
        ok = [r for r in reqs if r.get("outcome", "ok") == "ok"]
        # steady-state stats exclude calls that paid a compile; when EVERY
        # call compiled there is no steady state — say so instead of
        # presenting compile-inflated latencies as clean numbers
        warm = [g for g in ok if not g.get("compiled")]
        if warm:
            note = "  (warm requests only)" if len(warm) < len(ok) else ""
        else:
            warm = ok
            note = "  (ALL requests paid a compile — latencies include it)"
        for key in ("ttft_s", "prefill_s", "per_token_s", "tokens_per_sec"):
            vals = [float(g[key]) for g in warm if g.get(key) is not None]
            if vals and not (key == "prefill_s" and any("ttft_s" in g for g in warm)):
                lines.append(
                    f"  {key}: mean {sum(vals)/len(vals):.4g}  "
                    f"min {min(vals):.4g}  max {max(vals):.4g}" + note
                )
        # TPOT percentiles over every decoded token: merged per-request
        # log-bucket histograms (exact addition — global bucket bounds).
        # Canonical math lives in obs.metrics (the bucket base is
        # load-bearing for every committed tpot_hist); the inline copy is
        # only the no-package fallback, same pattern as load_events.
        def _merge_hists(rows_):
            out: Dict[int, int] = {}
            for g in rows_:
                for k, v in (g.get("tpot_hist") or {}).items():
                    out[int(k)] = out.get(int(k), 0) + int(v)
            return out

        try:
            from perceiver_io_tpu.obs.metrics import percentile_from_counts as _hpct
        except ImportError:
            growth = 2.0 ** 0.25  # must track obs.metrics.GROWTH

            def _hpct(counts, p):
                n = sum(counts.values())
                target, seen = max(int(math.ceil(p / 100.0 * n)), 1), 0
                for idx in sorted(counts):
                    seen += counts[idx]
                    if seen >= target:
                        return growth ** (idx + 0.5)
        merged = _merge_hists(warm)
        n_tok = sum(merged.values())
        if n_tok:
            low = "  (low_n)" if n_tok < 5 else ""
            lines.append(
                f"  tpot_s ({n_tok} tokens): p50 {_hpct(merged, 50):.4g}  "
                f"p90 {_hpct(merged, 90):.4g}  p99 {_hpct(merged, 99):.4g}{low}" + note
            )
        # queue-wait (loadgen-issued requests carry admission telemetry)
        qws = [float(g["queue_wait_s"]) for g in warm if g.get("queue_wait_s") is not None]
        if qws:
            lines.append(
                f"  queue_wait_s: p50 {_pct(qws, 50):.4g}  p99 {_pct(qws, 99):.4g}  "
                f"mean {sum(qws)/len(qws):.4g}" + note
            )
        # batched-engine occupancy (Pageline, docs/serving.md): requests
        # served by the continuous-batching engine carry the batch size
        # their decode steps ran at
        bsz = [float(g["batch_size_at_decode"]) for g in reqs
               if g.get("batch_size_at_decode") is not None]
        if bsz:
            lines.append(
                f"  batch_size_at_decode: mean {sum(bsz)/len(bsz):.4g}  "
                f"min {min(bsz):.4g}  max {max(bsz):.4g}  ({len(bsz)} engine requests)"
            )
        # prefix sharing (Shareline, docs/serving.md#prefix-sharing): hit
        # rate over the run's requests plus what the hits came to — pages
        # referenced instead of recomputed, prompt tokens prefill skipped
        hit_rows = [e for e in events if e.get("event") == "serve.prefix_hit"]
        if hit_rows:
            pages_shared = sum(int(h.get("pages_matched", 0)) for h in hit_rows)
            skipped = sum(int(h.get("tokens_skipped", 0)) for h in hit_rows)
            lines.append(
                f"  prefix_hit_rate: {len(hit_rows) / len(reqs):.3f}  "
                f"({len(hit_rows)}/{len(reqs)} requests, {pages_shared} pages "
                f"shared, {skipped} prompt tokens skipped)"
            )
        # per-tenant rollup (Simline, docs/serving.md#multi-tenant-telemetry):
        # tenant-stamped request rows become one line per tenant — outcome
        # rates, TTFT/TPOT percentiles, and the pages-held peak read from
        # the labeled engine gauge's high-water mark in the metrics rows
        tenants = sorted({str(r["tenant"]) for r in reqs if r.get("tenant") is not None})
        if tenants:
            peaks: Dict[str, float] = {}
            for e in events:
                if e.get("event") == "metrics":
                    for k, v in (e.get("gauge_peaks") or {}).items():
                        if k.startswith("engine_kv_pages_used{") and isinstance(v, (int, float)):
                            peaks[k] = max(peaks.get(k, 0.0), float(v))
            rows = []
            for t in tenants:
                trows = [r for r in reqs if str(r.get("tenant")) == t]
                n_t = len(trows)
                by_outcome: Dict[str, int] = {}
                for r in trows:
                    o = str(r.get("outcome", "ok"))
                    by_outcome[o] = by_outcome.get(o, 0) + 1
                tok = [r for r in trows if r.get("outcome", "ok") == "ok"]
                ttfts = [float(r["ttft_s"]) for r in tok if r.get("ttft_s") is not None]
                th = _merge_hists(tok)
                peak = peaks.get(f'engine_kv_pages_used{{tenant="{t}"}}')
                rows.append([
                    t,
                    str(n_t),
                    f"{by_outcome.get('ok', 0) / n_t:.3f}",
                    f"{by_outcome.get('shed', 0) / n_t:.3f}",
                    f"{by_outcome.get('timeout', 0) / n_t:.3f}",
                    f"{_pct(ttfts, 50):.4g}" if ttfts else "-",
                    f"{_pct(ttfts, 99):.4g}" if ttfts else "-",
                    f"{_hpct(th, 50):.4g}" if th else "-",
                    f"{_hpct(th, 99):.4g}" if th else "-",
                    f"{peak:.4g}" if peak is not None else "-",
                ])
            lines.append("")
            lines.append(f"== tenants ({len(tenants)}) ==")
            lines.extend("  " + r for r in _table(rows, [
                "tenant", "reqs", "ok", "shed", "timeout",
                "ttft_p50", "ttft_p99", "tpot_p50", "tpot_p99", "pages_peak",
            ]))

    # engine gauges (Pageline): the LAST registry snapshot's engine_* gauges
    # plus their run maxima — batch occupancy and page-pool utilization
    metric_rows = [e for e in events if e.get("event") == "metrics"]
    engine_series: Dict[str, List[float]] = {}
    for e in metric_rows:
        for k, v in (e.get("gauges") or {}).items():
            if k.startswith("engine_") and isinstance(v, (int, float)):
                engine_series.setdefault(k, []).append(float(v))
    if engine_series:
        lines.append("")
        lines.append("== engine (paged KV / continuous batching) ==")
        for k in sorted(engine_series):
            vals = engine_series[k]
            lines.append(f"  {k}: last {vals[-1]:.4g}  max {max(vals):.4g}")

    # per-request tail attribution: queue-wait -> prefill -> decode ->
    # compile-if-cold, the compile leg joined from span-stamped compile
    # events. Canonical join lives in obs.slo.request_breakdowns; the
    # inline copy is the no-package fallback (same pattern as load_events).
    bd = None
    if reqs:
        try:
            from perceiver_io_tpu.obs.slo import request_breakdowns

            bd = request_breakdowns(events)
        except ImportError:
            compile_s: Dict[str, float] = {}
            for e in events:
                if e.get("event") == "compile" and e.get("span_id") is not None:
                    compile_s[e["span_id"]] = compile_s.get(e["span_id"], 0.0) + float(
                        e.get("wall_s", 0.0)
                    )
            brows = []
            for r in reqs:
                brows.append(
                    {
                        "request_id": r.get("request_id"),
                        "outcome": r.get("outcome", "ok"),
                        "compiled": bool(r.get("compiled")),
                        "queue_wait_ms": None
                        if r.get("queue_wait_s") is None
                        else 1e3 * float(r["queue_wait_s"]),
                        "prefill_ms": None
                        if r.get("ttft_s") is None
                        else 1e3 * float(r["ttft_s"]),
                        "decode_ms": None
                        if r.get("decode_s") is None
                        else 1e3 * float(r["decode_s"]),
                        "compile_ms": 1e3 * compile_s.get(r.get("span_id"), 0.0),
                        "service_ms": 1e3
                        * sum(float(r.get(k) or 0.0) for k in ("ttft_s", "decode_s")),
                        "total_ms": 1e3
                        * sum(
                            float(r.get(k) or 0.0)
                            for k in ("queue_wait_s", "ttft_s", "decode_s")
                        ),
                    }
                )
            ok_rows = [b for b in brows if b["outcome"] == "ok"]
            warm_rows = [b for b in ok_rows if not b["compiled"]]
            pool = warm_rows or ok_rows
            med = {}
            for key in ("queue_wait_ms", "prefill_ms", "decode_ms", "service_ms", "total_ms"):
                vals = sorted(float(b[key]) for b in pool if b.get(key) is not None)
                if vals:
                    n = len(vals)
                    med[key] = vals[n // 2] if n % 2 else 0.5 * (vals[n // 2 - 1] + vals[n // 2])
            bd = {"n": len(brows), "requests": brows, "medians": med,
                  "warm_only": bool(warm_rows)}
    if bd and bd["n"]:
        lines.append("")
        lines.append(
            f"== request breakdown (queue -> prefill -> decode, {bd['n']} requests"
            + ("" if bd.get("warm_only", True) else "; ALL cold")
            + ") =="
        )
        med = bd.get("medians", {})
        if med:
            lines.append(
                "  medians: "
                + "  ".join(
                    f"{k.replace('_ms', '')} {med[k]:.4g} ms"
                    for k in (
                        "queue_wait_ms", "prefill_ms", "decode_ms",
                        "compile_ms_cold", "service_ms", "total_ms",
                    )
                    if k in med
                )
            )
        slowest = sorted(
            (b for b in bd["requests"] if b.get("total_ms") is not None),
            key=lambda b: -float(b["total_ms"]),
        )[:5]
        if slowest:
            rows = [
                [
                    str(b.get("request_id") or "?")[:10],
                    *(
                        "-" if b.get(k) is None else f"{float(b[k]):.4g}"
                        for k in (
                            "queue_wait_ms", "prefill_ms", "decode_ms",
                            "compile_ms", "total_ms",
                        )
                    ),
                    b.get("outcome", "ok") + (" (cold)" if b.get("compiled") else ""),
                ]
                for b in slowest
            ]
            lines.extend(
                "  " + r
                for r in _table(
                    rows,
                    ["request", "queue_ms", "prefill_ms", "decode_ms",
                     "compile_ms", "total_ms", "outcome"],
                )
            )
    return "\n".join(lines)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("run_dir", help="directory holding events.jsonl / run_manifest.json")
    p.add_argument(
        "--max-compile-rows", type=int, default=20, help="cap on compile-table rows"
    )
    args = p.parse_args()
    print(render(args.run_dir, max_compile_rows=args.max_compile_rows))


if __name__ == "__main__":
    main()
