"""Render a run directory's telemetry (``events.jsonl`` +
``run_manifest.json``) into a plain-text run summary.

    python tools/obs_report.py <run_dir> [--max-compile-rows N]

Sections: the manifest (what the run ran on), event counts, compile events
(the recompile audit — a second compile of the same function within one
process is a shape leak; resumed runs legitimately append another first
compile), the
latest throughput/MFU/goodput log row, the goodput breakdown from
``fit_end``, and generation latency stats. Stdlib-only: runs anywhere the
run directory can be copied to.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional


def load_events(run_dir: str) -> List[Dict]:
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # a torn tail line from a killed run is expected
    return events


def load_manifest(run_dir: str) -> Optional[Dict]:
    path = os.path.join(run_dir, "run_manifest.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _table(rows: List[List[str]], header: List[str]) -> List[str]:
    widths = [max(len(r[i]) for r in [header] + rows) for i in range(len(header))]
    out = ["  ".join(h.ljust(w) for h, w in zip(header, widths))]
    out.append("  ".join("-" * w for w in widths))
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return out


def render(run_dir: str, max_compile_rows: int = 20) -> str:
    """The run summary as one string (the CLI prints it; tests assert on it)."""
    lines: List[str] = [f"run: {os.path.abspath(run_dir)}"]
    manifest = load_manifest(run_dir)
    if manifest is not None:
        lines.append("")
        lines.append("== manifest ==")
        for key in (
            "created_at",
            "jax_version",
            "backend",
            "device_kind",
            "device_count",
            "process_count",
            "mesh",
            "config_hash",
        ):
            if key in manifest:
                lines.append(f"  {key}: {_fmt(manifest[key])}")

    events = load_events(run_dir)
    if not events:
        lines.append("\nno events.jsonl (telemetry off, or the run never logged)")
        return "\n".join(lines)

    counts: Dict[str, int] = {}
    for e in events:
        counts[e.get("event", "?")] = counts.get(e.get("event", "?"), 0) + 1
    lines.append("")
    lines.append("== events ==")
    lines.append("  " + ", ".join(f"{k}: {v}" for k, v in sorted(counts.items())))

    compiles = [e for e in events if e.get("event") == "compile"]
    if compiles:
        lines.append("")
        lines.append("== compiles ==")
        per_fn: Dict[str, List[float]] = {}
        for e in compiles:
            per_fn.setdefault(e.get("fn", "?"), []).append(float(e.get("wall_s", 0.0)))
        rows = [
            [fn, str(len(walls)), f"{sum(walls):.3f}s"]
            for fn, walls in sorted(per_fn.items())
        ]
        lines.extend("  " + r for r in _table(rows[:max_compile_rows], ["fn", "count", "wall"]))
        # shape-leak signal: an event's n_compiles counter > 1 means the SAME
        # process compiled the same fn twice — a raw per-file count would
        # false-positive on resumed runs, whose new process appends its own
        # legitimate first compile to the shared events.jsonl
        leaks = sorted({e.get("fn", "?") for e in compiles if e.get("n_compiles", 1) > 1})
        if leaks:
            lines.append(f"  WARNING: recompiles after the first on: {', '.join(leaks)}")

    logs = [e for e in events if e.get("event") == "log"]
    if logs:
        last = logs[-1]
        lines.append("")
        lines.append(f"== latest log row (step {last.get('step')}) ==")
        for key in sorted(last):
            if key in ("ts", "event", "step"):
                continue
            lines.append(f"  {key}: {_fmt(last[key])}")

    ends = [e for e in events if e.get("event") == "fit_end"]
    if ends:
        end = ends[-1]
        lines.append("")
        lines.append("== goodput (fit_end) ==")
        for key in sorted(end):
            if key in ("ts", "event"):
                continue
            lines.append(f"  {key}: {_fmt(end[key])}")

    gens = [e for e in events if e.get("event") == "generate"]
    if gens:
        lines.append("")
        lines.append(f"== generation ({len(gens)} calls) ==")
        # steady-state stats exclude calls that paid a compile; when EVERY
        # call compiled there is no steady state — say so instead of
        # presenting compile-inflated latencies as clean numbers
        warm = [g for g in gens if not g.get("compiled")]
        if warm:
            note = "  (warm calls only)" if len(warm) < len(gens) else ""
        else:
            warm = gens
            note = "  (ALL calls paid a compile — latencies include it)"
        for key in ("prefill_s", "per_token_s", "tokens_per_sec"):
            vals = [float(g[key]) for g in warm if key in g]
            if vals:
                lines.append(
                    f"  {key}: mean {sum(vals)/len(vals):.4g}  "
                    f"min {min(vals):.4g}  max {max(vals):.4g}" + note
                )
    return "\n".join(lines)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("run_dir", help="directory holding events.jsonl / run_manifest.json")
    p.add_argument(
        "--max-compile-rows", type=int, default=20, help="cap on compile-table rows"
    )
    args = p.parse_args()
    print(render(args.run_dir, max_compile_rows=args.max_compile_rows))


if __name__ == "__main__":
    main()
