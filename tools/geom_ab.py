"""Same-process interleaved A/B of batch/microbatch GEOMETRY on the flagship
train step, under the round-4 default configuration (host-sampled dropout
indices + bf16 Adam moments).

Motivation: the microbatch lever (round 3) and the host/bf16m levers
(round 4) were each measured at fixed geometry b=4, mb=2. But the levers
shift the optimum: per-sample fwd+bwd is cheapest at chunk size 2, while the
optimizer update is a fixed ~1 ms/step cost that larger batches amortize
over more samples. b=8 mb=4 keeps the cheap b=2 chunks AND halves the
per-sample optimizer tax — never measured. Variants are geometry strings
``b<batch>mb<microbatch>``; throughput (tok/s) normalizes per sample so
geometries are directly comparable.

    python tools/geom_ab.py [--variants b4mb2 b8mb4 b8mb2 b6mb3 b2mb1]
"""

from __future__ import annotations

import argparse
import functools
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, interleaved_slopes

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=16384)
    p.add_argument("--latents", type=int, default=1024)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument(
        "--variants", nargs="*", default=["b4mb2", "b8mb4", "b8mb2", "b6mb3", "b2mb1"]
    )
    args = p.parse_args()

    from perceiver_io_tpu.models.text import CausalLanguageModel
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step
    from perceiver_io_tpu.training.prefix_dropout import sample_prefix_keep_idx

    n = args.seq_len
    prefix_len = n - args.latents
    config = flagship_config(args.seq_len, args.latents)
    model = CausalLanguageModel(config, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    init_x = jnp.asarray(rng.integers(0, 262, size=(1, args.latents + 1)))
    params = model.init(jax.random.PRNGKey(0), init_x, prefix_len=1)
    loss_fn = clm_loss_fn(model.apply, max_latents=args.latents)

    def build(variant):
        m = re.fullmatch(r"b(\d+)mb(\d+)", variant)
        if not m:
            raise SystemExit(f"bad variant {variant!r}; expected e.g. b4mb2")
        b, mb = int(m.group(1)), int(m.group(2))
        t = rng.integers(0, 262, size=(b, n + 1))
        batch = {
            "labels": jnp.asarray(t[:, 1:]),
            "input_ids": jnp.asarray(t[:, :-1]),
            "pad_mask": None,
            "prefix_keep_idx": jnp.asarray(
                sample_prefix_keep_idx(rng, b, prefix_len, config.cross_attention_dropout)
            ),
        }
        tx = make_optimizer(1e-3, gradient_clip=1.0, moment_dtype="bfloat16")
        state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
        step = make_train_step(loss_fn, jit=False, microbatch=mb)

        @functools.partial(jax.jit, static_argnums=2)
        def run(state, batch, k):
            def body(c, _):
                l, s = c
                s, metrics = step(s, batch)
                return (l + metrics["loss"], s), ()

            (l, _), _ = jax.lax.scan(body, (jnp.float32(0), state), None, length=k)
            return l

        return b, (lambda k: float(run(state, batch, k)))

    n_short, n_long = 2, 2 + args.steps
    runs, batch_of = {}, {}
    for name in args.variants:
        batch_of[name], runs[name] = build(name)
        t0 = time.perf_counter()
        runs[name](n_short)
        runs[name](n_long)
        print(f"{name}: compiled in {time.perf_counter() - t0:.0f}s", flush=True)

    meds = interleaved_slopes(runs, n_short, n_long, reps=args.reps)
    print(f"{'variant':<10} {'ms/step':>8} {'tok/s':>12}")
    for v in args.variants:
        med = meds[v]
        if med is None:
            print(f"{v:<10}  all slope estimates non-positive (tunnel stall?) — rerun")
            continue
        print(f"{v:<10} {med * 1e3:8.3f} {batch_of[v] * n / med:12.0f}")


if __name__ == "__main__":
    main()
