"""Same-process interleaved A/B of image-classifier step variants (round 4:
the VERDICT r3 image roofline treatment). The image step's exclusive profile
(tools/profile_step.py --mode img) puts ~22.6 ms/step (12.7%) in XLA
layernorm stat fusions — an order of magnitude more LN work than the CLM
flagship (96 LN applications per forward over the 48-layer shared SA stack),
where the fused Pallas LN lost by 1%.

    python tools/img_ab.py [--batch-size 16] [--steps 8]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import interleaved_slopes  # noqa: E402  (repo root on sys.path above)

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--reps", type=int, default=4)
    # base = concat input route (round-3); split = fused split-kv input
    # (round-4 default); fusedln = base + Pallas LN (measured SLOWER)
    p.add_argument("--variants", nargs="*", default=["base", "split"])
    args = p.parse_args()

    from perceiver_io_tpu.core.config import ClassificationDecoderConfig
    from perceiver_io_tpu.models.vision.image_classifier import (
        ImageClassifier,
        ImageClassifierConfig,
        ImageEncoderConfig,
    )
    from perceiver_io_tpu.ops.layernorm import fused_ln
    from perceiver_io_tpu.training import TrainState, classification_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step

    config = ImageClassifierConfig(
        encoder=ImageEncoderConfig(
            image_shape=(224, 224, 3),
            num_frequency_bands=64,
            num_cross_attention_heads=1,
            num_self_attention_heads=8,
            num_self_attention_layers_per_block=6,
            num_self_attention_blocks=8,
            first_self_attention_block_shared=True,
        ),
        decoder=ClassificationDecoderConfig(
            num_classes=1000, num_output_query_channels=1024, num_cross_attention_heads=1
        ),
        num_latents=512,
        num_latent_channels=1024,
    )
    model = ImageClassifier(config, dtype=jnp.bfloat16)
    b = args.batch_size
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.normal(size=(b, 224, 224, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 1000, size=(b,))),
    }
    params = model.init(jax.random.PRNGKey(0), batch["image"])

    from perceiver_io_tpu.core.modules import PerceiverEncoder

    def build(variant):
        tx = make_optimizer(1e-3, gradient_clip=1.0)
        state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
        step = make_train_step(classification_loss_fn(model.apply), jit=False)

        @functools.partial(jax.jit, static_argnums=2)
        def run(state, batch, k):
            def body(c, _):
                l, s = c
                s, metrics = step(s, batch)
                return (l + metrics["loss"], s), ()

            (l, _), _ = jax.lax.scan(body, (jnp.float32(0), state), None, length=k)
            return l

        def call(k):
            # trace-time routing: 'base'/'fusedln' force the concat input
            # route by disabling the split gate; 'split' leaves the default
            orig = PerceiverEncoder._use_split_input
            if variant != "split":
                PerceiverEncoder._use_split_input = lambda self, pm, det: False
            try:
                with fused_ln(True if variant == "fusedln" else None):
                    return float(run(state, batch, k))
            finally:
                PerceiverEncoder._use_split_input = orig

        return call

    n_short, n_long = 1, 1 + args.steps
    runs = {}
    for name in args.variants:
        runs[name] = build(name)
        t0 = time.perf_counter()
        runs[name](n_short)
        runs[name](n_long)
        print(f"{name}: compiled in {time.perf_counter() - t0:.0f}s", flush=True)

    meds = interleaved_slopes(runs, n_short, n_long, reps=args.reps)
    print(f"{'variant':<16} {'ms/step':>8} {'img/s':>8}")
    for v in args.variants:
        med = meds[v]
        if med is None:
            print(f"{v:<16}  all slope estimates non-positive (tunnel stall?) — rerun")
            continue
        print(f"{v:<16} {med * 1e3:8.2f} {b / med:8.1f}")


if __name__ == "__main__":
    main()
