"""Runtime-regression differ: two runs' telemetry → regression / improvement
/ neutral verdicts under declared tolerances.

graphcheck (PRs 6-7) pins the *static* surface — a change can keep its
compiled-graph contract byte-identical and still regress wall-clock, and
nothing noticed. This tool is the runtime leg: it loads two run
directories' (shard-merged) ``events.jsonl`` + ``run_manifest.json``,
REFUSES non-comparable pairs (different mesh / device / model geometry /
jax — the same stale-contract discipline as ``diff_fingerprints``: that is
exit 2, *not* a regression), and classifies the delta in every shared
runtime metric:

- throughput/utilization from ``log`` rows: MFU, goodput, tokens/sec,
  input_wait;
- step-latency percentiles from the per-step ``span`` rows (p50/p99 of the
  host step wall; ``low_n`` windows classify neutral — a 3-sample p99 is
  not evidence);
- serving SLO percentiles from ``request`` rows via ``obs.slo`` (TTFT and
  histogram-derived TPOT p50/p99, error rate).

    python tools/obs_diff.py BASELINE_RUN CANDIDATE_RUN [--json]
        [--tolerance mfu=0.1 --tolerance step_ms_p99=0.3]

Exit codes (mirrors tools/graphcheck.py): 0 clean (improvements included),
1 regression, 2 not-comparable / missing telemetry, 3 internal error.
Wired into ``tasks.py obs`` (run-vs-itself must be clean) and — behind the
``OBS_BASELINE_RUN`` knob — ``tasks.py perf``, giving the perf ledger's
floors a runtime counterpart. docs/observability.md#runtime-diffing has the
comparability rules.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # direct `python tools/obs_diff.py` invocation
    sys.path.insert(0, _REPO)


# metric -> (better direction, tolerance kind, default tolerance). rel
# tolerances are fractions of the baseline; abs tolerances are absolute
# deltas (goodput/error_rate are already fractions). Tail percentiles get
# looser defaults than medians — they are noisier on short runs.
METRICS: Dict[str, tuple] = {
    "mfu": ("higher", "rel", 0.05),
    "goodput": ("higher", "abs", 0.03),
    "tokens_per_sec": ("higher", "rel", 0.05),
    "steps_per_sec": ("higher", "rel", 0.05),
    "input_wait_ms": ("lower", "rel", 0.50),
    "step_ms_p50": ("lower", "rel", 0.10),
    "step_ms_p99": ("lower", "rel", 0.25),
    "ttft_s_p50": ("lower", "rel", 0.10),
    "ttft_s_p99": ("lower", "rel", 0.25),
    "tpot_s_p50": ("lower", "rel", 0.10),
    "tpot_s_p99": ("lower", "rel", 0.25),
    "error_rate": ("lower", "abs", 0.0),
}

# manifest fields that must match for two runs' numbers to be comparable at
# all (diff_fingerprints discipline: a mismatch is a STALE baseline, not a
# regression) — mesh/devices/process topology, model geometry, jax version
_COMPARABILITY_KEYS = (
    "backend",
    "device_kind",
    "device_count",
    "process_count",
    "mesh",
    "jax_version",
    "model_config",
)


@dataclasses.dataclass
class Delta:
    metric: str
    kind: str  # "regression" | "improvement" | "neutral"
    old: Optional[float]
    new: Optional[float]
    detail: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class RunDiff:
    comparable: bool
    reason: str  # why not comparable ("" when comparable)
    deltas: List[Delta]

    @property
    def regressions(self) -> List[Delta]:
        return [d for d in self.deltas if d.kind == "regression"]

    @property
    def improvements(self) -> List[Delta]:
        return [d for d in self.deltas if d.kind == "improvement"]

    def ok(self) -> bool:
        return self.comparable and not self.regressions

    def format(self) -> str:
        if not self.comparable:
            return f"obs_diff: NOT COMPARABLE — {self.reason}"
        head = (
            f"obs_diff: {len(self.regressions)} regression(s), "
            f"{len(self.improvements)} improvement(s), "
            f"{len(self.deltas) - len(self.regressions) - len(self.improvements)} neutral"
        )
        lines = [head]
        order = {"regression": 0, "improvement": 1, "neutral": 2}
        for d in sorted(self.deltas, key=lambda d: (order[d.kind], d.metric)):
            old = "-" if d.old is None else f"{d.old:.6g}"
            new = "-" if d.new is None else f"{d.new:.6g}"
            note = f"  ({d.detail})" if d.detail else ""
            lines.append(f"  [{d.kind:<11}] {d.metric}: {old} -> {new}{note}")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {
            "comparable": self.comparable,
            "reason": self.reason,
            "ok": self.ok(),
            "deltas": [d.to_dict() for d in self.deltas],
        }


def _median(vals: List[float]) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def summarize_run(run_dir: str) -> dict:
    """``{manifest, metrics, low_n}`` — the comparable surface of one run
    directory. Metrics are medians over ``log`` rows (robust to one cold
    window), step percentiles over ``span`` rows, SLO percentiles from
    ``request`` rows; ``low_n`` names the percentile families whose sample
    count is below the exact-order-statistics threshold."""
    from perceiver_io_tpu.obs.events import merged_events
    from perceiver_io_tpu.obs.slo import build_slo_report
    from perceiver_io_tpu.utils.profiling import summarize_latencies

    manifest_path = os.path.join(run_dir, "run_manifest.json")
    manifest = None
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    events = merged_events(run_dir)
    metrics: Dict[str, float] = {}
    low_n: List[str] = []

    logs = [e for e in events if e.get("event") == "log"]
    for key in ("mfu", "goodput", "tokens_per_sec", "steps_per_sec", "input_wait_ms"):
        med = _median([float(e[key]) for e in logs if isinstance(e.get(key), (int, float))])
        if med is not None:
            metrics[key] = med

    steps = [
        e for e in events if e.get("event") == "span" and e.get("name") == "step"
    ]
    # warm steps only: a step span that absorbed a compile / graphlint /
    # graphcheck pass is wall-clock-dominated by it (the first step's span
    # is ~the XLA compile), and those events are stamped with their step
    # span's id — so exclusion is exact, not positional. Diffing
    # compile-inflated p99s would gate on compiler variance, the same
    # reason obs_report/obs.slo are warm-only.
    overhead_sids = {
        e.get("span_id")
        for e in events
        if e.get("event") in ("compile", "graphlint", "graphcheck")
    }
    warm_steps = [e for e in steps if e.get("span_id") not in overhead_sids]
    if warm_steps:
        s = summarize_latencies([float(e["dur_ms"]) for e in warm_steps])
        metrics["step_ms_p50"] = s["p50"]
        metrics["step_ms_p99"] = s["p99"]
        if s.get("low_n"):
            low_n.append("step_ms")

    slo = build_slo_report(events)
    if slo is not None:
        metrics["error_rate"] = float(slo.get("error_rate", 0.0))
        ttft = slo.get("ttft_s")
        if ttft:
            metrics["ttft_s_p50"] = float(ttft["p50"])
            metrics["ttft_s_p99"] = float(ttft["p99"])
            if ttft.get("low_n"):
                low_n.append("ttft_s")
        tpot = slo.get("tpot_s")
        if tpot:
            metrics["tpot_s_p50"] = float(tpot["p50"])
            metrics["tpot_s_p99"] = float(tpot["p99"])
            if tpot.get("low_n"):
                low_n.append("tpot_s")
    return {"run_dir": os.path.abspath(run_dir), "manifest": manifest, "metrics": metrics,
            "low_n": low_n, "n_events": len(events)}


def comparability_problems(old: dict, new: dict) -> List[str]:
    """Manifest mismatches that make a perf comparison meaningless."""
    om, nm = old.get("manifest"), new.get("manifest")
    if om is None or nm is None:
        missing = [s["run_dir"] for s, m in ((old, om), (new, nm)) if m is None]
        return [f"missing run_manifest.json in {d}" for d in missing]
    out = []
    for key in _COMPARABILITY_KEYS:
        if om.get(key) != nm.get(key):
            ov, nv = om.get(key), nm.get(key)
            if key == "model_config":  # too big to print whole
                ov, nv = "<baseline model_config>", "<differs>"
            out.append(f"{key}: {ov!r} != {nv!r}")
    return out


def diff_runs(
    old: dict, new: dict, tolerances: Optional[Dict[str, float]] = None
) -> RunDiff:
    """Classify every metric present in BOTH summaries. A metric whose
    sample count was low_n on either side is neutral (annotated) — exact
    order statistics over <5 samples are data, not tails."""
    problems = comparability_problems(old, new)
    if problems:
        return RunDiff(comparable=False, reason="; ".join(problems), deltas=[])
    if not old["metrics"] or not new["metrics"]:
        empty = [s["run_dir"] for s in (old, new) if not s["metrics"]]
        return RunDiff(
            comparable=False,
            reason="no runtime metrics in " + ", ".join(empty),
            deltas=[],
        )
    tolerances = tolerances or {}
    deltas: List[Delta] = []
    for metric, (direction, tol_kind, tol_default) in METRICS.items():
        o, n = old["metrics"].get(metric), new["metrics"].get(metric)
        if o is None and n is None:
            continue
        if o is None or n is None:
            deltas.append(
                Delta(metric, "neutral", o, n, "present in only one run")
            )
            continue
        family = metric.rsplit("_p", 1)[0]
        if family in old["low_n"] or family in new["low_n"]:
            deltas.append(Delta(metric, "neutral", o, n, "low_n sample"))
            continue
        tol = float(tolerances.get(metric, tol_default))
        margin = tol * abs(o) if tol_kind == "rel" else tol
        worse = (o - n) if direction == "higher" else (n - o)
        kind = "regression" if worse > margin else (
            "improvement" if -worse > margin else "neutral"
        )
        pct = f"{(n - o) / o * 100:+.1f}%" if o else f"{n - o:+.4g}"
        deltas.append(Delta(metric, kind, o, n, pct))
    return RunDiff(comparable=True, reason="", deltas=deltas)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("baseline", help="baseline run directory")
    p.add_argument("candidate", help="candidate run directory")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--tolerance",
        action="append",
        default=[],
        metavar="METRIC=TOL",
        help="override a tolerance (repeatable), e.g. --tolerance mfu=0.1",
    )
    args = p.parse_args(argv)
    tolerances = {}
    for spec in args.tolerance:
        if "=" not in spec:
            p.error(f"--tolerance wants METRIC=TOL, got {spec!r}")
        k, v = spec.split("=", 1)
        if k not in METRICS:
            p.error(f"unknown metric {k!r} (known: {', '.join(sorted(METRICS))})")
        tolerances[k] = float(v)
    try:
        old = summarize_run(args.baseline)
        new = summarize_run(args.candidate)
        diff = diff_runs(old, new, tolerances)
    except Exception as e:  # noqa: BLE001 — CI must see crash != verdict
        print(f"obs_diff: internal error: {e}", file=sys.stderr)
        return 3
    if args.json:
        print(json.dumps({"baseline": old["run_dir"], "candidate": new["run_dir"],
                          **diff.to_dict()}, indent=2))
    else:
        print(diff.format())
    if not diff.comparable:
        return 2
    return 0 if diff.ok() else 1


if __name__ == "__main__":
    sys.exit(main())
