"""Loadline CLI — drive synthetic serving load, certify it, round-trip it.

The standing serving-observability gate (``tasks.py load``; ``--smoke`` is
wired into ``tasks.py perf``): run a seeded closed-loop (or open-loop) load
against the tiny flagship-family CLM through the fully instrumented path —
flight recorder wrapping the event log, ``/metrics``+``/slo`` scrape server
up for the duration — then assert the whole surface end to end:

1. the event stream validates (``load.summary``, ``flight.dump``,
   queue-wait-stamped ``request`` rows all schema-checked);
2. a **planted SLO breach** (the recorder's TTFT bound tightened to ~0 for
   one extra request riding the already-compiled fns) produces EXACTLY one
   flight dump whose ``flight.dump`` event names the breaching request's
   span — the post-mortem path demonstrably works;
3. the live scrape surface answers: ``/metrics`` exposes
   ``histogram_quantile``-ready series, ``/slo`` serves the live report;
4. the run summarizes into a LOAD artifact body whose run-vs-itself
   :func:`obs.loadgen.diff_load` is clean (comparability rules hold);
5. the ledger's ``LOAD_r*.json`` floors hold against the latest committed
   artifact (``contracts/ledger.json`` — the same floor machinery the
   bench gate uses).

    python tools/loadgen.py                      # the full gate (200 reqs)
    python tools/loadgen.py --smoke              # CI-fast subset (24 reqs)
    python tools/loadgen.py --write-artifact     # refresh LOAD_r<next>.json
    python tools/loadgen.py --diff OLD.json NEW.json [--tolerance k=v]
    python tools/loadgen.py --mode open --rate 20 --requests 100
    python tools/loadgen.py --fleet 2            # Fleetline routed round

Exit codes (mirrors tools/obs_gate.py): 0 clean, 1 gate failure /
regression, 2 not comparable (diff mode), 3 internal error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import shutil
import sys
import tempfile
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def build_workload():
    """The gate's model: same tiny flagship-family geometry as
    tools/obs_gate.py (the gate certifies serving telemetry, not perf)."""
    import jax
    import numpy as np

    from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig

    config = CausalLanguageModelConfig(
        vocab_size=64, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    ids = np.random.default_rng(0).integers(0, config.vocab_size, size=(1, 12))
    import jax.numpy as jnp

    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids), prefix_len=8)
    return model, params, config


def _fetch(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode()


def run_gate(args) -> int:
    from perceiver_io_tpu.obs.events import EventLog, validate_events, write_run_manifest
    from perceiver_io_tpu.obs.flightrec import FlightRecorder, SLOBounds
    from perceiver_io_tpu.obs.loadgen import (
        WorkloadSpec,
        build_load_doc,
        diff_load,
        format_load_diff,
        run_load,
    )
    from perceiver_io_tpu.obs.slo import request_breakdowns, write_slo_report

    out_dir = args.out or tempfile.mkdtemp(prefix="loadgen_")
    keep = args.keep or args.out is not None
    problems: list = []
    try:
        n_requests = args.requests
        spec = WorkloadSpec(seed=args.seed)
        print(
            f"loadgen: {args.mode}-loop, {n_requests} requests "
            f"({'concurrency ' + str(args.concurrency) if args.mode == 'closed' else f'rate {args.rate}/s'}) "
            f"-> {out_dir}"
        )
        model, params, config = build_workload()
        events = EventLog(out_dir, main_process=True)
        manifest = write_run_manifest(
            out_dir, model_config=config, extra={"workload_spec": spec.to_dict()},
            main_process=True,
        )
        # generous standing bounds: the planted breach below, not normal CPU
        # jitter, is what should trip the recorder in this gate
        recorder = FlightRecorder(
            events, out_dir=out_dir,
            slo=SLOBounds(ttft_s=args.ttft_slo, tpot_p99_s=args.tpot_slo),
        )

        from perceiver_io_tpu.obs.metrics import MetricsRegistry
        from perceiver_io_tpu.obs.server import ObsServer

        registry = MetricsRegistry()
        with ObsServer(registry=registry, run_dir=out_dir) as server:
            report = run_load(
                model, params, spec,
                mode=args.mode, n_requests=n_requests,
                concurrency=args.concurrency, rate_rps=args.rate,
                num_latents=4, events=recorder, registry=registry,
                snapshot_interval_s=0.0,
            )
            summary = report.summary
            print(
                f"loadgen: {summary['n_requests']} requests in {summary['duration_s']:.2f}s "
                f"({summary['achieved_rps']:.1f} req/s, {summary['throughput_tok_s']:.0f} tok/s, "
                f"{summary['errors']} errors, {summary['n_cold']} cold)"
            )

            # span-joined tail attribution over the MAIN run (before the
            # plant adds its request): enriches the artifact's breakdown
            # with the compile-if-cold / service / total legs only the
            # event-stream join can see
            from perceiver_io_tpu.obs.events import merged_events

            bd = request_breakdowns(merged_events(out_dir))
            if not bd or "prefill_ms" not in bd.get("medians", {}):
                problems.append("request_breakdowns produced no prefill median")
            else:
                summary["breakdown_ms"] = {
                    key.replace("_ms", ""): val
                    for key, val in bd["medians"].items()
                }

            # --- planted SLO breach: exactly one dump, naming the span ---
            dumps_before = len(recorder.dumps)
            prev_ttft = recorder.slo.ttft_s
            recorder.slo.ttft_s = 1e-9
            plant = run_load(
                model, params, WorkloadSpec(seed=args.seed + 999),
                mode="closed", n_requests=1, concurrency=1,
                num_latents=4, events=recorder, registry=report.registry,
                generate_fns=report.generate_fns, snapshot_interval_s=1e9,
            )
            recorder.slo.ttft_s = prev_ttft
            if plant.records[0].outcome != "ok":
                problems.append(f"planted request errored: {plant.records[0].error}")
            new_dumps = recorder.dumps[dumps_before:]
            if len(new_dumps) != 1:
                problems.append(
                    f"planted SLO breach produced {len(new_dumps)} flight dumps, want exactly 1"
                )
            else:
                with open(new_dumps[0]) as f:
                    dump = json.load(f)
                if dump.get("trigger") != "slo_ttft":
                    problems.append(f"dump trigger {dump.get('trigger')!r} != 'slo_ttft'")
                if not dump.get("trigger_span_id"):
                    problems.append("flight dump does not name the breaching span")
                if not dump.get("events"):
                    problems.append("flight dump carries no ring events")
                elif not any(
                    e.get("event") == "span"
                    and e.get("span_id") == dump.get("trigger_span_id")
                    for e in dump["events"]
                ):
                    # the post-mortem contract: the ring frozen into the
                    # dump must hold the very span the dump names
                    problems.append("flight dump ring lacks the named trigger span")

            # --- scrape surface answers while the run is live ---
            metrics_text = _fetch(server.url + "/metrics")
            if 'generate_ttft_s_bucket{le="+Inf"}' not in metrics_text:
                problems.append("/metrics lacks the +Inf TTFT bucket (histogram_quantile would fail)")
            if "generate_queue_wait_s_count" not in metrics_text:
                problems.append("/metrics lacks the queue-wait histogram")
            health = json.loads(_fetch(server.url + "/healthz"))
            if health.get("status") != "ok":
                problems.append(f"/healthz status {health.get('status')!r}")
            slo_live = json.loads(_fetch(server.url + "/slo"))
            if slo_live.get("n_requests") != n_requests + 1:
                problems.append(
                    f"/slo n_requests {slo_live.get('n_requests')} != {n_requests + 1}"
                )

        # --- event stream validates, dump event in stream ---
        warnings_out: list = []
        problems += validate_events(out_dir, warnings_out=warnings_out)
        for w in warnings_out:
            print(f"loadgen: warning: {w}")
        stream = merged_events(out_dir)
        kinds = [e.get("event") for e in stream]
        if "load.summary" not in kinds:
            problems.append("no load.summary event in the stream")
        dump_rows = [e for e in stream if e.get("event") == "flight.dump"]
        if len(dump_rows) != 1:
            problems.append(f"{len(dump_rows)} flight.dump events in stream, want 1")
        else:
            breach = [e for e in stream if e.get("event") == "request"][-1]
            if dump_rows[0].get("trigger_span_id") != breach.get("span_id"):
                problems.append("flight.dump trigger_span_id != breaching request's span_id")
        loadgen_reqs = [
            e for e in stream
            if e.get("event") == "request" and e.get("queue_wait_s") is not None
        ]
        if len(loadgen_reqs) != n_requests + 1:
            problems.append(
                f"{len(loadgen_reqs)} queue-wait-stamped request rows, want {n_requests + 1}"
            )
        for key in ("achieved_rps", "throughput_tok_s", "error_rate", "ttft_s",
                    "queue_wait_s", "breakdown_ms"):
            if key not in summary:
                problems.append(f"summary missing {key!r}")
        write_slo_report(out_dir)

        # --- artifact body + run-vs-itself comparability diff ---
        doc = build_load_doc(
            args.round or _next_round(), summary, spec, manifest=manifest
        )
        self_diff = diff_load(doc, doc)
        if not (self_diff["comparable"] and self_diff["ok"]):
            problems.append("run-vs-itself load diff NOT clean (differ broken): "
                            + format_load_diff(self_diff))
        else:
            print("loadgen: run-vs-itself comparability diff clean")

        if args.write_artifact:
            # pre-validate THIS doc against the LOAD floors before it hits
            # disk: a sub-floor artifact (e.g. a --smoke-size run) would
            # become the latest round and fail every future gate run.
            # NOTE the LOAD family is ENGINE-floored since PR 13 (a
            # deliberate ratchet: committed serving rounds must sustain
            # engine-scale throughput) — sequential runs certify telemetry
            # here but produce new rounds with --engine.
            floor_fails = check_doc_floors(doc)
            if floor_fails:
                problems += [
                    f"refusing to write artifact: {f} (the LOAD family is "
                    "engine-floored — produce committed rounds with --engine)"
                    for f in floor_fails
                ]
            else:
                path = os.path.join(_REPO, f"LOAD_r{doc['n']:02d}.json")
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                    f.write("\n")
                print(f"loadgen: wrote {path}")

        # --- ledger floors over the committed LOAD artifacts ---
        problems += check_load_floors()

        if problems:
            print("loadgen: gate FAILED:")
            for p in problems:
                print(f"  - {p}")
            return 1
        print(
            "loadgen: OK — "
            f"ttft_p99={summary['ttft_s']['p99']}s "
            f"queue_p99={summary['queue_wait_s']['p99']}s "
            f"(1 planted breach -> 1 flight dump)"
        )
        return 0
    except Exception as e:  # noqa: BLE001 — CI must see crash != verdict
        print(f"loadgen: internal error: {e}", file=sys.stderr)
        import traceback

        traceback.print_exc()
        return 3
    finally:
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)


def run_engine_gate(args) -> int:
    """The ENGINE leg (``--engine``): a closed-loop (or, with ``--mode
    open --rate R``, an open-loop Poisson-arrival) run through the
    continuous-batching paged-KV engine (``serving.engine.EngineFrontEnd``,
    docs/serving.md) instead of the sequential instrumented path. The
    engine warms its compile caches through the same instance before the
    measured run (one request per workload geometry) — an open-loop queue
    must not flood during the cold-start compile storm the closed loop
    self-throttles through, and the Loadline charter is warm serving
    either way. Asserts:

    1. every request served ok, books balanced, zero leaked slots AND zero
       leaked pages (allocator audit);
    2. the event stream validates — engine ``request`` rows carry
       queue-wait and the ``batch_size_at_decode`` field;
    3. a planted mid-decode kill (its own engine instance + recorder, so
       the main artifact stays clean) leaves books balanced with exactly
       one flight dump naming the dead request's span;
    4. ``/metrics`` exposes the engine gauges
       (``engine_batch_fill_frac`` / ``engine_kv_pages_used``);
    5. the summary diffs clean against itself and the LOAD floors hold —
       including the engine throughput floor and p99-TPOT ceiling.

    The summary (and so the ``load.summary`` event and the LOAD artifact
    body) carries the Evictline counters — ``evictions`` / ``resumes`` /
    ``parked_depth_peak`` (the ``serve_parked_depth`` gauge's high-water
    mark) — as optional validated fields, so eviction behavior lands under
    the standing comparability-diffed gate
    (docs/robustness.md#engine-eviction-and-recovery).
    """
    import time as _time

    from perceiver_io_tpu.obs.events import EventLog, validate_events, write_run_manifest
    from perceiver_io_tpu.obs.flightrec import FlightRecorder, SLOBounds
    from perceiver_io_tpu.obs.loadgen import (
        RequestRecord,
        WorkloadSpec,
        build_load_doc,
        diff_load,
        format_load_diff,
        summarize_load,
    )
    from perceiver_io_tpu.obs.metrics import MetricsRegistry
    from perceiver_io_tpu.obs.server import ObsServer
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd
    from perceiver_io_tpu.serving.faultinject import FaultInjector

    out_dir = args.out or tempfile.mkdtemp(prefix="loadgen_engine_")
    keep = args.keep or args.out is not None
    problems: list = []
    try:
        n_requests = args.requests
        spec = WorkloadSpec(seed=args.seed)
        engine_cfg = EngineConfig(
            slots=args.slots, page_size=8, max_ca_tokens=24, max_sa_tokens=16
        )
        drive = (
            f"open-loop @ {args.rate} req/s" if args.mode == "open"
            else f"closed-loop, concurrency {args.concurrency}"
        )
        print(
            f"loadgen: ENGINE {drive}, {n_requests} requests "
            f"(slots {engine_cfg.slots}) -> {out_dir}"
        )
        model, params, config = build_workload()
        events = EventLog(out_dir, main_process=True)
        manifest = write_run_manifest(
            out_dir, model_config=config,
            extra={"workload_spec": spec.to_dict(), "engine": True},
            main_process=True,
        )
        recorder = FlightRecorder(
            events, out_dir=out_dir,
            slo=SLOBounds(ttft_s=args.ttft_slo, tpot_p99_s=args.tpot_slo),
        )
        from perceiver_io_tpu.serving import FrontEndConfig

        registry = MetricsRegistry()
        fe = EngineFrontEnd(
            model, params, num_latents=4, engine_config=engine_cfg,
            # frequent enough that live batch-fill/page gauges land in the
            # stream, coarse enough that snapshot I/O stays off the hot loop
            config=FrontEndConfig(snapshot_interval_s=0.25),
            events=recorder, registry=registry,
        )
        specs = spec.draw(n_requests, int(config.vocab_size))
        # warm the compile caches through the SAME engine instance before
        # the measured run: one request per (prompt_len, budget) geometry in
        # the mix compiles its prefill/join path. An open-loop run must not
        # flood its bounded queue during the cold-start compile storm (the
        # closed loop self-throttles there, open-loop arrivals do not wait)
        # — and the Loadline charter is to measure WARM serving either way.
        warm = dataclasses_replace_indices(
            [
                WorkloadSpec(
                    seed=args.seed + 7777 + i, prompt_lens=(p,), max_new_tokens=(m,)
                ).draw(1, int(config.vocab_size))[0]
                for i, (p, m) in enumerate(
                    (p, m) for p in spec.prompt_lens for m in spec.max_new_tokens
                )
            ],
            base=1_000_000,
        )
        fe.run_closed(warm, concurrency=len(warm))
        n_warm = len(warm)
        # measured-window boundary: the warm requests above fed the same
        # registry/engine counters the artifact summarizes — drop their
        # per-token samples and mark the step/fill counters so committed
        # percentiles and engine figures cover only measured traffic
        registry.histogram("generate_tpot_s").reset()
        warm_steps, warm_fill = fe._engine_steps, fe._fill_sum
        warm_books = fe.books()
        warm_evictions, warm_resumes = warm_books["evictions"], warm_books["resumes"]
        registry.gauge("serve_parked_depth").reset_peak()
        with ObsServer(registry=registry, run_dir=out_dir, health=fe.health) as server:
            t0 = _time.perf_counter()
            if args.mode == "open":
                # the open-loop leg (ISSUE 14 satellite — the item-1
                # certification remainder): Poisson arrivals at the target
                # rate absorbed by the continuous batch; achieved_rps is
                # the externally-imposed rate actually sustained, the
                # number the engine_open_achieved_rps ledger floor pins
                recs = fe.run_open(specs, rate_rps=args.rate, seed=args.seed + 1)
            else:
                recs = fe.run_closed(specs, concurrency=args.concurrency)
            duration_s = _time.perf_counter() - t0

            metrics_text = _fetch(server.url + "/metrics")
            for gauge in ("engine_batch_fill_frac", "engine_kv_pages_used"):
                if gauge not in metrics_text:
                    problems.append(f"/metrics lacks the {gauge} gauge")
            health = json.loads(_fetch(server.url + "/healthz"))
            if health.get("books_balanced") is not True:
                problems.append(f"/healthz books_balanced {health.get('books_balanced')!r}")

        books = fe.books()
        problems += [f"engine books: {p}" for p in fe.audit()]
        problems += [f"ca pages: {p}" for p in fe.ca_alloc.audit()]
        problems += [f"sa pages: {p}" for p in fe.sa_alloc.audit()]
        if fe.ca_alloc.pages_used or fe.sa_alloc.pages_used:
            problems.append(
                f"pages leaked after drain: ca={fe.ca_alloc.pages_used} "
                f"sa={fe.sa_alloc.pages_used}"
            )
        if books["ok"] != n_requests + n_warm:
            problems.append(
                f"served {books['ok']}/{n_requests} (+{n_warm} warmup) ok: {books}"
            )

        records = [
            RequestRecord(
                index=r.index, prompt_len=r.prompt_len,
                max_new_tokens=r.max_new_tokens, batch=r.batch,
                queue_wait_s=r.queue_wait_s or 0.0,
                outcome="ok" if r.outcome == "ok" else "error",
                compiled=r.compiled, ttft_s=r.ttft_s, decode_s=r.decode_s,
                tokens_out=r.tokens_out,
            )
            for r in recs
        ]
        summary = summarize_load(
            records, duration_s, registry=registry, mode=args.mode,
            concurrency=args.concurrency if args.mode == "closed" else None,
            rate_rps=args.rate if args.mode == "open" else None,
        )
        steps = fe._engine_steps - warm_steps
        summary["engine"] = {
            "slots": engine_cfg.slots,
            "page_size": engine_cfg.page_size,
            "decode_steps": steps,
            "batch_fill_frac": round(
                (fe._fill_sum - warm_fill) / (steps * engine_cfg.slots), 6
            ) if steps else 0.0,
        }
        # Evictline telemetry on the load.summary row AND the LOAD artifact
        # body — optional validated fields (obs.events._OPTIONAL_FIELD_TYPES:
        # pre-Evictline artifacts stay valid, a non-numeric regression here
        # fails validation), so eviction behavior rides the standing
        # comparability-diffed gate. Zero under the default full-headroom
        # pool; a committed run with a tight pool records its real churn —
        # delta-based at the measured-window boundary like decode_steps/
        # batch_fill above (the odometers are lifetime counters and the
        # parked-depth peak resets after warmup), so warmup churn never
        # contaminates the committed figures.
        fe_books = fe.books()
        parked_peak = fe.registry.gauge("serve_parked_depth").peak
        summary["evictions"] = fe_books["evictions"] - warm_evictions
        summary["resumes"] = fe_books["resumes"] - warm_resumes
        summary["parked_depth_peak"] = 0 if parked_peak is None else int(parked_peak)
        if events is not None:
            events.emit("load.summary", **summary)
            registry.maybe_emit(events, min_interval_s=0.0)
        print(
            f"loadgen: engine served {summary['n_requests']} requests in "
            f"{summary['duration_s']:.2f}s ({summary['throughput_tok_s']:.0f} tok/s, "
            f"{fe._engine_steps} batched steps, {summary['errors']} errors)"
        )

        # --- planted mid-decode kill: separate instance, clean main books --
        plant_dir = os.path.join(out_dir, "plant")
        plant_events = EventLog(plant_dir, main_process=True)
        plant_rec = FlightRecorder(plant_events, out_dir=plant_dir, slo=SLOBounds())
        injector = FaultInjector().kill_at(2, 1)
        plant_fe = EngineFrontEnd(
            model, params, num_latents=4, engine_config=engine_cfg,
            events=plant_rec, injector=injector,
        )
        plant_recs = plant_fe.run_closed(spec.draw(6, int(config.vocab_size)),
                                         concurrency=4)
        plant_books = plant_fe.books()
        if not plant_books["balanced"] or plant_books["error"] != 1:
            problems.append(f"planted kill books not clean: {plant_books}")
        if plant_fe.ca_alloc.pages_used or plant_fe.sa_alloc.pages_used:
            problems.append("planted kill leaked pages")
        if len(plant_rec.dumps) != 1:
            problems.append(
                f"planted kill produced {len(plant_rec.dumps)} flight dumps, want 1"
            )
        else:
            with open(plant_rec.dumps[0]) as f:
                dump = json.load(f)
            from perceiver_io_tpu.obs.events import merged_events as _merged

            err_rows = [e for e in _merged(plant_dir)
                        if e.get("event") == "request" and e.get("outcome") == "error"]
            if len(err_rows) != 1 or dump.get("trigger_span_id") != err_rows[0].get("span_id"):
                problems.append("kill dump does not name the dead request's span")
        dead = next((r for r in plant_recs if r.outcome == "error"), None)
        if dead is None or not (0 < dead.tokens_out < dead.max_new_tokens):
            problems.append(f"planted kill not mid-decode: {dead}")

        # --- stream validation (engine rows carry the new optional field) --
        warnings_out: list = []
        problems += validate_events(out_dir, warnings_out=warnings_out)
        for w in warnings_out:
            print(f"loadgen: warning: {w}")
        from perceiver_io_tpu.obs.events import merged_events

        stream = merged_events(out_dir)
        req_rows = [e for e in stream if e.get("event") == "request"]
        if len(req_rows) != n_requests + n_warm:
            problems.append(
                f"{len(req_rows)} request rows, want {n_requests} + {n_warm} warmup"
            )
        if not any(e.get("batch_size_at_decode") for e in req_rows):
            problems.append("no request row carries batch_size_at_decode")
        if not all(e.get("queue_wait_s") is not None for e in req_rows):
            problems.append("engine request rows missing queue_wait_s")

        for key in ("achieved_rps", "throughput_tok_s", "error_rate", "ttft_s",
                    "queue_wait_s", "tpot_s", "breakdown_ms",
                    "evictions", "resumes", "parked_depth_peak"):
            if key not in summary:
                problems.append(f"engine summary missing {key!r}")

        doc = build_load_doc(
            args.round or _next_round(), summary, spec, manifest=manifest,
        )
        self_diff = diff_load(doc, doc)
        if not (self_diff["comparable"] and self_diff["ok"]):
            problems.append("run-vs-itself load diff NOT clean: "
                            + format_load_diff(self_diff))

        if args.write_artifact:
            floor_fails = check_doc_floors(doc)
            if floor_fails:
                problems += [f"refusing to write artifact: {f}" for f in floor_fails]
            else:
                path = os.path.join(_REPO, f"LOAD_r{doc['n']:02d}.json")
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                    f.write("\n")
                print(f"loadgen: wrote {path}")

        problems += check_load_floors()

        if problems:
            print("loadgen: engine gate FAILED:")
            for p in problems:
                print(f"  - {p}")
            return 1
        print(
            "loadgen: engine OK — "
            f"{summary['throughput_tok_s']:.0f} tok/s at ok_rate "
            f"{summary['ok_rate']} (planted mid-decode kill: books balanced, "
            "1 flight dump, pages freed)"
        )
        return 0
    except Exception as e:  # noqa: BLE001 — CI must see crash != verdict
        print(f"loadgen: internal error: {e}", file=sys.stderr)
        import traceback

        traceback.print_exc()
        return 3
    finally:
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)


def build_prefix_workload():
    """The prefix gate's model: a WIDE flagship-family geometry (256
    channels, 8 latents, 448-token prompts). Sharing pays in skipped
    prefill compute — embed + CA k/v projections over the matched context
    run — and on the tiny c32 gate model that compute is dispatch noise,
    so a shared-vs-unshared TTFT ratio measured there would certify
    nothing. At c256 the unshared prefill is genuinely compute-bound and
    the 0.5x ratio floor measures the sharing win, not jit overhead."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig

    config = CausalLanguageModelConfig(
        vocab_size=256, max_seq_len=512, max_latents=32, num_channels=256,
        num_heads=8, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    ids = np.random.default_rng(0).integers(0, config.vocab_size, size=(1, 64))
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids), prefix_len=56)
    return model, params, config


def run_prefix_gate(args) -> int:
    """The PREFIX-SHARING leg (``--prefix``): the Shareline certification
    run (docs/serving.md#prefix-sharing). A closed-loop workload whose
    requests share a 440-token prompt prefix is served twice on the same
    wide-model geometry — once with ``EngineConfig.prefix_sharing`` on
    (the measured/artifact leg) and once with it off (the baseline leg) —
    and the gate asserts the sharing machinery end to end:

    1. every request served ok in BOTH legs, and the two legs'
       token streams are **bit-exact identical** per request (sharing is
       an allocator/prefill optimization, never an approximation);
    2. the measured leg actually shared: prefix hit rate >= the
       ``load_prefix_hit_rate`` ledger floor, ``serve.prefix_hit`` events
       span-attributed in the validated stream, ``serve_prefix_hits_total``
       live on ``/metrics``;
    3. books balanced, page audits clean, the SHARING audit clean
       (refcount balance + index/books agreement), and the prefix index
       fully expired at drain — no node may outlive its pages;
    4. the artifact body carries a ``summary.prefix`` block whose
       ``ttft_p50_ratio`` (shared / unshared TTFT p50, same geometry)
       holds the <= 0.5 ``load_shared_ttft_ratio`` ceiling.

    The committed doc deliberately does NOT carry ``summary.engine``: the
    engine-gate floors (throughput >= 621 tok/s, p99-TPOT <= 5ms) were
    calibrated on the tiny c32 gate model and keep reading the ``--engine``
    rounds (LOAD_r02/r03); the wide-model prefix round is judged by its own
    ``summary.prefix``-matched floors plus the family-wide ok-rate/size
    floors. The engine figures are still recorded under
    ``summary.prefix.engine`` for the record."""
    import dataclasses
    import time as _time

    from perceiver_io_tpu.obs.events import EventLog, validate_events, write_run_manifest
    from perceiver_io_tpu.obs.flightrec import FlightRecorder, SLOBounds
    from perceiver_io_tpu.obs.loadgen import (
        RequestRecord,
        WorkloadSpec,
        build_load_doc,
        diff_load,
        format_load_diff,
        summarize_load,
    )
    from perceiver_io_tpu.obs.metrics import MetricsRegistry
    from perceiver_io_tpu.obs.server import ObsServer
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd, FrontEndConfig

    out_dir = args.out or tempfile.mkdtemp(prefix="loadgen_prefix_")
    keep = args.keep or args.out is not None
    problems: list = []
    try:
        n_requests = args.requests
        spec = WorkloadSpec(
            seed=args.seed, prompt_lens=(448,), max_new_tokens=(8, 12),
            shared_prefix_len=440,
        )
        print(
            f"loadgen: PREFIX closed-loop, concurrency {args.concurrency}, "
            f"{n_requests} requests (prompt 448, shared prefix 440) -> {out_dir}"
        )
        model, params, config = build_prefix_workload()
        specs = spec.draw(n_requests, int(config.vocab_size))

        def engine_cfg(sharing: bool) -> EngineConfig:
            return EngineConfig(
                slots=3, page_size=8, max_ca_tokens=460, max_sa_tokens=20,
                prefix_sharing=sharing,
            )

        def warm_specs():
            # per-budget SHARED waves (not one lone request per geometry):
            # the shared-prefill program only compiles when a wave actually
            # shares, and warm residency must not leak into the measured
            # window — the waves drain fully, their run expires, and the
            # first measured request republishes (hit_rate = (N-1)/N)
            warm = []
            for j, m in enumerate(spec.max_new_tokens):
                ws = WorkloadSpec(
                    seed=args.seed + 9000 + j, prompt_lens=spec.prompt_lens,
                    max_new_tokens=(m,), shared_prefix_len=spec.shared_prefix_len,
                ).draw(3, int(config.vocab_size))
                warm += [dataclasses.replace(s, index=1_000_000 + 10 * j + k)
                         for k, s in enumerate(ws)]
            return warm

        # --- measured leg: sharing ON, fully instrumented -----------------
        events = EventLog(out_dir, main_process=True)
        manifest = write_run_manifest(
            out_dir, model_config=config,
            extra={"workload_spec": spec.to_dict(), "engine": True, "prefix": True},
            main_process=True,
        )
        recorder = FlightRecorder(
            events, out_dir=out_dir,
            slo=SLOBounds(ttft_s=args.ttft_slo, tpot_p99_s=args.tpot_slo),
        )
        registry = MetricsRegistry()
        fe = EngineFrontEnd(
            model, params, num_latents=8, engine_config=engine_cfg(True),
            config=FrontEndConfig(snapshot_interval_s=0.25),
            events=recorder, registry=registry,
        )
        warm = warm_specs()
        fe.run_closed(warm, concurrency=len(warm))
        n_warm = len(warm)
        registry.histogram("generate_tpot_s").reset()
        warm_steps, warm_fill = fe._engine_steps, fe._fill_sum
        hits0, pages0 = fe._n_prefix_hits, fe._n_prefix_pages_shared
        with ObsServer(registry=registry, run_dir=out_dir, health=fe.health) as server:
            t0 = _time.perf_counter()
            recs = fe.run_closed(specs, concurrency=args.concurrency)
            duration_s = _time.perf_counter() - t0
            metrics_text = _fetch(server.url + "/metrics")
            for counter in ("serve_prefix_hits_total", "serve_prefix_pages_shared"):
                if counter not in metrics_text:
                    problems.append(f"/metrics lacks the {counter} counter")
        hits = fe._n_prefix_hits - hits0
        pages_shared = fe._n_prefix_pages_shared - pages0

        problems += [f"engine books: {p}" for p in fe.audit()]
        problems += [f"sharing audit: {p}" for p in fe.sharing_audit()]
        if fe.ca_alloc.pages_used or fe.sa_alloc.pages_used:
            problems.append(
                f"pages leaked after drain: ca={fe.ca_alloc.pages_used} "
                f"sa={fe.sa_alloc.pages_used}"
            )
        if fe.prefix_index.pages():
            problems.append(
                f"prefix index names pages after drain: {fe.prefix_index.pages()}"
            )
        books = fe.books()
        if books["ok"] != n_requests + n_warm:
            problems.append(
                f"served {books['ok']}/{n_requests} (+{n_warm} warmup) ok: {books}"
            )

        # --- baseline leg: sharing OFF, same geometry, same workload ------
        base_reg = MetricsRegistry()
        fe_base = EngineFrontEnd(
            model, params, num_latents=8, engine_config=engine_cfg(False),
            registry=base_reg,
        )
        fe_base.run_closed(warm_specs(), concurrency=n_warm)
        base_reg.histogram("generate_tpot_s").reset()
        bt0 = _time.perf_counter()
        base_recs = fe_base.run_closed(specs, concurrency=args.concurrency)
        base_duration_s = _time.perf_counter() - bt0
        if fe_base._n_prefix_hits:
            problems.append(
                f"baseline leg shared anyway: {fe_base._n_prefix_hits} hits"
            )
        base_books = fe_base.books()
        if base_books["ok"] != n_requests + n_warm:
            problems.append(f"baseline leg not clean: {base_books}")

        # --- decode_shared consistency: the two legs are bit-exact --------
        diverged = [
            s.index for s in specs
            if fe.served_tokens.get(s.index) != fe_base.served_tokens.get(s.index)
        ]
        if diverged:
            problems.append(
                f"shared vs unshared token streams diverge for "
                f"{len(diverged)} requests (first: {diverged[:5]}) — "
                "prefix sharing must be exact, not approximate"
            )
        else:
            print(
                f"loadgen: decode_shared consistency — {n_requests} request "
                "token streams bit-exact across shared/unshared legs"
            )

        def to_records(raw):
            return [
                RequestRecord(
                    index=r.index, prompt_len=r.prompt_len,
                    max_new_tokens=r.max_new_tokens, batch=r.batch,
                    queue_wait_s=r.queue_wait_s or 0.0,
                    outcome="ok" if r.outcome == "ok" else "error",
                    compiled=r.compiled, ttft_s=r.ttft_s, decode_s=r.decode_s,
                    tokens_out=r.tokens_out,
                )
                for r in raw
            ]

        summary = summarize_load(
            to_records(recs), duration_s, registry=registry, mode="closed",
            concurrency=args.concurrency,
        )
        base_summary = summarize_load(
            to_records(base_recs), base_duration_s, registry=base_reg,
            mode="closed", concurrency=args.concurrency,
        )
        steps = fe._engine_steps - warm_steps
        cfg = engine_cfg(True)
        ratio = summary["ttft_s"]["p50"] / base_summary["ttft_s"]["p50"]
        summary["prefix"] = {
            "hit_rate": round(hits / n_requests, 6),
            "hits": hits,
            "pages_shared": pages_shared,
            "tokens_skipped": pages_shared * cfg.page_size,
            "ttft_p50_shared_s": summary["ttft_s"]["p50"],
            "ttft_p50_unshared_s": base_summary["ttft_s"]["p50"],
            "ttft_p50_ratio": round(ratio, 6),
            "baseline_throughput_tok_s": base_summary["throughput_tok_s"],
            "engine": {
                "slots": cfg.slots,
                "page_size": cfg.page_size,
                "decode_steps": steps,
                "batch_fill_frac": round(
                    (fe._fill_sum - warm_fill) / (steps * cfg.slots), 6
                ) if steps else 0.0,
            },
        }
        if events is not None:
            events.emit("load.summary", **summary)
            registry.maybe_emit(events, min_interval_s=0.0)
        print(
            f"loadgen: prefix leg served {summary['n_requests']} in "
            f"{summary['duration_s']:.2f}s — hit_rate "
            f"{summary['prefix']['hit_rate']}, ttft p50 "
            f"{summary['ttft_s']['p50'] * 1e3:.2f}ms shared vs "
            f"{base_summary['ttft_s']['p50'] * 1e3:.2f}ms unshared "
            f"(ratio {summary['prefix']['ttft_p50_ratio']})"
        )

        # --- stream validation: span-attributed serve.prefix_hit rows -----
        warnings_out: list = []
        problems += validate_events(out_dir, warnings_out=warnings_out)
        for w in warnings_out:
            print(f"loadgen: warning: {w}")
        from perceiver_io_tpu.obs.events import merged_events

        stream = merged_events(out_dir)
        hit_rows = [e for e in stream if e.get("event") == "serve.prefix_hit"]
        # warm waves hit too (2 waves x 2 sharers) — the stream carries both
        if len(hit_rows) != fe._n_prefix_hits:
            problems.append(
                f"{len(hit_rows)} serve.prefix_hit rows, want {fe._n_prefix_hits}"
            )
        if hit_rows and not all(e.get("span_id") for e in hit_rows):
            problems.append("serve.prefix_hit rows missing span attribution")
        if hit_rows and not all(
            0 < e["pages_matched"] <= e["pages_total"] for e in hit_rows
        ):
            problems.append("serve.prefix_hit rows with impossible page counts")

        doc = build_load_doc(
            args.round or _next_round(), summary, spec, manifest=manifest,
        )
        if "engine" in doc.get("summary", {}):
            problems.append(
                "prefix doc must not carry summary.engine (the engine-gate "
                "floors are calibrated on the c32 gate model)"
            )
        self_diff = diff_load(doc, doc)
        if not (self_diff["comparable"] and self_diff["ok"]):
            problems.append("run-vs-itself load diff NOT clean: "
                            + format_load_diff(self_diff))

        if args.write_artifact:
            floor_fails = check_doc_floors(doc)
            if floor_fails:
                problems += [f"refusing to write artifact: {f}" for f in floor_fails]
            else:
                path = os.path.join(_REPO, f"LOAD_r{doc['n']:02d}.json")
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                    f.write("\n")
                print(f"loadgen: wrote {path}")

        problems += check_load_floors()

        if problems:
            print("loadgen: prefix gate FAILED:")
            for p in problems:
                print(f"  - {p}")
            return 1
        print(
            "loadgen: prefix OK — "
            f"hit_rate {summary['prefix']['hit_rate']} at ttft ratio "
            f"{summary['prefix']['ttft_p50_ratio']} (legs bit-exact, "
            "refcounts balanced, index drained)"
        )
        return 0
    except Exception as e:  # noqa: BLE001 — CI must see crash != verdict
        print(f"loadgen: internal error: {e}", file=sys.stderr)
        import traceback

        traceback.print_exc()
        return 3
    finally:
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)


def run_fleet_gate(args) -> int:
    """The FLEET leg (``--fleet N``): the Fleetline certification round
    (docs/serving.md#fleet). A closed-loop run against N REAL engine
    replicas behind one ``FleetRouter`` submit surface, fully instrumented
    — flight recorder, ``/metrics`` with the labeled ``router_*`` series,
    ``/healthz`` answering from the FLEET health provider (one row per
    replica). Asserts:

    1. every request served ok fleet-wide, the fleet books identity
       closed (``Σ submitted == dispatched + re-admissions``, zero
       orphans), router audit clean, zero leaked pages on EVERY replica;
    2. the dispatch was a real fleet dispatch: every replica took a
       material share of the measured requests (>= 25% of fair share);
    3. the scrape surface answers fleet-wide: ``/healthz`` carries one
       row per replica with all dispatchable, ``/metrics`` exposes
       ``router_dispatch_total`` / ``router_outstanding``;
    4. the stream validates; the artifact body carries ``summary.fleet``
       and deliberately NOT ``summary.engine`` (the engine floors stay
       calibrated on the single-engine rounds), diffs clean against
       itself, and holds the ``fleet_throughput_tok_s`` ledger floor —
       >= 1.7x the single-engine LOAD_r02 floor.

    Single-host honesty: the N replicas interleave their decode steps on
    ONE host and ONE device here, so this round certifies the real
    routed fleet's absolute throughput and routing/accounting
    correctness — NOT parallel speedup, which one core cannot exhibit.
    The >= 1.7x replication-scaling claim itself is certified by the
    wall-clock-free discrete-event fleet gate (``tools/chaos.py
    sim_fleet``), where each replica owns an independent timeline; this
    leg's floor is beaten by amortization (one long-budget geometry,
    12 decode tokens per 8-token prompt, fewer join stalls per token)
    and the ``summary.fleet`` block records that provenance."""
    import time as _time

    from perceiver_io_tpu.obs.events import EventLog, validate_events, write_run_manifest
    from perceiver_io_tpu.obs.flightrec import FlightRecorder, SLOBounds
    from perceiver_io_tpu.obs.loadgen import (
        RequestRecord,
        WorkloadSpec,
        build_load_doc,
        diff_load,
        format_load_diff,
        summarize_load,
    )
    from perceiver_io_tpu.obs.metrics import MetricsRegistry
    from perceiver_io_tpu.obs.server import ObsServer
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd, FrontEndConfig
    from perceiver_io_tpu.serving.router import FleetRouter

    out_dir = args.out or tempfile.mkdtemp(prefix="loadgen_fleet_")
    keep = args.keep or args.out is not None
    problems: list = []
    try:
        n_replicas = args.fleet
        n_requests = args.requests
        # one long-budget geometry: joins amortize over 12 decode tokens
        # (vs the engine round's 6/10 mix), and a single compiled
        # (prompt, budget) pair keeps the warm wave minimal
        spec = WorkloadSpec(seed=args.seed, prompt_lens=(8,), max_new_tokens=(12,))
        engine_cfg = EngineConfig(
            slots=args.slots, page_size=8, max_ca_tokens=24, max_sa_tokens=16
        )
        concurrency = args.concurrency * n_replicas
        print(
            f"loadgen: FLEET closed-loop, {n_replicas} replicas "
            f"(slots {engine_cfg.slots} each), fleet concurrency {concurrency}, "
            f"{n_requests} requests -> {out_dir}"
        )
        model, params, config = build_workload()
        events = EventLog(out_dir, main_process=True)
        manifest = write_run_manifest(
            out_dir, model_config=config,
            extra={"workload_spec": spec.to_dict(), "engine": True,
                   "fleet": n_replicas},
            main_process=True,
        )
        recorder = FlightRecorder(
            events, out_dir=out_dir,
            slo=SLOBounds(ttft_s=args.ttft_slo, tpot_p99_s=args.tpot_slo),
        )
        registry = MetricsRegistry()
        router = FleetRouter(events=recorder, registry=registry)
        fes = {}
        for i in range(n_replicas):
            rid = f"r{i}"
            fes[rid] = EngineFrontEnd(
                model, params, num_latents=4, engine_config=engine_cfg,
                config=FrontEndConfig(snapshot_interval_s=0.25),
                events=recorder, registry=registry,
            )
            router.add_replica(rid, fes[rid])
        specs = spec.draw(n_requests, int(config.vocab_size))
        # warm THROUGH the router (not per-replica run_closed): the fleet
        # books identity counts every frontend submission against a router
        # dispatch, so a side-door warm request would unbalance it. An
        # idle fleet alternates submissions by the least-outstanding
        # tie-break, so 2 per replica lands every geometry on every one.
        warm = dataclasses_replace_indices(
            [
                WorkloadSpec(
                    seed=args.seed + 7777 + i, prompt_lens=(p,), max_new_tokens=(m,)
                ).draw(1, int(config.vocab_size))[0]
                for i, (p, m) in enumerate(
                    (p, m)
                    for p in spec.prompt_lens
                    for m in spec.max_new_tokens
                    for _ in range(2 * n_replicas)
                )
            ],
            base=1_000_000,
        )
        for w in warm:
            router.submit(w)
        router.pump()
        n_warm = len(warm)
        warm_share = {rid: fe.books()["submitted"] for rid, fe in fes.items()}
        if min(warm_share.values()) < 1:
            problems.append(f"a replica took no warm request: {warm_share}")
        # measured-window boundary (the engine-gate discipline): drop the
        # warm per-token samples and mark every per-replica odometer
        registry.histogram("generate_tpot_s").reset()
        warm_marks = {
            rid: (fe._engine_steps, fe._fill_sum) for rid, fe in fes.items()
        }
        registry.gauge("serve_parked_depth").reset_peak()
        with ObsServer(registry=registry, run_dir=out_dir, health=router.health) as server:
            t0 = _time.perf_counter()
            recs = router.run_closed(specs, concurrency=concurrency)
            duration_s = _time.perf_counter() - t0

            metrics_text = _fetch(server.url + "/metrics")
            for series in ("router_dispatch_total", "router_outstanding"):
                if series not in metrics_text:
                    problems.append(f"/metrics lacks the {series} series")
            health = json.loads(_fetch(server.url + "/healthz"))
            if health.get("n_replicas") != n_replicas:
                problems.append(f"/healthz not the fleet view: {health}")
            elif health.get("n_dispatchable") != n_replicas:
                problems.append(f"/healthz replicas not all dispatchable: {health}")

        books = router.books()
        problems += [f"fleet books: {p}" for p in router.audit()]
        for rid, fe in fes.items():
            if fe.ca_alloc.pages_used or fe.sa_alloc.pages_used:
                problems.append(
                    f"{rid} leaked pages: ca={fe.ca_alloc.pages_used} "
                    f"sa={fe.sa_alloc.pages_used}"
                )
            problems += [f"{rid} ca pages: {p}" for p in fe.ca_alloc.audit()]
            problems += [f"{rid} sa pages: {p}" for p in fe.sa_alloc.audit()]
        if books["outcomes"]["ok"] != n_requests + n_warm:
            problems.append(
                f"fleet served {books['outcomes']['ok']}/{n_requests} "
                f"(+{n_warm} warmup) ok: {books}"
            )
        if books["failovers"] != 0 or books["orphaned"] != 0:
            problems.append(f"clean run saw failovers/orphans: {books}")
        # real fleet dispatch: every replica took a material share
        measured_share = {
            rid: fes[rid].books()["submitted"] - warm_share[rid] for rid in fes
        }
        fair = n_requests / n_replicas
        for rid, share in measured_share.items():
            if share < 0.25 * fair:
                problems.append(
                    f"{rid} took {share}/{n_requests} measured requests "
                    f"(< 25% of fair share {fair:.0f}): not a fleet run"
                )

        records = [
            RequestRecord(
                index=r.index, prompt_len=r.prompt_len,
                max_new_tokens=r.max_new_tokens, batch=r.batch,
                queue_wait_s=r.queue_wait_s or 0.0,
                outcome="ok" if r.outcome == "ok" else "error",
                compiled=r.compiled, ttft_s=r.ttft_s, decode_s=r.decode_s,
                tokens_out=r.tokens_out,
            )
            for r in recs
        ]
        summary = summarize_load(
            records, duration_s, registry=registry, mode="closed",
            concurrency=concurrency,
        )
        per_replica = {}
        for rid, fe in fes.items():
            warm_steps, warm_fill = warm_marks[rid]
            steps = fe._engine_steps - warm_steps
            per_replica[rid] = {
                "dispatched": measured_share[rid],
                "decode_steps": steps,
                "batch_fill_frac": round(
                    (fe._fill_sum - warm_fill) / (steps * engine_cfg.slots), 6
                ) if steps else 0.0,
            }
        summary["fleet"] = {
            "n_replicas": n_replicas,
            "slots_per_replica": engine_cfg.slots,
            "dispatched": books["dispatched"],
            "requeued": books["requeued"],
            "failovers": books["failovers"],
            "replicas": per_replica,
            # provenance: this is a routed single-host run — the >=1.7x
            # replication-scaling claim is the DES gate's (sim_fleet)
            "drive": "interleaved_single_host",
            "scaling_certified_by": "tools/chaos.py sim_fleet",
        }
        if events is not None:
            events.emit("load.summary", **summary)
            registry.maybe_emit(events, min_interval_s=0.0)
        print(
            f"loadgen: fleet served {summary['n_requests']} requests in "
            f"{summary['duration_s']:.2f}s ({summary['throughput_tok_s']:.0f} "
            f"tok/s across {n_replicas} replicas, dispatch "
            f"{ {rid: v['dispatched'] for rid, v in sorted(per_replica.items())} })"
        )

        # --- stream validation: fleet lifecycle rows present --------------
        warnings_out: list = []
        problems += validate_events(out_dir, warnings_out=warnings_out)
        for w in warnings_out:
            print(f"loadgen: warning: {w}")
        from perceiver_io_tpu.obs.events import merged_events

        stream = merged_events(out_dir)
        joins = [e for e in stream if e.get("event") == "serve.replica"
                 and e.get("transition") == "join"]
        if len(joins) != n_replicas:
            problems.append(f"{len(joins)} serve.replica join rows, want {n_replicas}")
        req_rows = [e for e in stream if e.get("event") == "request"]
        if len(req_rows) != n_requests + n_warm:
            problems.append(
                f"{len(req_rows)} request rows, want {n_requests} + {n_warm} warmup"
            )

        doc = build_load_doc(
            args.round or _next_round(), summary, spec, manifest=manifest,
        )
        if "engine" in doc.get("summary", {}):
            problems.append(
                "fleet doc must not carry summary.engine (the engine-gate "
                "floors are calibrated on the single-engine rounds)"
            )
        self_diff = diff_load(doc, doc)
        if not (self_diff["comparable"] and self_diff["ok"]):
            problems.append("run-vs-itself load diff NOT clean: "
                            + format_load_diff(self_diff))

        if args.write_artifact:
            floor_fails = check_doc_floors(doc)
            if floor_fails:
                problems += [f"refusing to write artifact: {f}" for f in floor_fails]
            else:
                path = os.path.join(_REPO, f"LOAD_r{doc['n']:02d}.json")
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                    f.write("\n")
                print(f"loadgen: wrote {path}")

        problems += check_load_floors()

        if problems:
            print("loadgen: fleet gate FAILED:")
            for p in problems:
                print(f"  - {p}")
            return 1
        print(
            "loadgen: fleet OK — "
            f"{summary['throughput_tok_s']:.0f} tok/s at ok_rate "
            f"{summary['ok_rate']} across {n_replicas} replicas "
            "(fleet books balanced, dispatch shared, zero failovers)"
        )
        return 0
    except Exception as e:  # noqa: BLE001 — CI must see crash != verdict
        print(f"loadgen: internal error: {e}", file=sys.stderr)
        import traceback

        traceback.print_exc()
        return 3
    finally:
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)


def dataclasses_replace_indices(specs, base: int):
    """Re-index warmup specs far above the measured range so they can never
    collide with measured requests in per-index surfaces (served_tokens,
    injector targeting)."""
    import dataclasses

    return [dataclasses.replace(s, index=base + i) for i, s in enumerate(specs)]


def _next_round() -> int:
    rounds = [
        int(m.group(1))
        for p in glob.glob(os.path.join(_REPO, "LOAD_r*.json"))
        if (m := _ROUND_RE.search(p))
    ]
    return max(rounds) + 1 if rounds else 1


def _load_floors() -> dict:
    from perceiver_io_tpu.analysis.ledger import load_ledger

    ledger = load_ledger(os.path.join(_REPO, "contracts")) or {}
    return {
        name: floor
        for name, floor in ledger.get("floors", {}).items()
        if str(floor.get("artifact", "")).startswith("LOAD_")
    }


def check_doc_floors(doc: dict) -> list:
    """LOAD-floor failures of ONE candidate doc (before it is committed) —
    the write-side guard; :func:`check_load_floors` is the read-side gate
    over whatever is already on disk. Floors whose ``match`` clause the
    candidate does not satisfy are another mode's certification (an
    open-loop doc is not judged by the closed-loop throughput floor) and
    are skipped."""
    from perceiver_io_tpu.analysis.ledger import _dig, doc_matches

    failures = []
    for name, floor in _load_floors().items():
        if not doc_matches(doc, floor.get("match")):
            continue
        value = _dig(doc, floor["key"])
        if not isinstance(value, (int, float)):
            failures.append(f"{name}: {floor['key']} = {value!r} missing or non-numeric")
            continue
        if "min" in floor and value < floor["min"]:
            failures.append(f"{name}: {floor['key']} = {value!r} below floor {floor['min']}")
        if "max" in floor and value > floor["max"]:
            failures.append(f"{name}: {floor['key']} = {value!r} above ceiling {floor['max']}")
    return failures


def check_load_floors() -> list:
    """The ledger-floor hook: enforce every ``contracts/ledger.json`` floor
    whose artifact pattern targets LOAD_r*.json (latest round wins — the
    same machinery as the committed-bench floors). No LOAD floors, no
    committed artifact yet -> nothing to enforce."""
    from perceiver_io_tpu.analysis.ledger import check_bench_floors

    load_floors = _load_floors()
    if not load_floors:
        return []
    return check_bench_floors({"floors": load_floors}, _REPO)


def run_diff(args) -> int:
    from perceiver_io_tpu.obs.loadgen import LOAD_METRICS, diff_load, format_load_diff

    tolerances = {}
    for spec in args.tolerance:
        if "=" not in spec:
            print(f"--tolerance wants METRIC=TOL, got {spec!r}", file=sys.stderr)
            return 3
        k, v = spec.split("=", 1)
        if k not in LOAD_METRICS:
            print(f"unknown metric {k!r} (known: {', '.join(sorted(LOAD_METRICS))})",
                  file=sys.stderr)
            return 3
        tolerances[k] = float(v)
    with open(args.diff[0]) as f:
        old = json.load(f)
    with open(args.diff[1]) as f:
        new = json.load(f)
    diff = diff_load(old, new, tolerances)
    print(format_load_diff(diff))
    if not diff["comparable"]:
        return 2
    return 0 if diff["ok"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mode", choices=("closed", "open"), default="closed")
    p.add_argument("--requests", type=int, default=None,
                   help="request count (default: 200, or 24 with --smoke)")
    p.add_argument("--concurrency", type=int, default=None,
                   help="closed-loop inflight (default: 4, or 16 with --prefix)")
    p.add_argument("--rate", type=float, default=None, help="open-loop arrival rate (req/s)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="CI-fast gate: 24 requests, same assertions")
    p.add_argument("--engine", action="store_true",
                   help="drive the continuous-batching paged-KV engine "
                        "(serving.engine) instead of the sequential path; "
                        "includes a planted mid-decode kill with a clean-books "
                        "audit (default 400 requests, 24 with --smoke); "
                        "combine with --mode open --rate R for the open-loop "
                        "engine rate leg (LOAD_r03 / engine_open_achieved_rps)")
    p.add_argument("--prefix", action="store_true",
                   help="drive the Shareline prefix-sharing certification "
                        "(docs/serving.md#prefix-sharing): shared-prefix "
                        "closed loop on a wide model, sharing-on vs "
                        "sharing-off legs asserted bit-exact, summary.prefix "
                        "floors (hit rate, 0.5x TTFT ratio); default 200 "
                        "requests, 24 with --smoke")
    p.add_argument("--fleet", type=int, default=None, metavar="N",
                   help="drive N real engine replicas behind one FleetRouter "
                        "(docs/serving.md#fleet): closed-loop fleet round with "
                        "the fleet books identity, per-replica dispatch-share "
                        "and router_* scrape assertions, summary.fleet "
                        "artifact body (fleet_throughput_tok_s floor); "
                        "default 240 requests, 24 with --smoke")
    p.add_argument("--slots", type=int, default=8,
                   help="engine decode slots (batched step width)")
    p.add_argument("--out", default=None, help="run dir (default: a temp dir)")
    p.add_argument("--keep", action="store_true", help="keep the run dir (implied by --out)")
    p.add_argument("--write-artifact", action="store_true",
                   help="write/refresh LOAD_r<round>.json at the repo root")
    p.add_argument("--round", type=int, default=None,
                   help="artifact round number (default: next free)")
    p.add_argument("--ttft-slo", type=float, default=30.0,
                   help="standing flight-recorder TTFT bound (s)")
    p.add_argument("--tpot-slo", type=float, default=30.0,
                   help="standing flight-recorder TPOT-p99 bound (s)")
    p.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"),
                   help="diff two LOAD_r*.json artifacts instead of running")
    p.add_argument("--tolerance", action="append", default=[], metavar="METRIC=TOL")
    args = p.parse_args(argv)
    if args.diff:
        return run_diff(args)
    if args.requests is None:
        args.requests = 24 if args.smoke else (
            240 if args.fleet else (400 if args.engine else 200)
        )
    if args.concurrency is None:
        # the prefix leg wants the admission queue never empty: a drain gap
        # drops the shared run's last refcount, expires the index, and the
        # next arrival republishes instead of sharing; the fleet leg
        # multiplies per-replica depth by N, so it wants the single-engine
        # saturation depth (LOAD_r02's 16) per replica
        args.concurrency = 16 if (args.prefix or args.fleet) else 4
    if args.mode == "open" and not args.rate:
        p.error("--mode open needs --rate")
    if args.fleet is not None:
        if args.fleet < 2:
            p.error("--fleet needs N >= 2 (one replica is the --engine leg)")
        if args.mode == "open" or args.prefix or args.engine:
            p.error("--fleet is its own closed-loop certification")
        return run_fleet_gate(args)
    if args.prefix:
        if args.mode == "open":
            p.error("--prefix is a closed-loop certification")
        return run_prefix_gate(args)
    if args.engine:
        return run_engine_gate(args)
    return run_gate(args)


if __name__ == "__main__":
    sys.exit(main())
