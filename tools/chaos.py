#!/usr/bin/env python
"""Chaos harness — deterministic fault injection against the train loop.

Certifies the faults subsystem (training/faults.py, docs/robustness.md) the
same way dryrun_multichip certifies sharding: by RUNNING the failure and
asserting recovery, not by unit-testing pieces. ``python tasks.py chaos`` is
the gate. Scenarios:

- ``preempt``       — a REAL SIGTERM mid-fit: the trainer saves at the step
                      boundary and returns; a fresh trainer with
                      ``resume="auto"`` fast-forwards the data stream and the
                      combined loss trajectory matches the uninterrupted run
                      to <= 1e-6.
- ``preempt_mesh``  — the same kill/resume cycle under a {data:2, fsdp:4}
                      mesh (8 virtual CPU devices; the harness respawns
                      itself like dryrun_multichip), so auto-resume is
                      certified against ``shard_train_state`` placements.
- ``fetch_error``   — transient loader fetch failures at a chosen step are
                      absorbed by ``Batches(retry=RetryPolicy(...))``: the
                      trajectory is IDENTICAL to the fault-free run.
- ``nan_skip``      — a single NaN batch trips the in-graph sentinel skip:
                      params hold, step advances, one ``fault.skip`` event.
- ``nan_rollback``  — persistent NaN batches escalate past ``skip_limit``
                      into rollback-to-last-checkpoint; the run completes
                      with finite loss and a ``fault.rollback`` event.
- ``torn_save``     — a checkpoint step dir torn post-commit is quarantined;
                      ``restore`` falls back to the previous good step and
                      never selects the torn one.

Elastic-resume scenarios (docs/robustness.md#elastic-resume) — the pod
comes back with a DIFFERENT shape. Each runs its kill and resume halves in
separate subprocesses with different virtual-device counts (the only honest
way to change topology), sharing the checkpoint dir; the combined loss
trajectory must match the uninterrupted reference <= 1e-6, the restore must
emit a span-attributed ``resume.reshard`` event with the right old/new
meshes, and the resumed train step must lint clean on the new mesh:

- ``elastic_shrink`` — kill under {data:2, fsdp:4} on 8 devices, resume
                       under {data:2, fsdp:2} on 4 (preempted pod-slice
                       downsize).
- ``elastic_grow``   — kill under {data:2, fsdp:2} on 4, resume under
                       {data:2, fsdp:4} on 8 (mid-run scale-up).
- ``flat_to_mesh``   — kill unsharded on 1 device, resume under
                       {data:2, fsdp:2} on 4 (single-host prototype moved
                       onto a pod).
- ``mesh_to_flat``   — kill under {data:2, fsdp:2} on 4, resume unsharded
                       on 1 (pod gone; limp home on one chip).

Serving scenarios (Shedline, perceiver_io_tpu/serving,
docs/robustness.md#serving-hardening) — the hardened front end under
injected serving failures, all wall-clock-free on a ``ManualClock``; every
scenario closes with a clean-books audit (every submitted request at
exactly one terminal outcome, zero leaked worker slots):

- ``serve_overload``        — open-loop arrivals outpace an injected 100 ms
                              service time: admission sheds (first-class
                              ``shed`` events, never silent), queue depth
                              stays bounded, warm TTFT p99 of ADMITTED
                              requests holds the declared SLO, and
                              ``/healthz``+``/slo`` report it all live.
- ``serve_kill_mid_decode`` — a request dies between tokens: books close
                              (``error``), the slot comes back, exactly one
                              flight dump names the dead request's span.
- ``serve_deadline``        — an injected stall blows a deadline
                              mid-decode: the ``on_token`` seam cancels,
                              the ``timeout`` event carries the partial
                              TTFT/TPOT, one ``timeout`` dump names it.
- ``serve_drain``           — a REAL SIGTERM mid-run: admission stops
                              (late submissions shed ``draining``), queued
                              work finishes, ``serve.drain`` carries the
                              balanced final books.
- ``serve_breaker``         — consecutive injected errors open the circuit
                              breaker (shed ``breaker_open``, one
                              ``breaker`` dump); the RetryPolicy-spaced
                              half-open probe closes it on the manual clock.
- ``serve_spec_kill_mid_span`` — Specline: a kill lands MID-SPAN inside the
                              speculative engine (a verify step emits
                              m ∈ [1, k+1] tokens; the per-token seam fires
                              for each): the slot retires at the killed
                              token, span remainder dropped, pages freed,
                              books balanced, acceptance telemetry on every
                              event row, one dump names the dead span.
- ``serve_evict_storm``     — Evictline: a page pool sized BELOW the live
                              demand forces real page-pressure evictions;
                              every fit-able request still reaches ``ok``
                              (zero ``kv_pages_exhausted`` sheds), resumed
                              streams are token-exact vs the uninterrupted
                              sequential reference (greedy AND temperature),
                              the extended books identity (``submitted ==
                              terminal + queued + in_flight + parked``)
                              closes, and every ``serve.evict``/
                              ``serve.resume`` event is span-attributed.
- ``serve_crash_recover``   — Evictline: the ENGINE dies mid-decode (an
                              injected ``EngineCrash`` no accounting seam
                              catches — the SIGKILL analog); a second
                              engine recovers every non-terminal request
                              from the write-ahead journal and serves it
                              token-exactly; the combined books balance
                              ACROSS the restart (journal ``submitted ==
                              terminal``), span-attributed
                              ``serve.recover`` events name each
                              re-admission.

Fleetline scenarios (serving/router.py — N engine replicas behind one
``FleetRouter`` submit surface, docs/serving.md#fleet):

- ``serve_fleet_failover`` — a REPLICA dies mid-decode (an injected
                              ``EngineCrash`` at a replica-step
                              coordinate): the router replays its
                              write-ahead journal onto the survivor,
                              which finishes every journaled request
                              token-exactly; the FLEET books balance
                              across the handoff (every index exactly
                              one terminal outcome, zero double-served
                              tokens), the dead journal closes with
                              handoff markers, and exactly one flight
                              dump names the dead replica.
- ``serve_fleet_brownout``  — one replica browns out (injected service-
                              time inflation): the EWMA health check
                              flips it ``degraded`` and least-outstanding
                              dispatch drains traffic onto the healthy
                              replica while the slow one STAYS in the
                              fleet — no failover, books balanced.
- ``serve_fleet_drain``     — a mid-run graceful drain: dispatch to the
                              draining replica stops (post-drain
                              submissions land only on the survivor),
                              its outstanding work finishes, and ZERO
                              sheds are attributable to the drain.

Simline scenarios (serving/sim.py — the REAL engine control plane under a
ManualClock with sampled service times; no jax, no model,
docs/serving.md#multi-tenant-telemetry):

- ``sim_tenant_storm``      — one tenant floods at 10x each victim's rate,
                              far over join capacity: admission degrades
                              PROPORTIONALLY (demand-normalized Jain >=
                              0.9, neither victim starves), every shed is
                              a tenant-stamped first-class row, books
                              balance at the full offered scale.
- ``sim_noisy_neighbor``    — a long-budget bulk tenant forces REAL
                              Evictline evictions on a half-size page
                              pool shared with a latency tenant: both
                              tenants fully served, and per-tenant
                              ``SLOBounds`` prove isolation — the latency
                              tenant's planted TTFT bound trips flight
                              dumps naming ONLY its rows while the bulk
                              tenant's generous bound never fires.
- ``sim_fleet``             — Fleetline scale certification: the SAME
                              10k-req/s merged workload through 1 then 2
                              replicas on the discrete-event fleet loop
                              (per-replica clocks, causal next-event
                              drive); 2 replicas must deliver >= 1.7x
                              the token throughput with the committed
                              ``sim_fairness_jain``/``sim_starvation_age_s``
                              floors held on BOTH runs.

``--scenarios`` accepts fnmatch globs: ``--scenarios 'serve_*'`` runs the
serving family standalone, ``--scenarios 'elastic_*,preempt'`` composes.
``--smoke`` shrinks the Evictline scenarios (greedy-only, fewer requests)
for the ``tasks.py perf`` CI leg; assertions are identical.

Every injection is count-/step-deterministic (no wall-clock, no randomness
outside seeded generators), so failures reproduce exactly.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import re
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TOL = 1e-6


# ---------------------------------------------------------------------------
# fixture: a tiny linear-regression step — compiles in milliseconds, losses
# are deterministic functions of (seed, step), and the parameter is large
# enough ((8, 4) floats) for fsdp to actually shard it under min_weight_size=0
# ---------------------------------------------------------------------------


def _loss_fn():
    import jax.numpy as jnp

    from perceiver_io_tpu.obs.probes import probe

    def loss_fn(params, batch, rng):
        # Probeline tap: when the trainer runs probed (the sentinel
        # scenarios), the prediction's numerics stats ride out of the step —
        # a NaN input batch makes "chaos.pred" the FIRST non-finite scope,
        # which the blast-radius report must name
        pred = probe("chaos.pred", batch["x"] @ params["w"])
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, {"loss": loss}

    return loss_fn


def _fresh_state():
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.training import TrainState, make_optimizer

    tx = make_optimizer(1e-2)
    return TrainState.create(None, {"w": jnp.zeros((8, 4))}, tx, jax.random.PRNGKey(0))


def _batches(seed=0, batch_size=8, poison_at=()):
    """Infinite deterministic batch stream; ``poison_at`` (1-based fetch
    indices) yields batches with NaN inputs — the NaN-grad injection."""
    import numpy as np

    rng = np.random.default_rng(seed)
    for i in itertools.count(1):
        x = rng.normal(size=(batch_size, 8)).astype(np.float32)
        y = (x @ np.ones((8, 4))).astype(np.float32)
        if i in poison_at:
            x = x.copy()
            x[0, 0] = np.nan
        yield {"x": x, "y": y}


def _make_trainer(run_dir, max_steps, mesh=None, sentinel=False, **cfg_kw):
    from perceiver_io_tpu.training import MetricsLogger, Trainer, TrainerConfig

    cfg_kw.setdefault("graphlint", False)
    config = TrainerConfig(
        max_steps=max_steps,
        log_interval=1,
        checkpoint_dir=os.path.join(run_dir, "ckpt"),
        prefetch_batches=0,
        input_double_buffer=False,
        sentinel=sentinel,
        # sentinel scenarios run PROBED: a trip must produce a span-
        # attributed blast-radius report naming the planted scope
        probes=bool(sentinel),
        fsdp_min_weight_size=0,
        **cfg_kw,
    )
    logger = MetricsLogger(os.path.join(run_dir, "logs"), use_tensorboard=False)
    return Trainer(_loss_fn(), mesh=mesh, config=config, logger=logger)


def _record_losses(trainer, hook=None):
    """Wrap the trainer's step to host-fetch each loss (and optionally run a
    per-step injection hook)."""
    losses = []
    orig = trainer._train_step

    def wrapped(state, batch, _orig=orig):
        state, metrics = _orig(state, batch)
        losses.append(float(metrics["loss"]))
        if hook is not None:
            hook(trainer, state, metrics)
        return state, metrics

    trainer._train_step = wrapped
    return losses


def _assert_trajectories_match(ref, got, what):
    assert len(got) == len(ref), f"{what}: {len(got)} losses vs reference {len(ref)}"
    worst = max(abs(a - b) for a, b in zip(ref, got))
    assert worst <= TOL, f"{what}: trajectory diverged, max |d_loss| = {worst:.3e}"
    return worst


def _events(run_dir, kind):
    path = os.path.join(run_dir, "logs", "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(l) for l in f if json.loads(l).get("event") == kind]


def _assert_span_attributed(run_dir):
    """Spanline contract (ISSUE 8, extended by Evictline, Shareline and
    Fleetline): every fault.*/resume — and every per-request preemption,
    sharing or fleet-handoff event (``serve.evict``/``serve.resume``/
    ``serve.recover``/``serve.prefix_hit``/``serve.failover``) — in a
    chaos run must carry a span_id whose span row is in the same stream:
    an incident nobody can attribute to its step/request is an incident
    half-logged. Accepts both layouts (training runs log under ``logs/``,
    serving scenarios at the run dir root)."""
    path = os.path.join(run_dir, "logs", "events.jsonl")
    if not os.path.exists(path):
        path = os.path.join(run_dir, "events.jsonl")
    with open(path) as f:
        rows = [json.loads(l) for l in f if l.strip()]
    span_ids = {r.get("span_id") for r in rows if r.get("event") == "span"}
    audited = [
        r for r in rows
        if r.get("event", "").startswith("fault.")
        or r.get("event") in ("resume", "resume.reshard", "probe.blast",
                              "serve.evict", "serve.resume", "serve.recover",
                              "serve.prefix_hit", "serve.failover")
    ]
    for r in audited:
        assert r.get("span_id") in span_ids, (
            f"{r['event']} event not attributable to a span in-stream: {r}"
        )
    return len(audited)


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def scenario_preempt(tmp, mesh=None, tag="preempt"):
    """Kill-at-step-N via a real SIGTERM; auto-resume must reproduce the
    uninterrupted run's loss trajectory."""
    n_steps, kill_at = 12, 5
    ref_dir = os.path.join(tmp, f"{tag}_ref")
    tr = _make_trainer(ref_dir, n_steps, mesh=mesh)
    ref = _record_losses(tr)
    tr.fit(_fresh_state(), _batches())
    tr.close()

    run_dir = os.path.join(tmp, f"{tag}_run")
    t1 = _make_trainer(run_dir, n_steps, mesh=mesh)

    def kill(trainer, state, metrics):
        if int(state.step) == kill_at:
            # the real signal path: SIGTERM -> PreemptionGuard -> flag; the
            # loop notices at the next step boundary and saves
            os.kill(os.getpid(), signal.SIGTERM)

    part1 = _record_losses(t1, hook=kill)
    out1 = t1.fit(_fresh_state(), _batches())
    t1.close()
    assert int(out1.step) == kill_at, f"expected stop at {kill_at}, got {int(out1.step)}"
    assert _events(run_dir, "fault.preempt"), "no fault.preempt event emitted"

    t2 = _make_trainer(run_dir, n_steps, mesh=mesh)
    part2 = _record_losses(t2)
    out2 = t2.fit(_fresh_state(), _batches(), resume="auto")
    t2.close()
    assert int(out2.step) == n_steps
    ev = _events(run_dir, "resume")
    assert ev and ev[-1]["to_step"] == kill_at and ev[-1]["fast_forward_batches"] == kill_at
    worst = _assert_trajectories_match(ref, part1 + part2, tag)
    # no partial step dir may survive anywhere a restore could see it
    ckpt = os.path.join(run_dir, "ckpt")
    leftovers = [n for n in os.listdir(ckpt) if ".orbax-checkpoint-tmp" in n]
    assert not leftovers, f"tmp checkpoint leftovers: {leftovers}"
    n_attr = _assert_span_attributed(run_dir)
    print(f"chaos: {tag} ok — killed at {kill_at}, resumed, "
          f"{len(ref)} losses match <= {TOL:g} (worst {worst:.1e}), "
          f"{n_attr} fault/resume events span-attributed")


def scenario_preempt_mesh(tmp):
    """scenario_preempt under a {data:2, fsdp:4} mesh — certifies resume
    against shard_train_state placements (needs 8 devices; the entrypoint
    respawns with virtual CPU devices when short)."""
    import jax

    from perceiver_io_tpu.parallel import make_mesh

    assert len(jax.devices()) >= 8, "preempt_mesh needs 8 devices (respawn failed?)"
    mesh = make_mesh(devices=jax.devices()[:8], data=2, fsdp=4)
    scenario_preempt(tmp, mesh=mesh, tag="preempt_mesh")


def scenario_fetch_error(tmp):
    """Transient fetch errors at step N are retried with backoff inside the
    loader — the trajectory is identical to the fault-free run."""
    import numpy as np

    from perceiver_io_tpu.data.loader import Batches
    from perceiver_io_tpu.training.faults import RetryPolicy

    n_steps, fail_at_step, batch_size = 10, 4, 8

    class Dataset:
        def __init__(self, flaky=False):
            rng = np.random.default_rng(0)
            self.x = rng.normal(size=(n_steps * batch_size, 8)).astype(np.float32)
            self.flaky = flaky
            self.failures_left = 2 if flaky else 0
            self.fail_index = (fail_at_step - 1) * batch_size  # first fetch of step N
            self.retries_seen = 0

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            if self.flaky and i == self.fail_index and self.failures_left > 0:
                self.failures_left -= 1
                raise OSError("injected transient fetch failure")
            return {"x": self.x[i], "y": self.x[i] @ np.ones((8, 4), np.float32)}

    def run(flaky):
        tag = "flaky" if flaky else "clean"
        ds = Dataset(flaky=flaky)
        retries = []
        loader = Batches(
            ds, batch_size,
            retry=RetryPolicy(max_retries=3, base_delay=0.001, max_delay=0.002),
            on_retry=lambda a, e, d: retries.append((a, round(d, 6))),
        )
        tr = _make_trainer(os.path.join(tmp, f"fetch_{tag}"), n_steps)
        losses = _record_losses(tr)
        tr.fit(_fresh_state(), loader)
        tr.close()
        return losses, retries

    ref, _ = run(flaky=False)
    got, retries = run(flaky=True)
    assert len(retries) == 2, f"expected 2 retries, saw {retries}"
    worst = _assert_trajectories_match(ref, got, "fetch_error")
    print(f"chaos: fetch_error ok — 2 transient failures retried "
          f"(backoff {[d for _, d in retries]}), trajectory identical (worst {worst:.1e})")


def scenario_nan_skip(tmp):
    """One poison batch => one in-graph sentinel skip: params hold across the
    skipped step, the run completes, exactly one fault.skip event."""
    import numpy as np

    n_steps, poison_fetch = 10, 4
    run_dir = os.path.join(tmp, "nan_skip")
    tr = _make_trainer(run_dir, n_steps, sentinel=True)
    snapshots = []

    def snap(trainer, state, metrics):
        w = np.asarray(state.params["w"])
        snapshots.append((int(state.step), float(metrics["loss"]), w.copy()))

    losses = _record_losses(tr, hook=snap)
    tr.fit(_fresh_state(), _batches(poison_at=(poison_fetch,)))
    tr.close()
    assert len(losses) == n_steps
    skip_events = _events(run_dir, "fault.skip")
    assert len(skip_events) == 1 and skip_events[0]["step"] == poison_fetch, skip_events
    # params across the skipped step: unchanged (post-step-3 == post-step-4)
    w_before = snapshots[poison_fetch - 2][2]
    w_at = snapshots[poison_fetch - 1][2]
    assert np.array_equal(w_before, w_at), "skip did not hold params"
    assert not np.isnan(losses[poison_fetch:]).any(), "NaN leaked past the skip"
    blasts = _events(run_dir, "probe.blast")
    assert blasts and blasts[0]["scope"] == "chaos.pred" and blasts[0]["trigger"] == "skip", (
        f"skip not blast-attributed to the planted scope: {blasts}"
    )
    _assert_span_attributed(run_dir)
    print(f"chaos: nan_skip ok — poison batch at step {poison_fetch} skipped in-graph, "
          f"params held, blast named {blasts[0]['scope']!r}, final loss {losses[-1]:.4f} finite")


def scenario_nan_rollback(tmp):
    """Persistent NaN batches exhaust skip_limit and trip a rollback to the
    last checkpoint; the run then completes with finite loss."""
    import numpy as np

    from perceiver_io_tpu.training.faults import SentinelConfig

    n_steps = 12
    run_dir = os.path.join(tmp, "nan_rollback")
    tr = _make_trainer(
        run_dir, n_steps,
        sentinel=SentinelConfig(skip_limit=2, rollback_limit=2),
        val_interval=4,
    )
    losses = _record_losses(tr)
    # checkpoint lands at step 4 (val_interval); fetches 6+7 are poison —
    # two consecutive skips hit skip_limit=2 => rollback to step 4. The
    # injection is FETCH-indexed, so the replayed interval gets clean data.
    tr.fit(_fresh_state(), _batches(poison_at=(6, 7)), val_loader=[next(_batches(seed=9))])
    tr.close()
    rb = _events(run_dir, "fault.rollback")
    assert len(rb) == 1, f"expected 1 rollback, got {rb}"
    assert rb[0]["from_step"] == 7 and rb[0]["to_step"] == 4, rb
    finite = [l for l in losses if np.isfinite(l)]
    assert np.isfinite(losses[-1]) and len(finite) >= n_steps, "run did not recover"
    # Probeline blast radius (ISSUE 9): the trip must be ATTRIBUTED — a
    # probe.blast event naming the planted non-finite scope ("chaos.pred"
    # is the first probe in topological order; the NaN enters there), tied
    # to the offending step's span like every other fault event
    blasts = _events(run_dir, "probe.blast")
    assert blasts, "no probe.blast event despite a probed sentinel rollback"
    assert any(b.get("scope") == "chaos.pred" for b in blasts), (
        f"blast reports name {[b.get('scope') for b in blasts]}, "
        "expected the planted scope 'chaos.pred'"
    )
    assert all(b.get("trigger") in ("skip", "rollback", "halt") for b in blasts), blasts
    _assert_span_attributed(run_dir)
    print(f"chaos: nan_rollback ok — skip_limit tripped at step 7, rolled back to 4, "
          f"blast named {blasts[0]['scope']!r} (radius {blasts[0]['n_affected']}), "
          f"run completed with final loss {losses[-1]:.4f}")


def scenario_torn_save(tmp):
    """A torn (post-commit mutilated) step dir is quarantined and never
    selectable by restore/latest_step."""
    import shutil

    from perceiver_io_tpu.training.checkpoint import QUARANTINE_DIR, CheckpointManager

    ckpt = os.path.join(tmp, "torn", "ckpt")
    m = CheckpointManager(ckpt, max_to_keep=3, monitor="val_loss")
    s = _fresh_state()
    m.save(s.replace(step=s.step + 1), metrics={"val_loss": 1.0})
    s2 = s.replace(step=s.step + 2)
    m.save(s2, metrics={"val_loss": 0.5})
    m.close()
    # tear the newest step: drop its payload directory post-commit
    shutil.rmtree(os.path.join(ckpt, "2", "default"))

    m2 = CheckpointManager(ckpt, max_to_keep=3, monitor="val_loss")
    assert m2.latest_step() == 1, f"torn step selectable: latest={m2.latest_step()}"
    restored = m2.restore(_fresh_state())
    assert int(restored.step) == 1
    qdir = os.path.join(ckpt, QUARANTINE_DIR)
    assert os.path.isdir(qdir) and any(n.startswith("2") for n in os.listdir(qdir))
    m2.close()
    print("chaos: torn_save ok — mutilated step 2 quarantined, restore fell back to step 1")


# ---------------------------------------------------------------------------
# elastic resume: kill under one mesh/device-count, resume under another
# ---------------------------------------------------------------------------

# tag -> (kill mesh shape or None=flat, kill devices, resume shape, resume devices)
ELASTIC_SCENARIOS = {
    "elastic_shrink": (dict(data=2, fsdp=4), 8, dict(data=2, fsdp=2), 4),
    "elastic_grow": (dict(data=2, fsdp=2), 4, dict(data=2, fsdp=4), 8),
    "flat_to_mesh": (None, 1, dict(data=2, fsdp=2), 4),
    "mesh_to_flat": (dict(data=2, fsdp=2), 4, None, 1),
}


def _mesh_or_none(shape):
    if shape is None:
        return None
    import jax

    from perceiver_io_tpu.parallel import make_mesh

    need = 1
    for v in shape.values():
        need *= v
    assert len(jax.devices()) >= need, (
        f"mesh {shape} needs {need} devices, have {len(jax.devices())} (respawn failed?)"
    )
    return make_mesh(devices=jax.devices()[:need], **shape)


def _mesh_desc(mesh_axes):
    """Non-trivial axes of a fingerprint mesh dict ({} for flat/None)."""
    return {k: v for k, v in (mesh_axes or {}).items() if int(v) > 1}


def _elastic(tmp, tag, phase):
    """One mesh-elastic kill/resume cycle. ``phase=None`` orchestrates: the
    kill half (reference run + SIGTERM-at-step-5 run, both under the OLD
    mesh) and the resume half (``resume="auto"`` under the NEW mesh) each
    run in their own subprocess with that mesh's device count — a real
    topology change, not a same-process mesh swap. The resume phase does
    the asserting: combined trajectory == reference <= 1e-6, a
    span-attributed ``resume.reshard`` with the right old/new meshes, and
    a clean graphlint/graphcheck verdict on the resumed step."""
    kill_shape, kill_devices, resume_shape, resume_devices = ELASTIC_SCENARIOS[tag]
    n_steps, kill_at = 12, 5
    base = os.path.join(tmp, tag)

    if phase == "kill":
        mesh = _mesh_or_none(kill_shape)
        # uninterrupted reference under the ORIGINAL mesh — the trajectory
        # the kill+resume cycle must reproduce
        tr = _make_trainer(os.path.join(base, "ref"), n_steps, mesh=mesh)
        ref = _record_losses(tr)
        tr.fit(_fresh_state(), _batches())
        tr.close()

        t1 = _make_trainer(os.path.join(base, "run"), n_steps, mesh=mesh)

        def kill(trainer, state, metrics):
            if int(state.step) == kill_at:
                os.kill(os.getpid(), signal.SIGTERM)

        part1 = _record_losses(t1, hook=kill)
        out1 = t1.fit(_fresh_state(), _batches())
        t1.close()
        assert int(out1.step) == kill_at, f"{tag}: stopped at {int(out1.step)}, not {kill_at}"
        assert _events(os.path.join(base, "run"), "fault.preempt"), "no fault.preempt event"
        with open(os.path.join(base, "phase1.json"), "w") as f:
            json.dump({"ref": ref, "part1": part1}, f)
        return

    if phase == "resume":
        mesh = _mesh_or_none(resume_shape)
        with open(os.path.join(base, "phase1.json")) as f:
            d = json.load(f)
        run_dir = os.path.join(base, "run")
        # graphlint ON: the resumed step must lint clean ON THE NEW MESH
        t2 = _make_trainer(run_dir, n_steps, mesh=mesh, graphlint=True)
        part2 = _record_losses(t2)
        out2 = t2.fit(_fresh_state(), _batches(), resume="auto")
        t2.close()
        assert int(out2.step) == n_steps
        worst = _assert_trajectories_match(d["ref"], d["part1"] + part2, tag)

        ev = _events(run_dir, "resume")
        assert ev and ev[-1]["to_step"] == kill_at, ev
        assert ev[-1]["fast_forward_batches"] == kill_at, ev
        rr = _events(run_dir, "resume.reshard")
        assert rr, f"{tag}: no resume.reshard event despite a mesh change"
        r = rr[-1]
        assert r["step"] == kill_at, r
        assert _mesh_desc(r["old_mesh"]) == (kill_shape or {}), (
            f"{tag}: reshard old_mesh {r['old_mesh']} != killed mesh {kill_shape}"
        )
        assert _mesh_desc(r["new_mesh"]) == (resume_shape or {}), (
            f"{tag}: reshard new_mesh {r['new_mesh']} != resume mesh {resume_shape}"
        )
        assert r.get("leaves_resharded", 0) > 0 and r.get("bytes_moved", 0) > 0, r
        gl = _events(run_dir, "graphlint")
        assert gl and gl[-1].get("ok") is True and "error" not in gl[-1], (
            f"{tag}: resumed step failed graphlint on the new mesh: {gl}"
        )
        gc = _events(run_dir, "graphcheck")
        assert gc and "error" not in gc[-1], (
            f"{tag}: resumed step failed graphcheck fingerprinting: {gc}"
        )
        n_attr = _assert_span_attributed(run_dir)
        with open(os.path.join(base, "result.json"), "w") as f:
            json.dump(
                {"worst": worst, "reshard": r, "span_attributed": n_attr}, f
            )
        print(
            f"chaos: {tag} resume phase ok — mesh {_mesh_desc(r['old_mesh']) or 'flat'}"
            f" -> {_mesh_desc(r['new_mesh']) or 'flat'}, "
            f"{r['leaves_resharded']} leaves / {r['bytes_moved']}B resharded in "
            f"{r['wall_s']:.3f}s, trajectory worst {worst:.1e}, "
            f"{n_attr} events span-attributed, graphlint clean"
        )
        return

    # orchestrator: two subprocesses, two topologies, one checkpoint dir
    os.makedirs(base, exist_ok=True)
    rc = _respawn([tag], n_devices=kill_devices, phase="kill", tmp=tmp)
    assert rc == 0, f"{tag}: kill phase failed (rc={rc})"
    rc = _respawn([tag], n_devices=resume_devices, phase="resume", tmp=tmp)
    assert rc == 0, f"{tag}: resume phase failed (rc={rc})"
    with open(os.path.join(base, "result.json")) as f:
        result = json.load(f)
    print(
        f"chaos: {tag} ok — killed at step {kill_at} on {kill_devices} device(s), "
        f"resumed on {resume_devices}, {len(result['reshard'])}-field reshard event, "
        f"12 losses match <= {TOL:g} (worst {result['worst']:.1e})"
    )


def scenario_elastic_shrink(tmp, phase=None):
    _elastic(tmp, "elastic_shrink", phase)


def scenario_elastic_grow(tmp, phase=None):
    _elastic(tmp, "elastic_grow", phase)


def scenario_flat_to_mesh(tmp, phase=None):
    _elastic(tmp, "flat_to_mesh", phase)


def scenario_mesh_to_flat(tmp, phase=None):
    _elastic(tmp, "mesh_to_flat", phase)


# ---------------------------------------------------------------------------
# serving scenarios (Shedline): the hardened front end under injected
# serving failures — deterministic on a ManualClock, clean books certified
# ---------------------------------------------------------------------------

_SERVE_MODEL = {}


def _serving_model():
    """The serve_* scenarios run THE SAME tiny gate model as `tasks.py
    load` (tools/loadgen.py ``build_workload`` — one definition, so a
    geometry tweak there cannot desynchronize the two gates); cached per
    process."""
    if not _SERVE_MODEL:
        import importlib.util

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "loadgen_cli", os.path.join(repo, "tools", "loadgen.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        model, params, _config = mod.build_workload()
        _SERVE_MODEL.update(model=model, params=params)
    return _SERVE_MODEL["model"], _SERVE_MODEL["params"]


def _serve_env(tmp, tag, slo_ttft=None):
    """``(recorder, clock, run_dir)`` for one scenario — the recorder IS
    the event sink (it wraps a fresh EventLog over ``run_dir``)."""
    from perceiver_io_tpu.obs.events import EventLog
    from perceiver_io_tpu.obs.flightrec import FlightRecorder, SLOBounds
    from perceiver_io_tpu.serving import ManualClock

    run_dir = os.path.join(tmp, tag)
    events = EventLog(run_dir, main_process=True)
    recorder = FlightRecorder(events, out_dir=run_dir, slo=SLOBounds(ttft_s=slo_ttft))
    return recorder, ManualClock(), run_dir


def _serve_spec():
    from perceiver_io_tpu.obs.loadgen import WorkloadSpec

    # one compiled geometry (prompt 10, 4 new tokens): the scenarios certify
    # accounting, not the compile cache
    return WorkloadSpec(seed=7, prompt_lens=(10,), max_new_tokens=(4,))


def _audit_serving(frontend, run_dir, tag):
    """The clean-books + stream-integrity audit every serve_* scenario ends
    with: books balance exactly, zero leaked slots, the event stream
    validates with NO problems and NO forward-compat warnings."""
    from perceiver_io_tpu.obs.events import validate_events

    problems = frontend.audit()
    assert not problems, f"{tag}: books audit failed: {problems}"
    warnings_out = []
    stream_problems = validate_events(run_dir, warnings_out=warnings_out)
    assert not stream_problems, f"{tag}: event stream invalid: {stream_problems}"
    assert not warnings_out, f"{tag}: unexpected schema warnings: {warnings_out}"
    return frontend.books()


def _stream(run_dir):
    from perceiver_io_tpu.obs.events import merged_events

    return merged_events(run_dir)


def scenario_serve_overload(tmp):
    """Open-loop overload: arrivals at 50 req/s against an injected 100 ms
    service time. Admission must shed (honestly stamped), queue depth must
    stay bounded by the deadline, and warm TTFT p99 for ADMITTED requests
    must hold the declared SLO — all live on /healthz and /slo."""
    import json as _json
    import urllib.request

    from perceiver_io_tpu.obs.server import ObsServer
    from perceiver_io_tpu.obs.slo import build_slo_report
    from perceiver_io_tpu.serving import FaultInjector, FrontEndConfig, RequestFrontEnd

    ttft_slo, deadline, service = 1.0, 0.5, 0.1
    model, params = _serving_model()
    events, clock, run_dir = _serve_env(tmp, "serve_overload", slo_ttft=ttft_slo)
    injector = FaultInjector(clock=clock).stall_at(None, 1, service)
    fe = RequestFrontEnd(
        model, params, num_latents=4,
        config=FrontEndConfig(max_queue=32, est_service_s=service),
        events=events, clock=clock, sleep=clock.sleep, injector=injector,
    )
    with ObsServer(registry=fe.registry, run_dir=run_dir, health=fe.health) as server:
        recs = fe.run_open(_serve_spec().draw(40, 64), rate_rps=50.0,
                           deadline_s=deadline, seed=11)
        assert len(recs) == 40  # every arrival got a record, shed or served
        with urllib.request.urlopen(server.url + "/healthz", timeout=10) as r:
            health = _json.loads(r.read())
        with urllib.request.urlopen(server.url + "/slo", timeout=10) as r:
            slo_live = _json.loads(r.read())
    books = _audit_serving(fe, run_dir, "serve_overload")
    assert books["shed"] > 0 and books["ok"] > 0, books
    # borderline admits (projected wait ~= deadline) die mid-decode as
    # timeouts — also terminal, also accounted: nothing vanishes
    assert books["ok"] + books["timeout"] == books["admitted"], books
    # bounded queue: the deadline projection admits at most ~deadline/service
    # requests' worth of work ahead — far below the 32-deep queue cap
    bound = int(deadline / service) + 2
    assert books["max_queue_depth"] <= bound, (
        f"queue depth {books['max_queue_depth']} > deadline-implied bound {bound}"
    )
    report = build_slo_report(_stream(run_dir))
    assert report["n_requests"] == 40 and report["outcomes"]["shed"] == books["shed"]
    assert report.get("shed_rate", 0) > 0, "shed traffic not accounted in the SLO report"
    # the NON-vacuous admission guarantee, on the injected clock: admitted
    # requests waited at most ~their deadline (disable shedding and queue
    # waits grow to multiple seconds here — this is the assertion that
    # fails when admission control breaks; TTFT is real wall time on a
    # tiny CPU model, so its SLO check below guards the serving path, not
    # the queue)
    queue_p99 = report["queue_wait_s"]["p99"]
    assert queue_p99 <= deadline, (
        f"admitted-request queue-wait p99 {queue_p99}s exceeds the "
        f"{deadline}s deadline — admission projection is not bounding the queue"
    )
    ttft_p99 = report["ttft_s"]["p99"]
    assert ttft_p99 <= ttft_slo, (
        f"warm TTFT p99 {ttft_p99}s breaches the declared {ttft_slo}s SLO"
    )
    # every shed left a first-class request row — never a silent drop
    shed_rows = [e for e in _stream(run_dir)
                 if e.get("event") == "request" and e.get("outcome") == "shed"]
    assert len(shed_rows) == books["shed"]
    assert all(e.get("shed_reason") for e in shed_rows)
    assert health["breaker"]["state"] == "closed" and health["books_balanced"] is True
    assert slo_live["n_requests"] == 40
    print(
        f"chaos: serve_overload ok — {books['ok']} served / {books['timeout']} "
        f"deadline-timeout / {books['shed']} shed "
        f"(reasons {sorted({e['shed_reason'] for e in shed_rows})}), queue depth "
        f"<= {books['max_queue_depth']}, admitted queue-wait p99 {queue_p99}s <= "
        f"{deadline}s deadline, warm ttft_p99 {ttft_p99}s <= {ttft_slo}s SLO, "
        "books balanced, /healthz+/slo live"
    )


def scenario_serve_kill_mid_decode(tmp):
    """A request dies between tokens: the slot is freed, books close with
    exactly one ``error``, and exactly one flight dump names the dead
    request's span."""
    from perceiver_io_tpu.serving import FaultInjector, RequestFrontEnd

    model, params = _serving_model()
    recorder, clock, run_dir = _serve_env(tmp, "serve_kill")
    injector = FaultInjector(clock=clock).kill_at(3, 2)
    fe = RequestFrontEnd(model, params, num_latents=4, events=recorder,
                         clock=clock, sleep=clock.sleep, injector=injector)
    recs = fe.run_closed(_serve_spec().draw(8, 64), concurrency=2)
    books = _audit_serving(fe, run_dir, "serve_kill_mid_decode")
    assert [r.outcome for r in recs].count("error") == 1 and books["error"] == 1
    assert books["admitted"] == 8 and books["ok"] == 7, books
    dead = next(r for r in recs if r.outcome == "error")
    assert dead.index == 3 and 0 < dead.tokens_out < dead.max_new_tokens, vars(dead)
    assert [i["kind"] for i in injector.injected] == ["kill"]
    dumps = recorder.dumps
    assert len(dumps) == 1 and "flight-error" in os.path.basename(dumps[0]), dumps
    with open(dumps[0]) as f:
        dump = json.load(f)
    err_rows = [e for e in _stream(run_dir)
                if e.get("event") == "request" and e.get("outcome") == "error"]
    assert len(err_rows) == 1
    assert dump["trigger_span_id"] == err_rows[0]["span_id"], (
        "flight dump does not name the dead request's span"
    )
    assert any(e.get("event") == "span" and e.get("span_id") == dump["trigger_span_id"]
               for e in dump["events"]), "dump ring lacks the named span"
    print(
        f"chaos: serve_kill_mid_decode ok — request 3 killed after "
        f"{dead.tokens_out} token(s), slot freed, books balanced "
        f"(7 ok / 1 error), 1 flight dump names its span"
    )


def scenario_serve_deadline(tmp):
    """An injected stall blows a request's deadline mid-decode: the
    ``on_token`` seam cancels it, the ``timeout`` request event carries the
    partial TTFT/TPOT, and one ``timeout`` dump names the span."""
    from perceiver_io_tpu.serving import FaultInjector, RequestFrontEnd

    model, params = _serving_model()
    recorder, clock, run_dir = _serve_env(tmp, "serve_deadline")
    injector = FaultInjector(clock=clock).stall_at(2, 1, 5.0)  # >> deadline
    fe = RequestFrontEnd(model, params, num_latents=4, events=recorder,
                         clock=clock, sleep=clock.sleep, injector=injector)
    recs = fe.run_closed(_serve_spec().draw(5, 64), concurrency=1, deadline_s=1.0)
    books = _audit_serving(fe, run_dir, "serve_deadline")
    timed_out = [r for r in recs if r.outcome == "timeout"]
    assert len(timed_out) == 1 and timed_out[0].index == 2, recs
    assert books["ok"] == 4 and books["timeout"] == 1, books
    # the partial stream is accounted: >=1 token out before the cut
    assert 0 < timed_out[0].tokens_out < timed_out[0].max_new_tokens
    rows = [e for e in _stream(run_dir)
            if e.get("event") == "request" and e.get("outcome") == "timeout"]
    assert len(rows) == 1
    row = rows[0]
    assert row["tokens_out"] == timed_out[0].tokens_out
    assert row["ttft_s"] > 0 and row.get("tpot_hist"), (
        "timeout event lacks the partial TTFT/TPOT it must carry"
    )
    dumps = recorder.dumps
    assert len(dumps) == 1 and "flight-timeout" in os.path.basename(dumps[0]), dumps
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert dump["trigger_span_id"] == row["span_id"]
    print(
        f"chaos: serve_deadline ok — request 2 cancelled mid-decode after "
        f"{timed_out[0].tokens_out} token(s) (5.0s stall vs 1.0s deadline), "
        "timeout event carries partial TTFT/TPOT, 1 timeout dump names its span"
    )


def scenario_serve_drain(tmp):
    """A REAL SIGTERM mid-run: the PreemptionGuard flips the front end into
    drain — admission stops (late submissions shed ``draining``), queued
    work finishes, and ``serve.drain`` carries the balanced final books."""
    from perceiver_io_tpu.serving import RequestFrontEnd

    model, params = _serving_model()
    recorder, clock, run_dir = _serve_env(tmp, "serve_drain")
    fe = RequestFrontEnd(model, params, num_latents=4, events=recorder,
                         clock=clock, sleep=clock.sleep)
    guard = fe.install_guard()
    try:
        specs = _serve_spec().draw(7, 64)
        for s in specs[:5]:
            fe.submit(s)
        fe.pump(max_requests=2)
        os.kill(os.getpid(), signal.SIGTERM)  # the real signal path
        fe.pump()  # guard noticed at the boundary; queued work still finishes
        late = [fe.submit(s) for s in specs[5:]]
        books = fe.drain()
    finally:
        guard.uninstall()
    assert guard.requested and books["draining"] is True
    assert all(r.outcome == "shed" and r.shed_reason == "draining" for r in late), late
    assert books["ok"] == 5 and books["shed"] == 2 and books["balanced"], books
    _audit_serving(fe, run_dir, "serve_drain")
    stream = _stream(run_dir)
    assert any(e.get("event") == "serve.preempt" for e in stream), (
        "no serve.preempt event for the SIGTERM"
    )
    drains = [e for e in stream if e.get("event") == "serve.drain"]
    assert len(drains) == 1 and drains[0]["books"]["balanced"] is True, drains
    assert drains[0]["books"]["in_flight"] == 0 and drains[0]["books"]["queued"] == 0
    print(
        "chaos: serve_drain ok — SIGTERM mid-run, 3 queued requests finished, "
        "2 late submissions shed as draining, serve.drain books balanced"
    )


def scenario_serve_breaker(tmp):
    """Consecutive injected errors open the circuit breaker: admissions
    shed ``breaker_open`` with a ``breaker`` flight dump; after the
    RetryPolicy-spaced probe delay (stepped on the manual clock) the
    half-open probe closes it again."""
    from perceiver_io_tpu.serving import (
        BreakerConfig,
        FaultInjector,
        FrontEndConfig,
        RequestFrontEnd,
    )
    from perceiver_io_tpu.training.faults import RetryPolicy

    model, params = _serving_model()
    recorder, clock, run_dir = _serve_env(tmp, "serve_breaker")
    injector = FaultInjector(clock=clock)
    for i in (1, 2, 3):
        injector.kill_at(i, 1)
    cfg = FrontEndConfig(breaker=BreakerConfig(
        window=4, min_requests=3, error_rate_to_open=0.5,
        probe_backoff=RetryPolicy(base_delay=2.0, max_delay=10.0, jitter=0.0),
    ))
    fe = RequestFrontEnd(model, params, num_latents=4, config=cfg, events=recorder,
                         clock=clock, sleep=clock.sleep, injector=injector)
    specs = _serve_spec().draw(10, 64)
    recs = fe.run_closed(specs[:8], concurrency=1)
    assert fe.breaker.state == "open", fe.breaker.state
    breaker_sheds = [r for r in recs if r.shed_reason == "breaker_open"]
    assert breaker_sheds, "breaker open but nothing shed"
    # probe spacing is the RetryPolicy schedule: jitter=0 -> exactly base_delay
    early = fe.submit(specs[8])
    assert early.outcome == "shed" and early.shed_reason == "breaker_open"
    clock.advance(2.0)
    probe = fe.submit(specs[9])
    fe.pump()
    assert probe.probe is True and probe.outcome == "ok", vars(probe)
    assert fe.breaker.state == "closed"
    books = _audit_serving(fe, run_dir, "serve_breaker")
    transitions = [(e["prev"], e["state"], e["reason"])
                   for e in _stream(run_dir) if e.get("event") == "serve.breaker"]
    assert transitions == [
        ("closed", "open", "error-rate"),
        ("open", "half_open", "probe-delay-elapsed"),
        ("half_open", "closed", "probe-succeeded"),
    ], transitions
    assert any("flight-breaker" in os.path.basename(p) for p in recorder.dumps), (
        recorder.dumps
    )
    print(
        f"chaos: serve_breaker ok — {books['error']} injected errors opened the "
        f"breaker ({len(breaker_sheds) + 1} shed breaker_open, 1 breaker dump), "
        "2.0s probe delay on the manual clock, half-open probe closed it"
    )


def scenario_serve_engine_kill_mid_decode(tmp):
    """Pageline: a request dies between tokens INSIDE a live decode batch —
    only its slot retires (the rest of the batch keeps decoding), its pages
    return to the free list, books close with exactly one ``error``, and
    exactly one flight dump names the dead request's span."""
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd, FaultInjector

    model, params = _serving_model()
    recorder, clock, run_dir = _serve_env(tmp, "serve_engine_kill")
    injector = FaultInjector(clock=clock).kill_at(3, 2)
    fe = EngineFrontEnd(
        model, params, num_latents=4,
        engine_config=EngineConfig(slots=4, page_size=8, max_ca_tokens=24,
                                   max_sa_tokens=16),
        events=recorder, clock=clock, sleep=clock.sleep, injector=injector,
    )
    recs = fe.run_closed(_serve_spec().draw(8, 64), concurrency=4)
    books = _audit_serving(fe, run_dir, "serve_engine_kill_mid_decode")
    assert [r.outcome for r in recs].count("error") == 1 and books["error"] == 1
    assert books["admitted"] == 8 and books["ok"] == 7, books
    dead = next(r for r in recs if r.outcome == "error")
    assert dead.index == 3 and 0 < dead.tokens_out < dead.max_new_tokens, vars(dead)
    # page-exact clean books: every page back on the free list, allocator
    # invariants hold (no double-ownership, no leak)
    assert fe.ca_alloc.pages_used == 0 and fe.sa_alloc.pages_used == 0, (
        fe.ca_alloc.pages_used, fe.sa_alloc.pages_used
    )
    assert fe.ca_alloc.audit() == [] and fe.sa_alloc.audit() == []
    dumps = recorder.dumps
    assert len(dumps) == 1 and "flight-error" in os.path.basename(dumps[0]), dumps
    with open(dumps[0]) as f:
        dump = json.load(f)
    err_rows = [e for e in _stream(run_dir)
                if e.get("event") == "request" and e.get("outcome") == "error"]
    assert len(err_rows) == 1
    assert dump["trigger_span_id"] == err_rows[0]["span_id"], (
        "flight dump does not name the dead request's span"
    )
    # the batch stayed live: the victim's event shows >1 requests in its
    # decode batch, and the survivors' streams completed in full
    assert err_rows[0].get("batch_size_at_decode", 0) > 1, err_rows[0]
    ok_rows = [e for e in _stream(run_dir)
               if e.get("event") == "request" and e.get("outcome") == "ok"]
    assert all(e["tokens_out"] == 4 for e in ok_rows), ok_rows
    print(
        f"chaos: serve_engine_kill_mid_decode ok — request 3 killed after "
        f"{dead.tokens_out} token(s) in a live batch "
        f"(batch_size {err_rows[0]['batch_size_at_decode']}), slot + pages freed, "
        "books balanced (7 ok / 1 error), 1 flight dump names its span"
    )


def scenario_serve_engine_pages(tmp):
    """Pageline page-pool discipline: an impossible request (KV footprint
    over the pool) sheds ``kv_pages_exhausted`` at admission; a pool sized
    BELOW the slot count exerts backpressure (requests wait for pages, none
    shed) and still serves everything; the allocator's books stay exact."""
    from perceiver_io_tpu.obs.loadgen import RequestSpec
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd

    import numpy as np

    model, params = _serving_model()
    recorder, clock, run_dir = _serve_env(tmp, "serve_engine_pages")
    # pool_headroom 0.5: pages for ~2 of the 4 slots — joins must wait
    fe = EngineFrontEnd(
        model, params, num_latents=4,
        engine_config=EngineConfig(slots=4, page_size=8, max_ca_tokens=24,
                                   max_sa_tokens=16, pool_headroom=0.5),
        events=recorder, clock=clock, sleep=clock.sleep,
    )
    specs = list(_serve_spec().draw(8, 64))
    # an impossible request: prompt + budget over max_ca_tokens
    rng = np.random.default_rng(3)
    specs.append(RequestSpec(index=len(specs), prompt_len=20, max_new_tokens=16,
                             input_ids=rng.integers(0, 64, size=(1, 20)),
                             rng_seed=7))
    recs = fe.run_closed(specs, concurrency=9)
    books = _audit_serving(fe, run_dir, "serve_engine_pages")
    assert books["ok"] == 8 and books["shed"] == 1 and books["balanced"], books
    shed = [r for r in recs if r.outcome == "shed"]
    assert len(shed) == 1 and shed[0].shed_reason == "kv_pages_exhausted", shed
    shed_rows = [e for e in _stream(run_dir)
                 if e.get("event") == "request" and e.get("outcome") == "shed"]
    assert len(shed_rows) == 1 and shed_rows[0]["shed_reason"] == "kv_pages_exhausted"
    assert fe.ca_alloc.pages_used == 0 and fe.ca_alloc.audit() == []
    assert fe.sa_alloc.pages_used == 0 and fe.sa_alloc.audit() == []
    # backpressure really happened: the half-size CA pool (6 pages, 2 per
    # request) caps the live batch at 3 of 4 slots — the 4th join must wait
    # for a retire, so mean fill can never reach the full-pool value
    assert fe.mean_batch_fill <= 0.75 + 1e-6, fe.mean_batch_fill
    print(
        "chaos: serve_engine_pages ok — half-size pool backpressured joins "
        f"(mean batch fill {fe.mean_batch_fill:.2f}, page-capped at 3 of 4 "
        "slots), 8 served / 1 impossible request shed kv_pages_exhausted, "
        "page books exact"
    )


def scenario_serve_spec_kill_mid_span(tmp):
    """Specline: a request dies MID-SPAN inside the speculative engine —
    a verify step emits m ∈ [1, k+1] tokens and streams each through the
    per-token seam, so the kill takes effect at its exact token index even
    when that index lands inside a span: the slot retires ``error`` there,
    the span's remaining tokens are dropped (never served), pages return,
    books balance, every request row carries acceptance telemetry, and one
    flight dump names the dead request's span."""
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd, FaultInjector

    model, params = _serving_model()
    recorder, clock, run_dir = _serve_env(tmp, "serve_spec_kill")
    injector = FaultInjector(clock=clock).kill_at(3, 2)
    fe = EngineFrontEnd(
        model, params, num_latents=4,
        # max_sa_tokens == the gate model's max_latents: the speculative
        # no-slide contract, validated at construction
        engine_config=EngineConfig(slots=4, page_size=8, max_ca_tokens=24,
                                   max_sa_tokens=8, spec_k=2, spec_depth=1),
        events=recorder, clock=clock, sleep=clock.sleep, injector=injector,
    )
    recs = fe.run_closed(_serve_spec().draw(8, 64), concurrency=4)
    books = _audit_serving(fe, run_dir, "serve_spec_kill_mid_span")
    assert [r.outcome for r in recs].count("error") == 1 and books["error"] == 1
    assert books["admitted"] == 8 and books["ok"] == 7, books
    dead = next(r for r in recs if r.outcome == "error")
    assert dead.index == 3 and 0 < dead.tokens_out < dead.max_new_tokens, vars(dead)
    # the kill's token index is exact: tokens 0..2 served, nothing after
    assert dead.tokens_out == 3 and len(fe.served_tokens[3]) == 3
    assert fe.ca_alloc.pages_used == 0 and fe.sa_alloc.pages_used == 0
    assert fe.ca_alloc.audit() == [] and fe.sa_alloc.audit() == []
    rows = [e for e in _stream(run_dir) if e.get("event") == "request"]
    assert len(rows) == 8
    # the measurement satellite holds under chaos: every row carries the
    # acceptance pair, and the spec step really batched multiple tokens
    assert all(isinstance(e.get("acceptance_rate"), (int, float)) for e in rows)
    assert all(e.get("tokens_per_step", 0) >= 1.0 for e in rows)
    assert any(e["tokens_per_step"] > 1.0 for e in rows), (
        "no request emitted more than one token per verify step — the "
        "mid-SPAN property is vacuous"
    )
    dumps = recorder.dumps
    assert len(dumps) == 1 and "flight-error" in os.path.basename(dumps[0]), dumps
    with open(dumps[0]) as f:
        dump = json.load(f)
    err_rows = [e for e in rows if e.get("outcome") == "error"]
    assert len(err_rows) == 1
    assert dump["trigger_span_id"] == err_rows[0]["span_id"], (
        "flight dump does not name the dead request's span"
    )
    ok_rows = [e for e in rows if e.get("outcome") == "ok"]
    assert all(e["tokens_out"] == 4 for e in ok_rows), ok_rows
    tps = [e["tokens_per_step"] for e in ok_rows]
    print(
        f"chaos: serve_spec_kill_mid_span ok — request 3 killed at token 3 "
        f"mid-span (k=2 spec engine, tokens/step up to {max(tps):.2f}), span "
        "remainder dropped, slot + pages freed, books balanced "
        "(7 ok / 1 error), acceptance telemetry on all 8 rows, 1 dump names the span"
    )


# ---------------------------------------------------------------------------
# Evictline scenarios: page-pressure eviction with token-exact resume, and
# journal-backed engine crash recovery (docs/robustness.md
# #engine-eviction-and-recovery)
# ---------------------------------------------------------------------------

# set by --smoke: the Evictline scenarios shrink to their CI-fast shape
# (greedy-only, fewer requests) with IDENTICAL assertions
SMOKE = False


def _evict_gen_configs():
    """(tag, GenerationConfig) pairs the Evictline scenarios certify
    token-exactness under — greedy AND temperature sampling (the rng-chain
    alignment claim is vacuous under argmax alone); --smoke keeps greedy."""
    from perceiver_io_tpu.generation import GenerationConfig

    configs = [("greedy", GenerationConfig())]
    if not SMOKE:
        configs.append(
            ("temperature", GenerationConfig(do_sample=True, temperature=0.8, top_k=10))
        )
    return configs


def _sequential_reference(model, params, spec, base_config):
    """The uninterrupted stream: the spec decoded alone through the
    contiguous host-driven pair with its pinned rng chain — what an
    evicted/recovered request's served tokens must equal exactly."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.generation import make_decode_fns

    cfg = _dc.replace(base_config, max_new_tokens=spec.max_new_tokens)
    prefill, step = make_decode_fns(model, 4, cfg)
    tok, state = prefill(
        params, jnp.asarray(spec.input_ids), None, jax.random.PRNGKey(spec.rng_seed)
    )
    out = [int(tok[0])]
    for _ in range(spec.max_new_tokens - 1):
        state, tok = step(state)
        out.append(int(tok[0]))
    return out


def _evict_workload(n):
    """Mixed-geometry specs under the no-slide eviction bound of the gate
    model (max_latents 8, num_latents 4 => budgets <= 4)."""
    from perceiver_io_tpu.obs.loadgen import WorkloadSpec

    return WorkloadSpec(seed=13, prompt_lens=(8, 12), max_new_tokens=(3, 4)).draw(n, 64)


def scenario_serve_evict_storm(tmp):
    """Evictline page-pressure preemption: a pool sized at half the slot
    demand (pool_headroom 0.5) forces real evictions — yet every fit-able
    request reaches ``ok`` with ZERO ``kv_pages_exhausted`` sheds (the
    pre-Evictline behavior this scenario exists to retire), each resumed
    stream is token-exact vs the uninterrupted sequential reference
    (greedy and temperature — the rng chain advanced one split per emitted
    token), the extended books identity closes, pages come back exact, and
    every ``serve.evict``/``serve.resume`` event resolves to an in-stream
    span."""
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd

    model, params = _serving_model()
    n = 6 if SMOKE else 8
    for tag, base in _evict_gen_configs():
        recorder, clock, run_dir = _serve_env(tmp, f"serve_evict_storm_{tag}")
        fe = EngineFrontEnd(
            model, params, num_latents=4, base_config=base,
            engine_config=EngineConfig(slots=4, page_size=8, max_ca_tokens=16,
                                       max_sa_tokens=8, pool_headroom=0.5,
                                       eviction=True),
            events=recorder, clock=clock, sleep=clock.sleep,
        )
        specs = _evict_workload(n)
        recs = fe.run_closed(specs, concurrency=n)
        books = _audit_serving(fe, run_dir, f"serve_evict_storm_{tag}")
        # the storm was real: page pressure preempted in-flight work...
        assert books["evictions"] >= 1 and books["resumes"] >= 1, books
        assert books["evictions"] == books["resumes"], books
        # ...and STILL nothing shed and everything served: ok_rate 1.0
        assert books["ok"] == n and books["shed"] == 0, books
        assert all(r.outcome == "ok" for r in recs), [vars(r) for r in recs]
        assert books["parked"] == 0 and books["in_flight"] == 0, books
        stream = _stream(run_dir)
        shed_rows = [e for e in stream if e.get("event") == "request"
                     and e.get("outcome") == "shed"]
        assert not shed_rows, f"fit-able requests shed under eviction: {shed_rows}"
        # token-exactness: every served stream equals the uninterrupted
        # reference — the evicted-and-resumed ones prove the replay seam
        for spec in specs:
            want = _sequential_reference(model, params, spec, base)
            got = fe.served_tokens[spec.index]
            assert got == want, (
                f"serve_evict_storm[{tag}] request {spec.index}: "
                f"engine {got} != sequential {want}"
            )
        # page-exact books after the storm
        assert fe.ca_alloc.pages_used == 0 and fe.sa_alloc.pages_used == 0
        assert fe.ca_alloc.audit() == [] and fe.sa_alloc.audit() == []
        evicts = [e for e in stream if e.get("event") == "serve.evict"]
        resumes = [e for e in stream if e.get("event") == "serve.resume"]
        assert len(evicts) == books["evictions"], (len(evicts), books["evictions"])
        assert len(resumes) == books["resumes"], (len(resumes), books["resumes"])
        assert all(e.get("pages_freed", 0) > 0 for e in evicts), evicts
        n_attr = _assert_span_attributed(run_dir)
        # the parked-depth gauge saw the storm (its peak feeds loadgen)
        assert fe.registry.gauge("serve_parked_depth").peak >= 1
        print(
            f"chaos: serve_evict_storm[{tag}] ok — {books['evictions']} "
            f"evictions / {books['resumes']} resumes under a half-size pool, "
            f"{n}/{n} served ok (0 sheds), all streams token-exact, "
            f"{n_attr} evict/resume events span-attributed"
        )


def scenario_serve_prefix_storm(tmp):
    """Shareline prefix storm: N requests sharing one page-aligned prompt
    prefix hit the engine together. Exactly ONE of them prefills the
    shared run (counter-asserted: N-1 admission hits — the queue never
    drains mid-storm, so the run stays resident from first publish to
    last release), every stream is token-exact vs the uninterrupted
    UNSHARED sequential reference (greedy AND temperature — sharing is an
    allocator optimization, never an approximation), every hit lands a
    span-attributed ``serve.prefix_hit`` row, and at drain the refcounts
    balance: zero pages used, sharing audit clean, the radix index fully
    expired (no node outlives its pages)."""
    from perceiver_io_tpu.obs.loadgen import WorkloadSpec
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd

    model, params = _serving_model()
    n = 6 if SMOKE else 8
    for tag, base in _evict_gen_configs():
        recorder, clock, run_dir = _serve_env(tmp, f"serve_prefix_storm_{tag}")
        fe = EngineFrontEnd(
            model, params, num_latents=4, base_config=base,
            engine_config=EngineConfig(slots=4, page_size=8,
                                       max_ca_tokens=24, max_sa_tokens=16),
            events=recorder, clock=clock, sleep=clock.sleep,
        )
        # prompt 16, latents 4 => context region 12 tokens => exactly one
        # full page (8 tokens) is shareable; the 8-token shared prefix
        # covers it, the 8-token unique tail keeps every stream distinct
        specs = WorkloadSpec(seed=31, prompt_lens=(16,), max_new_tokens=(3, 4),
                             shared_prefix_len=8).draw(n, 64)
        assert len({tuple(s.input_ids[0]) for s in specs}) == n
        recs = fe.run_closed(specs, concurrency=n)
        books = _audit_serving(fe, run_dir, f"serve_prefix_storm_{tag}")
        assert books["ok"] == n and books["shed"] == 0, books
        assert all(r.outcome == "ok" for r in recs), [vars(r) for r in recs]
        # exactly one prefill of the shared run: the first join published,
        # every other admission matched (concurrency == n keeps the run
        # refcounted end to end — no drain gap, no republish)
        assert fe._n_prefix_hits == n - 1, (
            f"serve_prefix_storm[{tag}]: {fe._n_prefix_hits} admission hits "
            f"for {n} same-prefix requests, want {n - 1} (one publisher)"
        )
        assert fe._n_prefix_pages_shared == n - 1, fe._n_prefix_pages_shared
        # token-exactness: every stream equals the unshared sequential
        # reference — shared-prefix prefill changed nothing observable
        for spec in specs:
            want = _sequential_reference(model, params, spec, base)
            got = fe.served_tokens[spec.index]
            assert got == want, (
                f"serve_prefix_storm[{tag}] request {spec.index}: "
                f"shared {got} != unshared reference {want}"
            )
        # refcounts balanced at drain: nothing leaked, nothing double-freed,
        # and the index expired with its pages (stale matches impossible)
        assert fe.sharing_audit() == [], fe.sharing_audit()
        assert fe.ca_alloc.pages_used == 0 and fe.sa_alloc.pages_used == 0
        assert fe.ca_alloc.stats().pages_shared == 0
        assert fe.prefix_index.pages() == (), fe.prefix_index.pages()
        stream = _stream(run_dir)
        hit_rows = [e for e in stream if e.get("event") == "serve.prefix_hit"]
        assert len(hit_rows) == n - 1, (len(hit_rows), n - 1)
        assert all(0 < e["pages_matched"] <= e["pages_total"] for e in hit_rows)
        n_attr = _assert_span_attributed(run_dir)
        assert n_attr >= n - 1, (n_attr, n - 1)
        print(
            f"chaos: serve_prefix_storm[{tag}] ok — {n} same-prefix requests, "
            f"1 prefill of the shared run + {fe._n_prefix_hits} admission "
            f"hits, all streams token-exact vs the unshared reference, "
            f"refcounts balanced at drain ({n_attr} events span-attributed)"
        )


def scenario_serve_crash_recover(tmp):
    """Evictline crash recovery: the engine is torn down mid-decode by an
    injected ``EngineCrash`` (a BaseException no accounting seam catches —
    in-flight slots freeze, no terminal records land, exactly a SIGKILL);
    a SECOND engine recovers from the write-ahead journal, re-admits every
    non-terminal request (mid-decode ones parked with their served prefix,
    unjoined ones re-queued) and serves them token-exactly vs the
    uninterrupted reference (greedy and temperature). The combined books
    balance ACROSS the restart — journal ``submitted == terminal`` with
    every outcome accounted once — and each re-admission lands a
    span-attributed ``serve.recover`` event."""
    from perceiver_io_tpu.serving import (
        EngineConfig,
        EngineCrash,
        EngineFrontEnd,
        FaultInjector,
        RequestJournal,
    )

    model, params = _serving_model()
    n = 4 if SMOKE else 6
    for tag, base in _evict_gen_configs():
        recorder, clock, run_dir = _serve_env(tmp, f"serve_crash_recover_{tag}")
        jpath = os.path.join(run_dir, "journal.jsonl")
        specs = _evict_workload(n)
        engine_cfg = EngineConfig(slots=4, page_size=8, max_ca_tokens=16,
                                  max_sa_tokens=8)
        injector = FaultInjector(clock=clock).crash_at(2, 1)
        fe1 = EngineFrontEnd(
            model, params, num_latents=4, base_config=base,
            engine_config=engine_cfg, events=recorder, clock=clock,
            sleep=clock.sleep, injector=injector, journal=jpath,
        )
        crashed = False
        try:
            fe1.run_closed(specs, concurrency=n)
        except EngineCrash:
            crashed = True
        assert crashed, "injected EngineCrash did not propagate (a seam ate it)"
        books1 = fe1.books()
        assert books1["terminal"] < books1["submitted"], (
            f"crash left nothing owed — the recovery is vacuous: {books1}"
        )
        # the second incarnation: fresh engine, same event stream, same
        # journal file — recover() re-admits everything still owed
        fe2 = EngineFrontEnd(
            model, params, num_latents=4, base_config=base,
            engine_config=engine_cfg, events=recorder, clock=clock,
            sleep=clock.sleep,
        )
        journal = RequestJournal(jpath)
        owed = len(journal.pending())
        assert owed == books1["submitted"] - books1["terminal"], (owed, books1)
        info = fe2.recover(journal)
        assert info["recovered"] == owed, (info, owed)
        assert info["parked"] >= 1, (
            f"no request recovered MID-decode (all prompt-only): {info} — "
            "the token-exact replay claim is vacuous"
        )
        fe2.pump()
        books2 = _audit_serving(fe2, run_dir, f"serve_crash_recover_{tag}")
        assert books2["recovered"] == owed and books2["parked"] == 0, books2
        # combined books balance ACROSS the restart: every submitted index
        # reached exactly one terminal outcome, in one incarnation or the other
        jb = journal.books()
        assert jb["balanced"] and jb["submitted"] == n, jb
        assert jb["pending"] == 0 and jb["outcomes"] == {"ok": n}, jb
        assert journal.audit() == [], journal.audit()
        # token-exact across the restart: served streams (second engine's
        # replay included) equal the uninterrupted reference
        served = dict(fe1.served_tokens)
        served.update(fe2.served_tokens)
        for spec in specs:
            want = _sequential_reference(model, params, spec, base)
            got = served.get(spec.index)
            assert got == want, (
                f"serve_crash_recover[{tag}] request {spec.index}: "
                f"recovered {got} != uninterrupted {want}"
            )
        stream = _stream(run_dir)
        recovers = [e for e in stream if e.get("event") == "serve.recover"]
        assert len(recovers) == owed, (len(recovers), owed)
        n_attr = _assert_span_attributed(run_dir)
        assert fe2.ca_alloc.pages_used == 0 and fe2.sa_alloc.pages_used == 0
        print(
            f"chaos: serve_crash_recover[{tag}] ok — engine crashed with "
            f"{owed} requests owed ({info['parked']} mid-decode), second "
            f"engine recovered all {owed} from the journal, books balanced "
            f"across the restart ({n}/{n} ok), streams token-exact, "
            f"{n_attr} events span-attributed"
        )


# ---------------------------------------------------------------------------
# Fleetline scenarios: N engine replicas behind one FleetRouter submit
# surface (serving/router.py; docs/serving.md#fleet) — replica death,
# brownout and graceful drain, wall-clock-free on the injected clock
# ---------------------------------------------------------------------------


def _audit_fleet(router, run_dir, tag, expect_drained=True):
    """The fleet analog of ``_audit_serving``: the fleet books identity
    closes (``Σ submitted == dispatched + re-admissions``, every orphan
    re-homed exactly once), every live replica's own audit is empty, every
    dead replica's journal is handoff-closed, and the event stream
    validates with NO problems and NO forward-compat warnings."""
    from perceiver_io_tpu.obs.events import validate_events

    problems = router.audit(expect_drained=expect_drained)
    assert not problems, f"{tag}: fleet audit failed: {problems}"
    warnings_out = []
    stream_problems = validate_events(run_dir, warnings_out=warnings_out)
    assert not stream_problems, f"{tag}: event stream invalid: {stream_problems}"
    assert not warnings_out, f"{tag}: unexpected schema warnings: {warnings_out}"
    return router.books()


def scenario_serve_fleet_failover(tmp):
    """Fleetline failover: TWO real engines behind the router; an injected
    replica kill (``EngineCrash`` at a replica-step coordinate — the
    SIGKILL analog, no accounting seam catches it) lands MID-DECODE on
    r0. The router must declare r0 dead, replay its write-ahead journal
    onto r1 through the recover handoff seam, and the survivor must
    finish every journaled request TOKEN-EXACTLY vs the uninterrupted
    sequential reference. The fleet books balance across the handoff —
    every submitted index reaches exactly one terminal outcome fleet-wide,
    the orphan count equals the re-admissions (zero double-served
    tokens), the dead journal closes with handoff markers — and exactly
    one flight dump (trigger ``failover``) names the dead replica."""
    from perceiver_io_tpu.serving import (
        EngineConfig,
        EngineFrontEnd,
        FaultInjector,
    )
    from perceiver_io_tpu.serving.router import FleetRouter

    model, params = _serving_model()
    n = 4 if SMOKE else 6
    for tag, base in _evict_gen_configs():
        recorder, clock, run_dir = _serve_env(tmp, f"serve_fleet_failover_{tag}")
        injector = FaultInjector(clock=clock).kill_replica_at("r0", 2)
        router = FleetRouter(clock=clock, events=recorder, injector=injector)
        engine_cfg = EngineConfig(slots=4, page_size=8, max_ca_tokens=16,
                                  max_sa_tokens=8)
        fes = {}
        for rid in ("r0", "r1"):
            fes[rid] = EngineFrontEnd(
                model, params, num_latents=4, base_config=base,
                engine_config=engine_cfg, events=recorder, clock=clock,
                sleep=clock.sleep,
                journal=os.path.join(run_dir, f"journal-{rid}.jsonl"),
            )
            router.add_replica(rid, fes[rid])
        specs = _evict_workload(n)
        router.run_closed(specs, concurrency=n)
        books = _audit_fleet(router, run_dir, f"serve_fleet_failover_{tag}")
        # the kill was real and the fleet absorbed it: one failover, the
        # dead replica's frozen work re-homed exactly once, all served
        assert books["failovers"] == 1, books
        assert books["orphaned"] >= 1, (
            f"r0 died owing nothing — the failover is vacuous: {books}"
        )
        assert books["orphaned"] == books["readmitted"], books
        assert books["outcomes"]["ok"] == n and books["outcomes"]["shed"] == 0, books
        assert router._replicas["r0"].state == "dead"
        assert router._replicas["r1"].state == "active"
        # mid-decode proof: at least one request crossed the handoff with
        # tokens already served (parked on the survivor, resumed there)
        fo_rows = [e for e in _stream(run_dir) if e.get("event") == "serve.failover"]
        assert len(fo_rows) == 1, fo_rows
        fo = fo_rows[0]
        assert fo["dead_replica"] == "r0" and fo["survivor"] == "r1", fo
        assert fo["n_replayed"] == books["readmitted"], (fo, books)
        assert fo["n_parked"] >= 1, (
            f"no request crossed the handoff MID-decode: {fo} — "
            "the token-exact replay claim is vacuous"
        )
        # the dead journal is CLOSED by handoff markers: nothing pending,
        # every non-terminal entry explicitly handed to the survivor
        jb = fes["r0"].journal.books()
        assert jb["balanced"] and jb["handed_off"] >= 1, jb
        assert len(fes["r0"].journal.pending()) == 0, jb
        assert fes["r0"].journal.audit() == [], fes["r0"].journal.audit()
        # token-exact ACROSS the handoff: merged served streams (survivor
        # wins for handed-off indices) equal the uninterrupted reference
        served = dict(fes["r0"].served_tokens)
        served.update(fes["r1"].served_tokens)
        for spec in specs:
            want = _sequential_reference(model, params, spec, base)
            got = served.get(spec.index)
            assert got == want, (
                f"serve_fleet_failover[{tag}] request {spec.index}: "
                f"fleet {got} != sequential {want}"
            )
        # exactly one flight dump, and it names the dead replica
        dumps = sorted(
            f for f in os.listdir(run_dir) if f.startswith("flight-failover-")
        )
        assert len(dumps) == 1, dumps
        with open(os.path.join(run_dir, dumps[0])) as f:
            payload = json.load(f)
        assert payload["trigger"] == "failover", payload["trigger"]
        assert payload["trigger_event"]["dead_replica"] == "r0", payload
        n_attr = _assert_span_attributed(run_dir)
        # the survivor's pages came back exact after the storm
        assert fes["r1"].ca_alloc.pages_used == 0 and fes["r1"].sa_alloc.pages_used == 0
        assert fes["r1"].ca_alloc.audit() == [] and fes["r1"].sa_alloc.audit() == []
        print(
            f"chaos: serve_fleet_failover[{tag}] ok — r0 killed mid-decode "
            f"owing {books['orphaned']} ({fo['n_parked']} mid-stream), r1 "
            f"replayed all {fo['n_replayed']} from the journal, fleet books "
            f"balanced across the handoff ({n}/{n} ok), streams token-exact, "
            f"1 flight dump, {n_attr} events span-attributed"
        )


def scenario_serve_fleet_brownout(tmp):
    """Fleetline brownout: replica r1's service times are inflated 5x by
    the injector (a slow host, not a dead one). The router's per-step
    EWMA health check must flip r1 ``degraded`` (a ``serve.replica``
    transition row) and least-outstanding dispatch must drain traffic
    onto the healthy r0 — while r1 STAYS in the fleet (no failover, its
    in-flight work finishes). Books balance at full scale."""
    from perceiver_io_tpu.serving import EngineConfig, FaultInjector, FrontEndConfig
    from perceiver_io_tpu.serving.sim import TenantSpec, run_fleet_sim

    window = 0.04 if SMOKE else 0.08
    tenants = [
        TenantSpec("burst", rate_rps=5000.0, n_requests=int(5000 * window), seed=11),
        TenantSpec("steady", rate_rps=3500.0, n_requests=int(3500 * window), seed=22),
    ]
    recorder, _clock, run_dir = _serve_env(tmp, "serve_fleet_brownout")
    injector = FaultInjector().brownout_replica("r1", 5.0)
    report = run_fleet_sim(
        tenants, n_replicas=2, service_model=_sim_service_model(),
        engine_config=EngineConfig(slots=16, page_size=8, max_ca_tokens=32,
                                   max_sa_tokens=16),
        # queue deep enough that ROUTING PREFERENCE decides placement:
        # a saturated healthy replica would shed and re-dispatch overflow
        # onto the slow one, muddying the drain signal
        config=FrontEndConfig(max_queue=1024, admission_projection=False,
                              breaker=None),
        events=recorder, injector=injector,
    )
    s = report.summary
    books = _audit_fleet(report.router, run_dir, "serve_fleet_brownout")
    assert s["books_balanced"] and s["failovers"] == 0, (s, books)
    assert books["outcomes"]["shed"] == 0, (
        f"queue overflow contaminated the routing signal: {books['outcomes']}"
    )
    # the health check SAW the brownout: r1 degraded, r0 clean
    assert s["replicas"]["r1"]["degraded"] is True, s["replicas"]
    assert s["replicas"]["r0"]["degraded"] is False, s["replicas"]
    # ...and dispatch ACTED on it: traffic drained onto the healthy
    # replica (the browned-out one still served its early admissions)
    r0_sub = s["replicas"]["r0"]["submitted"]
    r1_sub = s["replicas"]["r1"]["submitted"]
    assert r0_sub >= 3 * max(r1_sub, 1), (
        f"brownout did not drain traffic: r0 {r0_sub} vs r1 {r1_sub}"
    )
    assert s["replicas"]["r1"]["state"] == "active", s["replicas"]
    assert s["replicas"]["r1"]["submitted"] >= 1, (
        f"r1 never dispatched — the drain claim is vacuous: {s['replicas']}"
    )
    # the flip is a first-class transition row naming the slow replica
    degraded_rows = [
        e for e in _stream(run_dir)
        if e.get("event") == "serve.replica" and e.get("transition") == "degraded"
    ]
    assert degraded_rows and all(
        e["replica_id"] == "r1" for e in degraded_rows
    ), degraded_rows
    print(
        f"chaos: serve_fleet_brownout ok — r1 browned out 5x and flipped "
        f"degraded, dispatch drained onto r0 ({r0_sub} vs {r1_sub} submitted), "
        f"no failover, {s['n_requests']} requests booked balanced"
    )


def scenario_serve_fleet_drain(tmp):
    """Fleetline graceful drain: r0 is drained MID-RUN with work in
    flight. Dispatch to it must stop immediately (every post-drain
    submission lands on r1), its outstanding work must finish (state
    ``drained``, not a shed in sight), and the fleet books must close
    with ZERO sheds attributable to the drain — because the replica's own
    ``drain()`` gate is never raised while it still owes tokens."""
    from perceiver_io_tpu.serving import EngineConfig, EngineFrontEnd
    from perceiver_io_tpu.serving.router import FleetRouter

    model, params = _serving_model()
    n = 4 if SMOKE else 6
    tag, base = _evict_gen_configs()[0]  # greedy: the drain certifies routing
    recorder, clock, run_dir = _serve_env(tmp, "serve_fleet_drain")
    router = FleetRouter(clock=clock, events=recorder)
    engine_cfg = EngineConfig(slots=4, page_size=8, max_ca_tokens=16,
                              max_sa_tokens=8)
    fes = {}
    for rid in ("r0", "r1"):
        fes[rid] = EngineFrontEnd(
            model, params, num_latents=4, base_config=base,
            engine_config=engine_cfg, events=recorder, clock=clock,
            sleep=clock.sleep,
        )
        router.add_replica(rid, fes[rid])
    specs = _evict_workload(n + 2)
    for spec in specs[:n]:
        router.submit(spec)
    router.step()  # both replicas now mid-decode
    assert router._outstanding(fes["r0"]) >= 1, (
        "r0 idle at drain time — the mid-run claim is vacuous"
    )
    r0_submitted_at_drain = fes["r0"].books()["submitted"]
    router.drain_replica("r0")
    late = [router.submit(spec) for spec in specs[n:]]
    router.pump()
    books = _audit_fleet(router, run_dir, "serve_fleet_drain")
    # zero sheds attributable to the drain — or to anything else
    assert books["outcomes"]["shed"] == 0, books
    assert books["outcomes"]["ok"] == n + 2, books
    assert router._replicas["r0"].state == "drained"
    # dispatch stopped AT the drain: r0 took nothing after it...
    assert fes["r0"].books()["submitted"] == r0_submitted_at_drain, (
        fes["r0"].books(), r0_submitted_at_drain
    )
    # ...and every late submission landed on the survivor, served ok
    assert all(router._assigned[r.index] == "r1" for r in late), router._assigned
    assert all(r.outcome == "ok" for r in late), [vars(r) for r in late]
    # the drain lifecycle is first-class in the stream: drain -> drained
    transitions = [
        e["transition"] for e in _stream(run_dir)
        if e.get("event") == "serve.replica" and e.get("replica_id") == "r0"
    ]
    assert transitions == ["join", "drain", "drained"], transitions
    assert fes["r0"].ca_alloc.pages_used == 0 and fes["r1"].ca_alloc.pages_used == 0
    print(
        f"chaos: serve_fleet_drain ok — r0 drained mid-run with "
        f"{r0_submitted_at_drain} in its books, finished them all, "
        f"{len(late)} post-drain submissions routed to r1, "
        f"{n + 2}/{n + 2} ok with zero sheds"
    )


# ---------------------------------------------------------------------------
# Simline scenarios: multi-tenant pressure at simulated scale — the real
# engine control plane under a ManualClock with sampled service times
# (serving/sim.py; docs/serving.md#multi-tenant-telemetry). No jax, no
# model: tens of thousands of simulated requests in host-loop time.
# ---------------------------------------------------------------------------


def _sim_service_model():
    """A fixed synthetic service model for the chaos scenarios: the gate
    artifact (tools/sim.py) fits from a committed LOAD round; chaos wants
    pinned numbers so the pressure geometry never drifts with the
    artifact."""
    from perceiver_io_tpu.serving.sim import ServiceTimeModel

    return ServiceTimeModel(
        prefill_p50_s=0.002, prefill_p99_s=0.004,
        tpot_p50_s=0.0005, tpot_p99_s=0.001, source="chaos_synthetic",
    )


def scenario_sim_tenant_storm(tmp):
    """Simline tenant storm: one tenant floods at 10x each victim's rate,
    far over the engine's join capacity. Admission must degrade
    PROPORTIONALLY — demand-normalized shares stay near-equal (Jain >=
    0.9), neither victim starves (its achieved share holds within 35% of
    the flooder's, queue-wait p99 bounded), and every shed is a
    first-class tenant-stamped row with the books balancing at the full
    offered scale."""
    from perceiver_io_tpu.obs.slo import build_slo_report
    from perceiver_io_tpu.serving import EngineConfig, FrontEndConfig
    from perceiver_io_tpu.serving.sim import TenantSpec, run_sim

    window = 1.0 if SMOKE else 2.0
    tenants = [
        TenantSpec("victim_a", rate_rps=60.0, n_requests=int(60 * window),
                   prompt_lens=(8,), max_new_tokens=(4,), seed=11),
        TenantSpec("victim_b", rate_rps=60.0, n_requests=int(60 * window),
                   prompt_lens=(8, 12), max_new_tokens=(4, 6), seed=22),
        TenantSpec("flood", rate_rps=600.0, n_requests=int(600 * window),
                   prompt_lens=(8,), max_new_tokens=(4,), seed=33),
    ]
    recorder, clock, run_dir = _serve_env(tmp, "sim_tenant_storm")
    report = run_sim(
        tenants, service_model=_sim_service_model(),
        engine_config=EngineConfig(slots=8, page_size=8, max_ca_tokens=24,
                                   max_sa_tokens=8),
        config=FrontEndConfig(max_queue=64, admission_projection=False),
        events=recorder, clock=clock, seed=5,
    )
    s = report.summary
    books = _audit_serving(report.frontend, run_dir, "sim_tenant_storm")
    assert s["books_balanced"] and s["error_rate"] == 0.0, s["books"]
    # the storm was real: offered far over capacity, sheds happened
    assert s["shed_rate"] > 0.2, f"no real pressure: shed_rate {s['shed_rate']}"
    # ...and degraded FAIRLY: demand-normalized shares near-equal
    assert s["fairness_jain"] >= 0.9, (
        f"flood tenant skewed admission: fairness {s['fairness_jain']}, "
        f"tenants {s['tenants']}"
    )
    flood_share = s["tenants"]["flood"]["achieved_rps"] / 600.0
    for victim in ("victim_a", "victim_b"):
        share = s["tenants"][victim]["achieved_rps"] / 60.0
        assert share >= 0.65 * flood_share, (
            f"{victim} starved: share {share:.3f} vs flood {flood_share:.3f}"
        )
        qw = s["tenants"][victim].get("queue_wait_s")
        assert qw is not None and qw["p99"] <= 1.0, (
            f"{victim} queue-wait p99 unbounded under the storm: {qw}"
        )
    # every shed is a first-class tenant-stamped row — never a silent drop
    stream = _stream(run_dir)
    shed_rows = [e for e in stream if e.get("event") == "request"
                 and e.get("outcome") == "shed"]
    assert len(shed_rows) == books["shed"], (len(shed_rows), books["shed"])
    assert all(e.get("shed_reason") and e.get("tenant") for e in shed_rows)
    per_tenant_shed = sum(t["shed"] for t in s["tenants"].values())
    assert per_tenant_shed == books["shed"], (per_tenant_shed, books)
    assert any(e.get("event") == "sim.summary" for e in stream)
    slo = build_slo_report(stream, by_tenant=True)
    assert set(slo["tenants"]) == {"victim_a", "victim_b", "flood"}, slo.keys()
    print(
        f"chaos: sim_tenant_storm ok — flood offered 600 req/s vs 60+60 "
        f"victims ({s['n_requests']} requests, shed_rate {s['shed_rate']}), "
        f"fairness {s['fairness_jain']}, victim shares within 35% of the "
        f"flooder's, {books['shed']} sheds all tenant-stamped, books balanced"
    )


def scenario_sim_noisy_neighbor(tmp):
    """Simline noisy neighbor: a long-prompt/long-budget bulk tenant shares
    the engine with a latency-sensitive tenant under a page pool sized
    BELOW the combined demand (Evictline on) — the bulk pressure forces
    REAL evictions through the real allocator, yet both tenants reach
    ``ok`` on every request, parked work all resumes, and the PER-TENANT
    SLO machinery proves isolation: the latency tenant's planted
    near-zero TTFT bound (``SLOBounds.tenants``) trips flight dumps naming
    ONLY its rows while the bulk tenant's generous bound never fires."""
    from perceiver_io_tpu.obs.flightrec import SLOBounds
    from perceiver_io_tpu.obs.slo import build_slo_report
    from perceiver_io_tpu.serving import EngineConfig, FrontEndConfig
    from perceiver_io_tpu.serving.sim import TenantSpec, run_sim

    from perceiver_io_tpu.serving.sim import ServiceTimeModel

    n = 40 if SMOKE else 80
    tenants = [
        TenantSpec("lat", rate_rps=30.0, n_requests=n,
                   prompt_lens=(8,), max_new_tokens=(3, 4), seed=44),
        TenantSpec("bulk", rate_rps=30.0, n_requests=n,
                   prompt_lens=(16,), max_new_tokens=(12, 16), seed=55),
    ]
    recorder, clock, run_dir = _serve_env(tmp, "sim_noisy_neighbor")
    # the per-tenant bounds under test: lat's is a planted always-breach,
    # bulk's is generous — a shared bound could not tell them apart
    recorder.slo = SLOBounds(
        ttft_s=10.0, tenants={"lat": SLOBounds(ttft_s=1e-9)}
    )
    # a slower service model than _sim_service_model(): a bulk request
    # must OCCUPY its slot long enough (~90ms) that ~3 of them overlap on
    # the half-size pool — that overlap IS the page pressure under test
    slow = ServiceTimeModel(
        prefill_p50_s=0.005, prefill_p99_s=0.010,
        tpot_p50_s=0.004, tpot_p99_s=0.008, source="chaos_synthetic_slow",
    )
    report = run_sim(
        tenants, service_model=slow,
        engine_config=EngineConfig(slots=4, page_size=8, max_ca_tokens=32,
                                   max_sa_tokens=24, pool_headroom=0.5,
                                   eviction=True),
        config=FrontEndConfig(max_queue=64, admission_projection=False),
        events=recorder, clock=clock, seed=6,
    )
    s = report.summary
    fe = report.frontend
    books = _audit_serving(fe, run_dir, "sim_noisy_neighbor")
    # the pressure was real page pressure: evictions through the REAL
    # allocator, everything parked came back, pages exact after drain
    assert books["evictions"] >= 1 and books["evictions"] == books["resumes"], books
    assert books["parked"] == 0 and fe.ca_alloc.pages_used == 0, books
    assert fe.ca_alloc.audit() == [] and fe.sa_alloc.audit() == []
    # ...and STILL both tenants fully served: the neighbor was noisy, not lethal
    for name in ("lat", "bulk"):
        blk = s["tenants"][name]
        assert blk["ok"] == n and blk["shed"] == 0, (name, blk)
    stream = _stream(run_dir)
    evict_rows = [e for e in stream if e.get("event") == "serve.evict"]
    assert evict_rows and all(e.get("tenant") for e in evict_rows), (
        "serve.evict rows must be tenant-stamped"
    )
    # per-tenant SLO series: both sub-reports present, each over its own rows
    slo = build_slo_report(stream, by_tenant=True)
    assert set(slo["tenants"]) == {"lat", "bulk"}
    assert slo["tenants"]["lat"]["n_requests"] == n
    # the isolation proof: lat's planted bound tripped dumps naming ONLY
    # lat rows; bulk's TTFTs (same distribution) never tripped its own
    assert recorder.dumps, "lat's planted TTFT bound produced no flight dump"
    for path in recorder.dumps:
        with open(path) as f:
            dump = json.load(f)
        assert dump["trigger"] == "slo_ttft", dump["trigger"]
        assert dump["trigger_event"].get("tenant") == "lat", (
            f"dump names a non-lat row: {dump['trigger_event']}"
        )
    # the bulk tenant really held pages the victim didn't: per-tenant
    # pages-held peaks reflect the asymmetric footprints
    lat_peak = s["tenants"]["lat"]["pages_held_peak"] or 0
    bulk_peak = s["tenants"]["bulk"]["pages_held_peak"] or 0
    assert bulk_peak > lat_peak, (lat_peak, bulk_peak)
    print(
        f"chaos: sim_noisy_neighbor ok — bulk tenant forced "
        f"{books['evictions']} evictions (pool_headroom 0.5), {n}+{n} "
        f"requests all ok, per-tenant bounds tripped {len(recorder.dumps)} "
        f"dumps all naming 'lat' rows, pages peak bulk {bulk_peak:.0f} > "
        f"lat {lat_peak:.0f}, books balanced"
    )


def scenario_sim_prefix_skew(tmp):
    """Simline prefix skew (Shareline at simulated scale): an "agent"
    tenant whose prompts all open with one shared template prefix shares
    the engine with an "adhoc" tenant of unique prompts, both offered
    over the join capacity. The REAL sharing machinery runs (radix index,
    refcounted grants, expire-on-release) with the service model charging
    a matched join only its unmatched tokens — so the agent tenant's
    joins are structurally cheaper. The certification: that cheapness
    must show up WHERE it belongs (agent TTFT p50 well under adhoc's,
    every hit tenant-stamped + span-attributed) and NOWHERE else —
    admission stays demand-proportional (Jain >= 0.9, the
    ``sim_fairness_jain`` floor's bar), the adhoc tenant is not starved,
    refcounts balance and the index drains with its pages."""
    from perceiver_io_tpu.serving import EngineConfig, FrontEndConfig
    from perceiver_io_tpu.serving.sim import TenantSpec, run_sim

    window = 1.0 if SMOKE else 2.0
    tenants = [
        TenantSpec("agent", rate_rps=400.0, n_requests=int(400 * window),
                   prompt_lens=(16,), max_new_tokens=(4,), seed=71,
                   shared_prefix_len=8),
        TenantSpec("adhoc", rate_rps=400.0, n_requests=int(400 * window),
                   prompt_lens=(16,), max_new_tokens=(4,), seed=72),
    ]
    recorder, clock, run_dir = _serve_env(tmp, "sim_prefix_skew")
    report = run_sim(
        tenants, service_model=_sim_service_model(),
        engine_config=EngineConfig(slots=8, page_size=8, max_ca_tokens=24,
                                   max_sa_tokens=8),
        config=FrontEndConfig(max_queue=64, admission_projection=False),
        events=recorder, clock=clock, seed=9,
    )
    s = report.summary
    fe = report.frontend
    books = _audit_serving(fe, run_dir, "sim_prefix_skew")
    assert s["books_balanced"] and s["error_rate"] == 0.0, books
    assert s["shed_rate"] > 0.1, f"no real pressure: shed_rate {s['shed_rate']}"
    # the sharing was real: most of the agent tenant's admitted requests
    # matched at admission (the template run stays resident under
    # continuous pressure; a full-drain republish is the only miss)
    agent_ok = s["tenants"]["agent"]["ok"]
    assert fe._n_prefix_hits >= 0.5 * agent_ok, (fe._n_prefix_hits, agent_ok)
    assert s.get("prefix_hits") == fe._n_prefix_hits, s.get("prefix_hits")
    # ...attributed to the right tenant: every hit is the agent's, none
    # the adhoc tenant's (its unique prompts can never match)
    hits_c = fe.registry.counter("serve_prefix_hits_total")
    assert hits_c.labels(tenant="agent").value == fe._n_prefix_hits
    assert hits_c.labels(tenant="adhoc").value == 0
    # the service-time skew lands where it belongs: matched joins are
    # charged only their unmatched tokens, so agent TTFT p50 runs well
    # under adhoc's on the same engine
    agent_p50 = s["tenants"]["agent"]["ttft_s"]["p50"]
    adhoc_p50 = s["tenants"]["adhoc"]["ttft_s"]["p50"]
    assert agent_p50 <= 0.75 * adhoc_p50, (agent_p50, adhoc_p50)
    # ...and NOT in admission: cheaper joins must not skew fairness below
    # the committed sim_fairness_jain bar, nor starve the unique tenant
    assert s["fairness_jain"] >= 0.9, (
        f"prefix sharing skewed admission: fairness {s['fairness_jain']}, "
        f"tenants {s['tenants']}"
    )
    agent_share = s["tenants"]["agent"]["achieved_rps"] / 400.0
    adhoc_share = s["tenants"]["adhoc"]["achieved_rps"] / 400.0
    assert adhoc_share >= 0.65 * agent_share, (
        f"adhoc tenant starved: share {adhoc_share:.3f} vs agent {agent_share:.3f}"
    )
    # refcounts balanced at drain, index expired with its pages
    assert fe.sharing_audit() == [], fe.sharing_audit()
    assert fe.ca_alloc.pages_used == 0 and fe.prefix_index.pages() == ()
    stream = _stream(run_dir)
    hit_rows = [e for e in stream if e.get("event") == "serve.prefix_hit"]
    assert len(hit_rows) == fe._n_prefix_hits, (len(hit_rows), fe._n_prefix_hits)
    assert all(e.get("tenant") == "agent" for e in hit_rows)
    n_attr = _assert_span_attributed(run_dir)
    print(
        f"chaos: sim_prefix_skew ok — {s['n_requests']} requests "
        f"(shed_rate {s['shed_rate']}), agent hit {fe._n_prefix_hits}x "
        f"(ttft p50 {agent_p50 * 1e3:.2f}ms vs adhoc {adhoc_p50 * 1e3:.2f}ms), "
        f"fairness {s['fairness_jain']} held, refcounts balanced, "
        f"{n_attr} events span-attributed"
    )


def scenario_sim_fleet(tmp):
    """Fleetline scale certification: the SAME merged workload — 10k
    offered req/s across three tenants — through 1 then 2 replicas on
    the discrete-event fleet loop (per-replica ManualClocks, causal
    next-event drive, fleet duration = the latest replica timeline). Two
    replicas must deliver >= 1.7x the single-replica token throughput
    (the replication claim: near-linear scaling, honestly measured on
    independent timelines), and BOTH runs must hold the committed
    ``sim_fairness_jain`` (>= 0.9) and ``sim_starvation_age_s`` (<= 1.0)
    floors with fleet books balanced — scale that costs fairness or
    starves a tenant is not scale the ledger accepts."""
    from perceiver_io_tpu.serving import EngineConfig, FrontEndConfig
    from perceiver_io_tpu.serving.sim import TenantSpec, run_fleet_sim

    window = 0.06 if SMOKE else 0.12
    def _tenants():
        return [
            TenantSpec("burst", rate_rps=5000.0,
                       n_requests=int(5000 * window), seed=11),
            TenantSpec("steady", rate_rps=3500.0,
                       n_requests=int(3500 * window), seed=22),
            TenantSpec("trickle", rate_rps=1500.0,
                       n_requests=int(1500 * window), seed=33),
        ]

    engine_cfg = EngineConfig(slots=16, page_size=8, max_ca_tokens=32,
                              max_sa_tokens=16)
    fe_cfg = FrontEndConfig(max_queue=256, admission_projection=False,
                            breaker=None)
    summaries = {}
    for n_replicas in (1, 2):
        recorder, _clock, run_dir = _serve_env(tmp, f"sim_fleet_{n_replicas}")
        report = run_fleet_sim(
            _tenants(), n_replicas=n_replicas,
            service_model=_sim_service_model(), engine_config=engine_cfg,
            config=fe_cfg, events=recorder,
        )
        s = report.summary
        _audit_fleet(report.router, run_dir, f"sim_fleet_{n_replicas}")
        assert s["books_balanced"], s["books"]
        assert s["offered_rps"] >= 10000.0, s["offered_rps"]
        # the committed sim floors hold at EVERY fleet size
        assert s["fairness_jain"] >= 0.9, (
            f"sim_fleet[{n_replicas}]: fairness {s['fairness_jain']} "
            f"under the committed floor: {s['tenants']}"
        )
        assert s["max_starvation_age_s"] <= 1.0, (
            f"sim_fleet[{n_replicas}]: starvation "
            f"{s['max_starvation_age_s']}s over the committed ceiling"
        )
        summaries[n_replicas] = s
    ratio = summaries[2]["throughput_tok_s"] / summaries[1]["throughput_tok_s"]
    assert ratio >= 1.7, (
        f"2 replicas scaled only {ratio:.3f}x "
        f"({summaries[1]['throughput_tok_s']} -> "
        f"{summaries[2]['throughput_tok_s']} tok/s) — under the 1.7x bar"
    )
    print(
        f"chaos: sim_fleet ok — {summaries[2]['n_requests']} requests at "
        f"{summaries[2]['offered_rps']:.0f} offered rps, "
        f"{summaries[1]['throughput_tok_s']:.1f} -> "
        f"{summaries[2]['throughput_tok_s']:.1f} tok/s ({ratio:.2f}x >= 1.7x), "
        f"fairness {summaries[2]['fairness_jain']} / starvation "
        f"{summaries[2]['max_starvation_age_s']}s floors held at both sizes"
    )


SCENARIOS = {
    "preempt": scenario_preempt,
    "preempt_mesh": scenario_preempt_mesh,
    "fetch_error": scenario_fetch_error,
    "nan_skip": scenario_nan_skip,
    "nan_rollback": scenario_nan_rollback,
    "torn_save": scenario_torn_save,
    "elastic_shrink": scenario_elastic_shrink,
    "elastic_grow": scenario_elastic_grow,
    "flat_to_mesh": scenario_flat_to_mesh,
    "mesh_to_flat": scenario_mesh_to_flat,
    "serve_overload": scenario_serve_overload,
    "serve_kill_mid_decode": scenario_serve_kill_mid_decode,
    "serve_deadline": scenario_serve_deadline,
    "serve_drain": scenario_serve_drain,
    "serve_breaker": scenario_serve_breaker,
    "serve_engine_kill_mid_decode": scenario_serve_engine_kill_mid_decode,
    "serve_engine_pages": scenario_serve_engine_pages,
    "serve_spec_kill_mid_span": scenario_serve_spec_kill_mid_span,
    "serve_evict_storm": scenario_serve_evict_storm,
    "serve_prefix_storm": scenario_serve_prefix_storm,
    "serve_crash_recover": scenario_serve_crash_recover,
    "serve_fleet_failover": scenario_serve_fleet_failover,
    "serve_fleet_brownout": scenario_serve_fleet_brownout,
    "serve_fleet_drain": scenario_serve_fleet_drain,
    "sim_tenant_storm": scenario_sim_tenant_storm,
    "sim_noisy_neighbor": scenario_sim_noisy_neighbor,
    "sim_prefix_skew": scenario_sim_prefix_skew,
    "sim_fleet": scenario_sim_fleet,
}


def _respawn(scenarios, n_devices=8, phase=None, tmp=None) -> int:
    """Re-exec scenarios in a subprocess with ``n_devices`` virtual CPU
    devices (same bootstrap contract as
    __graft_entry__._respawn_with_virtual_devices: set XLA_FLAGS before any
    device query, force the platform via jax.config). ``phase``/``tmp``
    pass through to the child's argv — the elastic scenarios use this to
    run their kill and resume halves on DIFFERENT topologies over one
    shared scratch dir."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    argv = ["chaos.py", "--scenarios", ",".join(scenarios)]
    if phase:
        argv += ["--phase", phase]
    if tmp:
        argv += ["--tmp", tmp]
    bootstrap = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        f"import sys; sys.path.insert(0, {repo!r})\n"
        f"import runpy; sys.argv = {argv!r}\n"
        f"runpy.run_path({os.path.abspath(__file__)!r}, run_name='__main__')\n"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["_CHAOS_RESPAWNED"] = "1"
    flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "", env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    proc = subprocess.run([sys.executable, "-c", bootstrap], cwd=repo, env=env, timeout=540)
    return proc.returncode


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--scenarios",
        default=",".join(SCENARIOS),
        help="comma-separated scenario names and/or fnmatch globs "
        f"(e.g. 'serve_*' or 'elastic_*,preempt') over: {', '.join(SCENARIOS)}",
    )
    parser.add_argument("--tmp", default=None, help="scratch dir (default: mkdtemp)")
    parser.add_argument(
        "--smoke", action="store_true",
        help="CI-fast Evictline scenarios (greedy-only, fewer requests; "
        "same assertions) — the tasks.py perf serve-chaos leg",
    )
    parser.add_argument(
        "--phase",
        default=None,
        choices=("kill", "resume"),
        help="internal: run one half of an elastic scenario (the orchestrator "
        "respawns each half with its own virtual-device count)",
    )
    args = parser.parse_args(argv)
    global SMOKE
    SMOKE = bool(args.smoke)
    # each comma token is a literal name or an fnmatch glob; a token that
    # matches nothing is a usage error (a typo'd selector silently running
    # zero scenarios would read as a green gate)
    import fnmatch

    wanted = []
    for token in (t.strip() for t in args.scenarios.split(",")):
        if not token:
            continue
        matches = [s for s in SCENARIOS if fnmatch.fnmatch(s, token)]
        if not matches:
            parser.error(
                f"scenario selector {token!r} matches nothing "
                f"(known: {', '.join(SCENARIOS)})"
            )
        wanted.extend(m for m in matches if m not in wanted)
    if args.phase and any(s not in ELASTIC_SCENARIOS for s in wanted):
        parser.error("--phase applies only to the elastic scenarios")

    import jax

    run_local = list(wanted)
    rc = 0
    if (
        "preempt_mesh" in run_local
        and len(jax.devices()) < 8
        and not os.environ.get("_CHAOS_RESPAWNED")
    ):
        # mesh case needs 8 devices: run it in a virtual-device subprocess,
        # everything else in this process (the elastic scenarios manage
        # their OWN per-phase subprocesses and never need a parent respawn)
        run_local.remove("preempt_mesh")
        rc = _respawn(["preempt_mesh"])
        if rc != 0:
            print("chaos: preempt_mesh FAILED (respawned subprocess)", file=sys.stderr)

    import tempfile

    tmp = args.tmp or tempfile.mkdtemp(prefix="chaos_")
    for name in run_local:
        if name in ELASTIC_SCENARIOS:
            SCENARIOS[name](tmp, phase=args.phase)
        else:
            SCENARIOS[name](tmp)
    if rc == 0 and not args.phase:
        print(f"chaos: all {len(wanted)} scenario(s) passed")
    return rc


if __name__ == "__main__":
    sys.exit(main())
