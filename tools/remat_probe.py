"""Measure HBM for the train step with/without activation checkpointing at
the 455M-class FSDP geometry (reference: examples/training/clm/train_fsdp.sh —
the config whose single-chip viability depends on remat).

Uses XLA's compile-time memory analysis (``compiled.memory_analysis()``), so
nothing is executed: works at sizes that would OOM, and reports the exact
buffer assignment the real run would use.

    python tools/remat_probe.py --num-channels 1024 --layers 16 --seq-len 6144 ...
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def hbm_bytes(config, batch_size: int, latents: int, seq_len: int):
    from perceiver_io_tpu.models.text import CausalLanguageModel
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step

    model = CausalLanguageModel(config, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    t = rng.integers(0, config.vocab_size, size=(batch_size, seq_len + 1))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    # init with a tiny slice: we only need the param shapes
    params = jax.eval_shape(
        lambda r: model.init(r, batch["input_ids"][:, : latents + 1], prefix_len=1),
        jax.random.PRNGKey(0),
    )
    params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)
    tx = make_optimizer(1e-3, gradient_clip=1.0)
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
    step = make_train_step(clm_loss_fn(model.apply, max_latents=latents), jit=False)

    lowered = jax.jit(step, donate_argnums=(0,)).lower(state, batch)
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    return mem, n_params


def fmt(n):
    return f"{n / 2**30:.2f}G"


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=6144)
    p.add_argument("--latents", type=int, default=2048)
    p.add_argument("--num-channels", type=int, default=1024)
    p.add_argument("--layers", type=int, default=16)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--batch-size", type=int, default=2)
    args = p.parse_args()

    from perceiver_io_tpu.models.text import CausalLanguageModelConfig

    for remat in (False, True):
        config = CausalLanguageModelConfig(
            vocab_size=args.vocab_size,
            max_seq_len=args.seq_len,
            max_latents=args.latents,
            num_channels=args.num_channels,
            num_heads=args.heads,
            num_self_attention_layers=args.layers,
            cross_attention_dropout=0.5,
            activation_checkpointing=remat,
        )
        try:
            mem, n_params = hbm_bytes(config, args.batch_size, args.latents, args.seq_len)
        except Exception as e:  # XLA raises on un-fittable allocations
            print(f"remat={remat}: COMPILE FAILED: {type(e).__name__}: {str(e)[:300]}")
            continue
        print(
            f"remat={remat}: params={n_params/1e6:.0f}M "
            f"temp={fmt(mem.temp_size_in_bytes)} "
            f"argument={fmt(mem.argument_size_in_bytes)} "
            f"output={fmt(mem.output_size_in_bytes)} "
            f"alias={fmt(mem.alias_size_in_bytes)} "
            f"peak_temp+args={fmt(mem.temp_size_in_bytes + mem.argument_size_in_bytes)}"
        )


if __name__ == "__main__":
    main()
