"""Minimal XSpace (xplane.pb) reader CLI — aggregate device-op durations from
a ``jax.profiler.trace`` capture without TensorFlow/tensorboard installed.

The implementation lives in ``perceiver_io_tpu/obs/xplane.py`` (this file
shims to it so existing ``python tools/xplane.py <capture>`` invocations and
importers keep working); the library adds a per-named-scope rollup on top of
the raw per-op totals (``--by-scope``).

Usage: python tools/xplane.py <capture_dir_or_pb> [--top 30] [--by-scope]
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

# load obs/xplane.py BY PATH, not through the package: the tool's point is
# reading a copied capture on any box with a bare python — importing
# perceiver_io_tpu would execute the package __init__ and require jax/flax
_impl_path = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "perceiver_io_tpu",
    "obs",
    "xplane.py",
)
_spec = importlib.util.spec_from_file_location("_obs_xplane", _impl_path)
_impl = importlib.util.module_from_spec(_spec)
sys.modules[_spec.name] = _impl  # dataclass decoration resolves via sys.modules
_spec.loader.exec_module(_impl)

PlaneSummary = _impl.PlaneSummary
ScopeRollup = _impl.ScopeRollup
fields = _impl.fields
iter_planes = _impl.iter_planes
parse_line_events = _impl.parse_line_events
parse_plane = _impl.parse_plane
resolve_capture = _impl.resolve_capture
rollup = _impl.rollup
rollup_planes = _impl.rollup_planes
scope_of = _impl.scope_of
summarize = _impl.summarize

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("path")
    p.add_argument("--top", type=int, default=30)
    p.add_argument("--line", default="", help="only lines whose name contains this")
    p.add_argument(
        "--by-scope",
        action="store_true",
        help="aggregate by jax.named_scope / module path instead of raw HLO op name",
    )
    p.add_argument(
        "--depth", type=int, default=None, help="truncate scope paths to this many components"
    )
    args = p.parse_args()
    summarize(args.path, args.top, args.line, by_scope=args.by_scope, depth=args.depth)
