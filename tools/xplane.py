"""Minimal XSpace (xplane.pb) reader — aggregate device-op durations from a
``jax.profiler.trace`` capture without TensorFlow/tensorboard installed.

Wire-format notes (tensorflow/core/profiler/protobuf/xplane.proto):
  XSpace:        planes = 1 (repeated XPlane)
  XPlane:        id=1, name=2, lines=3 (repeated XLine),
                 event_metadata=4 (map<int64, XEventMetadata>),
                 stat_metadata=5
  XLine:         id=1, display_name? name=2/3, events=6? — fields probed
  XEvent:        metadata_id=1, offset_ps=2, duration_ps=3
  XEventMetadata: id=1, name=2

Usage: python tools/xplane.py <capture_dir_or_pb> [--top 30]
"""

from __future__ import annotations

import argparse
import collections
import glob
import os


def _varint(buf: bytes, i: int):
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a protobuf message."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:
            val, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            val = buf[i : i + ln]
            i += ln
        elif wt == 5:
            val = int.from_bytes(buf[i : i + 4], "little")
            i += 4
        elif wt == 1:
            val = int.from_bytes(buf[i : i + 8], "little")
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fnum, wt, val


def parse_plane(plane: bytes):
    name = ""
    metadata = {}
    lines = []
    for fnum, wt, val in fields(plane):
        if fnum == 2 and wt == 2:
            name = val.decode(errors="replace")
        elif fnum == 3 and wt == 2:
            lines.append(val)
        elif fnum == 4 and wt == 2:
            # map entry: key=1 varint, value=2 XEventMetadata
            k = v = None
            for f2, w2, v2 in fields(val):
                if f2 == 1:
                    k = v2
                elif f2 == 2:
                    v = v2
            if k is not None and v is not None:
                mname = ""
                mdisplay = ""
                for f3, w3, v3 in fields(v):
                    if f3 == 2 and w3 == 2:
                        mname = v3.decode(errors="replace")
                    elif f3 == 3 and w3 == 2:
                        mdisplay = v3.decode(errors="replace")
                metadata[k] = mdisplay or mname
    return name, metadata, lines


def parse_line_events(line: bytes):
    """Yield (metadata_id, duration_ps) for each XEvent on the line."""
    lname = ""
    evs = []
    for fnum, wt, val in fields(line):
        if fnum in (2, 11) and wt == 2:
            lname = val.decode(errors="replace") or lname
        elif fnum == 4 and wt == 2:  # XLine.events
            mid = dur = 0
            for f2, w2, v2 in fields(val):
                if f2 == 1:
                    mid = v2
                elif f2 == 3:
                    dur = v2
            evs.append((mid, dur))
    for mid, dur in evs:
        yield lname, mid, dur


def summarize(path: str, top: int = 30, line_filter: str = ""):
    if os.path.isdir(path):
        pbs = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"), recursive=True))
        if not pbs:
            raise FileNotFoundError(f"no xplane.pb under {path}")
        path = pbs[-1]
    buf = open(path, "rb").read()
    print(f"{path} ({len(buf)/1e6:.0f} MB)")
    for fnum, wt, plane in fields(buf):
        if fnum != 1 or wt != 2:
            continue
        name, metadata, lines = parse_plane(plane)
        per_op = collections.Counter()
        counts = collections.Counter()
        per_line = collections.Counter()
        for line in lines:
            for lname, mid, dur in parse_line_events(line):
                if line_filter and line_filter not in lname:
                    continue
                op = metadata.get(mid, f"#{mid}")
                per_op[op] += dur
                counts[op] += 1
                per_line[lname] += dur
        if not per_op:
            continue
        total = sum(per_line.values())
        print(f"\n=== plane: {name} | lines: {dict(per_line.most_common(6))}")
        print(f"    sum of event time: {total/1e9:.3f} ms")
        for op, d in per_op.most_common(top):
            print(f"  {d/1e9:9.3f} ms {counts[op]:6d}x  {op[:100]}")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("path")
    p.add_argument("--top", type=int, default=30)
    p.add_argument("--line", default="", help="only lines whose name contains this")
    args = p.parse_args()
    summarize(args.path, args.top, args.line)
