"""graphlint CLI — static analysis of the flagship compiled graphs.

Lints the flagship train step, prefill and decode functions
(perceiver_io_tpu/analysis/flagship.py builds the same programs bench.py
measures) against the full rule set and prints a human report per target
plus, optionally, one JSON artifact. Exit status follows ``--fail-on``, so
this is the CI gate `tasks.py graphlint` wraps:

    python tools/graphlint.py --fail-on error
    python tools/graphlint.py --geometry flagship --no-compiled   # trace-only
    python tools/graphlint.py --kernel-features twoseg            # A/B the lint
    python tools/graphlint.py --json graphlint.json --allow 'hot-concat:*mlp*'
    python tools/graphlint.py --mesh data=2,fsdp=4 --targets train  # sharded step
    python tools/graphlint.py --programs all --no-compiled  # the 5 graphcheck
                                                            # programs, dataflow rules

``--mesh data=N[,fsdp=M]`` lints the SHARDED flagship train step — by
default the overlap-scheduled shard_map step (parallel/overlap.py) with the
``collective-overlap`` rule armed and a collective budget derived from its
bucket plan; ``--overlap off`` lints the GSPMD step instead. When the host
has fewer devices than the mesh needs, the CLI re-execs itself with that
many virtual CPU devices (the __graft_entry__ dryrun trick).

``--programs all`` lints the five graphcheck programs (train_flat,
train_sharded, train_overlap, prefill, decode) with per-program policies
that arm the dataflow rules — rng-key-reuse, dead-compute, sharding-flow
(on the sharded steps), cross-program-consistency (decode vs prefill).
This is the gate ``tasks.py perf`` runs after graphcheck.

Exit codes: 0 — no violation at/above ``--fail-on``; 1 — violations found;
2 — usage error (e.g. an unknown ``--rules``/``--programs`` name — the
message lists what is registered); 3 — a rule or target build CRASHED (the
lint itself is broken, which CI must not confuse with either verdict).
The contract is shared with tools/hostlint.py through
perceiver_io_tpu/analysis/lintcli.py.

Rule catalog and allowlist syntax: docs/static-analysis.md.
"""

from __future__ import annotations

import argparse
import json
import sys

from perceiver_io_tpu.analysis.lintcli import (
    add_common_lint_args,
    finish_lint,
    lint_crashed,
    parse_rules,
)


def _ensure_devices(n: int) -> None:
    """Re-exec with ``n`` virtual CPU devices when fewer are visible
    (shared respawn: utils/compat.respawn_cli_with_virtual_devices)."""
    from perceiver_io_tpu.utils.compat import respawn_cli_with_virtual_devices

    respawn_cli_with_virtual_devices(n, __file__, "_GRAPHLINT_RESPAWNED")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--geometry", choices=("micro", "flagship"), default="micro",
                   help="micro (default): flagship architecture at toy sizes — "
                        "graph-shape rules are geometry-invariant and this "
                        "compiles in seconds on CPU; flagship: the real 16k "
                        "geometry (compiling it is a TPU-sized job — pair "
                        "with --no-compiled elsewhere)")
    p.add_argument("--targets", default="train,prefill,decode",
                   help="comma list of flagship functions to lint")
    p.add_argument("--programs", default=None, metavar="P1,P2|all",
                   help="lint the five graphcheck programs instead of the "
                        "--targets trio: train_flat, train_sharded (GSPMD), "
                        "train_overlap (shard_map), prefill, decode — 'all' "
                        "or a comma list; the sharded pair re-execs with "
                        "virtual CPU devices when the host is short. This is "
                        "the dataflow-rule gate `tasks.py perf` runs")
    add_common_lint_args(
        p,
        allow_help="extra allowlist entry (repeatable), fnmatch-ed against "
                   "'rule' and 'rule:scope' — e.g. 'hot-concat:*decode*'",
    )
    p.add_argument("--compiled", dest="compiled", action="store_true", default=None,
                   help="force lowering+compiling (the donation/collective rules)")
    p.add_argument("--no-compiled", dest="compiled", action="store_false",
                   help="forbid compiling — trace-only rules")
    p.add_argument("--kernel-features", default=None,
                   help="trace-time flash kernel feature set to lint under: "
                        "'all', 'none', or a comma list (e.g. 'twoseg') — same "
                        "tokens as bench.py --kernel-features")
    p.add_argument("--collective-budget", default=None,
                   help="JSON dict enabling the collective-budget rule, e.g. "
                        "'{\"all-gather\": 2, \"total\": 4}'")
    p.add_argument("--mesh", default=None, metavar="data=N[,fsdp=M]",
                   help="shard the train target over this data/fsdp mesh and "
                        "lint the distributed step (re-execs with virtual CPU "
                        "devices when the host has too few)")
    p.add_argument("--overlap", choices=("on", "off"), default="on",
                   help="with --mesh: lint the overlap-scheduled shard_map "
                        "step (on, default — arms the collective-overlap rule "
                        "and a derived collective budget) or the GSPMD step (off)")
    args = p.parse_args(argv)

    from perceiver_io_tpu.analysis.rules import RULES

    rules = parse_rules(p, args.rules, RULES)

    programs = None
    if args.programs:
        from perceiver_io_tpu.analysis.flagship import DEFAULT_MESH_SPEC, PROGRAMS

        programs = (
            tuple(PROGRAMS)
            if args.programs == "all"
            else tuple(x for x in args.programs.split(",") if x)
        )
        unknown_programs = [x for x in programs if x not in PROGRAMS]
        if unknown_programs:
            p.error(
                f"unknown program(s) {', '.join(unknown_programs)}; known: "
                f"{', '.join(PROGRAMS)}"
            )
        if any(x in ("train_sharded", "train_overlap") for x in programs):
            from perceiver_io_tpu.parallel.overlap import parse_mesh_spec, required_devices

            _ensure_devices(required_devices(parse_mesh_spec(DEFAULT_MESH_SPEC)))

    mesh = None
    if args.mesh:
        from perceiver_io_tpu.parallel.overlap import (
            mesh_from_spec,
            parse_mesh_spec,
            required_devices,
        )

        _ensure_devices(required_devices(parse_mesh_spec(args.mesh)))
        mesh = mesh_from_spec(args.mesh)

    from perceiver_io_tpu.analysis.flagship import lint_flagship, lint_programs

    features = None
    if args.kernel_features is not None:
        from perceiver_io_tpu.ops.flash_attention import ALL_FEATURES

        features = {
            "all": tuple(ALL_FEATURES), "none": ()
        }.get(args.kernel_features, tuple(f for f in args.kernel_features.split(",") if f))

    budget = json.loads(args.collective_budget) if args.collective_budget else None
    try:
        if programs is not None:
            reports = lint_programs(
                programs,
                geometry=args.geometry,
                rules=rules,
                allow=tuple(args.allow),
                compiled=args.compiled,
                features=features,
            )
        else:
            reports = lint_flagship(
                geometry=args.geometry,
                targets=tuple(t for t in args.targets.split(",") if t),
                rules=rules,
                allow=tuple(args.allow),
                compiled=args.compiled,
                collective_budget=budget,
                features=features,
                mesh=mesh,
                overlap=args.overlap == "on",
            )
    except Exception as e:  # noqa: BLE001 — a rule/build CRASH is not a verdict
        # exit 3, distinct from 1 (violations found): CI must not read "the
        # linter itself broke" as "the graph got worse" — or, with
        # --fail-on none, as a pass
        return lint_crashed("graphlint", e)

    return finish_lint("graphlint", reports, fail_on=args.fail_on,
                       json_path=args.json)


if __name__ == "__main__":
    sys.exit(main())
