"""Same-process interleaved A/B of attention-path policies on the flagship
train step. Cross-process comparisons are untrustworthy on this chip (clock
drifts 1.5-1.8x between burst and sustained); here every variant is traced in
ONE process and the slope measurements interleave A/B/C round-robin so drift
hits all variants equally.

Variants: all-flash, auto policy (SA einsum + CA flash), all-einsum.

    python tools/flash_ab.py [--batch-size 1] [--steps 20]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, interleaved_slopes

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=16384)
    p.add_argument("--latents", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--variants", nargs="*", default=["flash", "auto", "einsum"])
    args = p.parse_args()

    from perceiver_io_tpu.models.text import CausalLanguageModel
    from perceiver_io_tpu.ops.flash_attention import set_default_flash
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step

    config = flagship_config(args.seq_len, args.latents)
    model = CausalLanguageModel(config, dtype=jnp.bfloat16)

    b, n = args.batch_size, args.seq_len
    rng = np.random.default_rng(0)
    t = rng.integers(0, config.vocab_size, size=(b, n + 1))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"][:, : args.latents + 1], prefix_len=1)
    tx = make_optimizer(1e-3, gradient_clip=1.0)
    state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
    step = make_train_step(clm_loss_fn(model.apply, max_latents=args.latents), jit=False)

    def make_run():
        # fresh jit wrapper per variant: the flash default is read at trace
        # time, so each variant's traces are pinned at compile below
        @functools.partial(jax.jit, static_argnums=2)
        def run(state, batch, k):
            def body(c, i):
                l, s = c
                s, metrics = step(s, batch)
                return (l + metrics["loss"], s), ()

            (l, _), _ = jax.lax.scan(body, (jnp.float32(0), state), jnp.arange(k))
            return l

        return lambda k: float(run(state, batch, k))

    modes = {"flash": True, "auto": None, "einsum": False}
    n_short, n_long = 2, 2 + args.steps
    runs = {}
    for name in args.variants:
        set_default_flash(modes[name])
        runs[name] = make_run()
        t0 = time.perf_counter()
        runs[name](n_short)  # compile short
        runs[name](n_long)  # compile long
        print(f"{name}: compiled in {time.perf_counter() - t0:.0f}s", flush=True)
    set_default_flash(None)

    meds = interleaved_slopes(runs, n_short, n_long, reps=args.reps)
    print(f"{'variant':<8} {'ms/step':>8} {'tok/s':>12}")
    for v in args.variants:
        med = meds[v]
        if med is None:
            print(f"{v:<8}  all slope estimates non-positive (tunnel stall?) — rerun")
            continue
        print(f"{v:<8} {med * 1e3:8.3f} {b * n / med:12.0f}")


if __name__ == "__main__":
    main()
