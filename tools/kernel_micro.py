"""Flash-kernel microbenchmark on the flagship attention geometries.

Times the packed kernels ALONE (forward, and forward+backward) on the exact
CA/SA shapes of the 16k flagship at batch 4, against their matmul rooflines,
so kernel-internal changes can be iterated without 4-minute full-model
compiles. Same-process variant interleaving (see tools/kernel_ab.py for why
cross-process comparisons are untrustworthy here).

    python tools/kernel_micro.py [--variants all none] [--fwd-only]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import interleaved_slopes  # noqa: E402  (repo root on sys.path above)

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# flagship attention geometries at batch 4 (16k ctx, 1024 latents, 8 x 64
# heads, 0.5 prefix dropout -> CA kv 8704)
GEOMS = {
    "ca": dict(b=4, nq=1024, nkv=8704, h=8, d=64),
    "sa": dict(b=4, nq=1024, nkv=1024, h=8, d=64),
}
PEAK_TFLOPS = 197e12  # v5e bf16
# roofline denominator: measured CA-fwd runs at >100% of a 0.5x ceiling, so
# K=64 contractions are NOT half-rate on this chip — report vs full peak
MXU_CEILING = 1.0


# score-tile matmuls executed per alive kernel: fwd kernel = s + o; dq
# kernel = recompute-s + dp + dq; dkv kernel = recompute-s + dv + dp + dk
_CHAIN_MATMULS = {"fwd": 2, "dq": 2 + 3, "dkv": 2 + 4, "fwdbwd": 2 + 3 + 4}


def roofline_ms(g, chain: str) -> float:
    per_head = 2 * g["nq"] * g["nkv"] * g["d"]  # one tile matmul (x2 flops)
    flops = 2 * per_head * _CHAIN_MATMULS[chain] * g["h"] * g["b"]
    return flops / (PEAK_TFLOPS * MXU_CEILING) * 1e3


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--variants", nargs="*", default=["none", "all"])
    p.add_argument("--geoms", nargs="*", default=["ca", "sa"])
    p.add_argument("--fwd-only", action="store_true")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--block-q", type=int, default=None)
    p.add_argument("--block-kv", type=int, default=None)
    args = p.parse_args()

    import perceiver_io_tpu.ops.flash_attention
    fa = sys.modules["perceiver_io_tpu.ops.flash_attention"]

    def mode(name):
        # "bkv1088" / "bq512": round-2 kernels with BWD_BLOCK_KV/Q overridden
        if name.startswith("bkv") or name.startswith("bq"):
            return False
        return True if name == "all" else False if name == "none" else name.split(",")

    def bwd_blocks(name):
        if name.startswith("bkv"):
            return None, int(name[3:])
        if name.startswith("bq"):
            return int(name[2:]), None
        return None, None

    rng = np.random.default_rng(0)
    runs = {}  # (variant, geom, mode) -> fn(iters) -> float
    for gname in args.geoms:
        g = GEOMS[gname]
        q = jnp.asarray(rng.normal(size=(g["b"], g["nq"], g["h"] * g["d"])), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(g["b"], g["nkv"], g["h"] * g["d"])), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(g["b"], g["nkv"], g["h"] * g["d"])), jnp.bfloat16)

        for vname in args.variants:
            fa.set_fast_kernels(mode(vname))
            fa.BWD_BLOCK_Q, fa.BWD_BLOCK_KV = bwd_blocks(vname)

            def attn(q, k, v):
                return fa.flash_attention_packed(
                    q, k, v, num_heads=g["h"], causal=True, sm_scale=g["d"] ** -0.5,
                    block_q=args.block_q, block_kv=args.block_kv,
                )

            @functools.partial(jax.jit, static_argnums=3)
            def fwd_chain(q, k, v, iters):
                def body(c, _):
                    o = attn(c, k, v)
                    # feed output back through q so steps serialize
                    return o.astype(c.dtype), ()

                c, _ = jax.lax.scan(body, q, None, length=iters)
                return jnp.sum(c.astype(jnp.float32))

            def loss(q, k, v):
                return jnp.sum(attn(q, k, v).astype(jnp.float32))

            # per-kernel isolation: a gradient that is not fed back into the
            # carry is dead code and XLA REMOVES its kernel (observed:
            # impossible >100%-of-roofline readings). 'dq' keeps fwd+dq
            # kernels alive; 'dkv' keeps fwd+dkv alive; a *0 contribution
            # would likewise DCE the whole backward.
            eps = jnp.bfloat16(1e-3)

            @functools.partial(jax.jit, static_argnums=3)
            def dq_chain(q, k, v, iters):
                def body(c, _):
                    dq = jax.grad(loss, argnums=0)(c, k, v)
                    return (c + dq.astype(c.dtype) * eps).astype(c.dtype), ()

                c, _ = jax.lax.scan(body, q, None, length=iters)
                return jnp.sum(c.astype(jnp.float32))

            @functools.partial(jax.jit, static_argnums=3)
            def dkv_chain(q, k, v, iters):
                def body(c, _):
                    ck, cv = c
                    dk, dv = jax.grad(loss, argnums=(1, 2))(q, ck, cv)
                    return (
                        (ck + dk.astype(ck.dtype) * eps).astype(ck.dtype),
                        (cv + dv.astype(cv.dtype) * eps).astype(cv.dtype),
                    ), ()

                (ck, cv), _ = jax.lax.scan(body, (k, v), None, length=iters)
                return jnp.sum(ck.astype(jnp.float32)) + jnp.sum(cv.astype(jnp.float32))

            @functools.partial(jax.jit, static_argnums=3)
            def fwdbwd_chain(q, k, v, iters):
                def body(c, _):
                    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(c, k, v)
                    keep = (
                        jnp.sum(dk.astype(jnp.float32)) + jnp.sum(dv.astype(jnp.float32))
                    ).astype(c.dtype)
                    return (c + dq.astype(c.dtype) * eps + keep * eps).astype(c.dtype), ()

                c, _ = jax.lax.scan(body, q, None, length=iters)
                return jnp.sum(c.astype(jnp.float32))

            chains = {"fwd": fwd_chain}
            if not args.fwd_only:
                chains.update({"dq": dq_chain, "dkv": dkv_chain, "fwdbwd": fwdbwd_chain})
            for cname, chain in chains.items():
                fn = lambda it, ch=chain, q=q, k=k, v=v: float(ch(q, k, v, it))
                # compile NOW, while this variant's trace-time flag is
                # active — jit traces lazily, so deferring the first call
                # would trace every variant with the LAST flag value
                t0 = time.perf_counter()
                fn(2)
                fn(2 + args.iters)
                print(f"{(vname, gname, cname)}: compiled in {time.perf_counter() - t0:.0f}s", flush=True)
                runs[(vname, gname, cname)] = fn
    fa.BWD_BLOCK_Q = fa.BWD_BLOCK_KV = None
    fa.set_fast_kernels(False)  # library default (round-2 kernels)

    n_short, n_long = 2, 2 + args.iters

    # interleave ALL variants inside each rep (bench.interleaved_slopes) —
    # sequential per-variant robust_slope windows minutes apart are swamped
    # by the chip's 1.5-1.8x burst-vs-sustained clock drift (observed:
    # fwd+bwd reading "faster" than fwd alone)
    meds = interleaved_slopes(runs, n_short, n_long)
    results = {k: (float("inf") if m is None else m) for k, m in meds.items()}

    print(f"\n{'variant':<22} {'geom':<4} {'pass':<7} {'ms':>8} {'roofline':>9} {'% of ceil':>9}")
    for (vname, gname, cname), t in results.items():
        ms = t * 1e3
        roof = roofline_ms(GEOMS[gname], cname)
        print(f"{vname:<22} {gname:<4} {cname:<7} {ms:8.3f} {roof:9.3f} {100 * roof / ms:8.1f}%")


if __name__ == "__main__":
    main()
