"""Same-process interleaved A/B of the overlap-scheduled distributed train
step (parallel/overlap.py: chunk-interleaved gradient reduce-scatter +
bucket-chained FSDP all-gather prefetch) against the GSPMD step, across mesh
shapes — the staged measurement docs/performance.md round 7 calls for before
the overlap path graduates from its default-off gate.

Variants are ``<mesh-spec>`` x ``{overlap, gspmd}``; both members of each
mesh pair run in ONE process, visited round-robin (cross-process comparisons
drift 1.5-1.8x with the chip clock — docs/performance.md):

    # TPU pod slice / multi-chip host:
    python tools/overlap_ab.py --mesh data=4 data=2,fsdp=2 --batch-size 32

    # CPU smoke of the harness itself (numbers meaningless, wiring real):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/overlap_ab.py --micro --mesh data=2,fsdp=4 --steps 4

Each variant's per-step time comes from bench.interleaved_slopes (min-reduced
reps, median of estimates, non-positive slopes dropped). ``--microbatch``
controls the chunk count the interleaving claim rides on (>= 2 to matter).
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, interleaved_slopes

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mesh", nargs="+", default=["data=2,fsdp=2"],
                   help="mesh specs to A/B, e.g. data=4 data=2,fsdp=2")
    p.add_argument("--seq-len", type=int, default=16384)
    p.add_argument("--latents", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--microbatch", type=int, default=4)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--bucket-mb", type=float, default=4.0)
    p.add_argument("--micro", action="store_true",
                   help="toy geometry (64-ctx, 32-ch) for harness smoke on CPU")
    args = p.parse_args()

    from perceiver_io_tpu.models.text import CausalLanguageModel
    from perceiver_io_tpu.parallel import shard_batch
    from perceiver_io_tpu.parallel.overlap import OverlapConfig, mesh_from_spec
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step, shard_train_state

    if args.micro:
        args.seq_len, args.latents = 64, 16
        config = flagship_config(args.seq_len, args.latents)
        config.num_channels, config.num_heads, config.num_self_attention_layers = 32, 4, 2
    else:
        config = flagship_config(args.seq_len, args.latents)
    model = CausalLanguageModel(config, dtype=jnp.bfloat16)

    b, n = args.batch_size, args.seq_len
    rng = np.random.default_rng(0)
    t = rng.integers(0, config.vocab_size, size=(b, n + 1))
    base_batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    params = model.init(
        jax.random.PRNGKey(0), base_batch["input_ids"][:, : args.latents + 1], prefix_len=1
    )
    loss = clm_loss_fn(model.apply, max_latents=args.latents)

    def build(spec_str, overlap: bool):
        try:
            mesh = mesh_from_spec(spec_str)
        except ValueError as e:
            raise SystemExit(str(e)) from None
        tx = make_optimizer(1e-3, gradient_clip=1.0, moment_dtype="bfloat16")
        state = shard_train_state(
            TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1)), mesh
        )
        batch = shard_batch(dict(base_batch), mesh)
        cfg = OverlapConfig(mesh=mesh, bucket_bytes=int(args.bucket_mb * (1 << 20)))
        step = make_train_step(
            loss, jit=False, microbatch=args.microbatch, overlap=cfg if overlap else None
        )

        @functools.partial(jax.jit, static_argnums=2)
        def run(state, batch, k):
            def body(c, _):
                l, s = c
                s, metrics = step(s, batch)
                return (l + metrics["loss"], s), ()

            (l, _), _ = jax.lax.scan(body, (jnp.float32(0), state), None, length=k)
            return l

        return lambda k: float(run(state, batch, k))

    n_short, n_long = 2, 2 + args.steps
    runs = {}
    for spec_str in args.mesh:
        for overlap in (False, True):
            name = f"{spec_str}:{'overlap' if overlap else 'gspmd'}"
            runs[name] = build(spec_str, overlap)
            t0 = time.perf_counter()
            runs[name](n_short)
            runs[name](n_long)
            print(f"{name}: compiled in {time.perf_counter() - t0:.0f}s", flush=True)

    meds = interleaved_slopes(runs, n_short, n_long, reps=args.reps)
    print(f"{'variant':<28} {'ms/step':>9} {'tok/s':>12}")
    for name in runs:
        med = meds[name]
        if med is None:
            print(f"{name:<28}  all slope estimates non-positive (tunnel stall?) — rerun")
            continue
        print(f"{name:<28} {med * 1e3:9.3f} {b * n / med:12.0f}")


if __name__ == "__main__":
    main()
