"""graphcheck CLI — compiled-graph contracts, the graduation ledger, and
committed-bench floors, as one perf-CI gate.

Extracts a GraphFingerprint (analysis/fingerprint.py: collectives, hot-scope
concats, donation aliases, captured consts, dtype histogram, FLOPs, static
peak-HBM breakdown) from each flagship program — train flat, train
data x fsdp (GSPMD), train overlap (explicit shard_map), prefill, decode —
and semantically diffs it against the committed snapshot in ``contracts/``.
A regression (more collectives, a new hot concat, fewer donation aliases,
fatter memory/FLOPs beyond tolerance) fails the gate; an improvement or
neutral drift passes and is printed. The graduation ledger
(``contracts/ledger.json``, analysis/ledger.py) is schema- and
state-machine-validated, its ``default_on`` features pick the kernel
feature set the graphs are fingerprinted under, and its ``floors`` pin
committed BENCH_*.json numbers.

    python tools/graphcheck.py                          # the gate (tasks.py perf)
    python tools/graphcheck.py --programs train_flat,decode
    python tools/graphcheck.py --update --reason "twoseg graduated (BENCH_r07 A/B)"
    python tools/graphcheck.py --json graphcheck.json

--update etiquette: a snapshot move is a REVIEWED decision — the reason
lands in the contract file, so `git log contracts/` reads as the decision
history. Never --update to silence a regression you don't understand.

Exit codes: 0 clean; 1 regression / floor failure / invalid ledger;
2 missing or stale (incomparable) contracts — run --update; 3 internal
error (the check itself broke — distinct from "the graph got worse").

Hosts with fewer devices than the sharded programs need re-exec with
virtual CPU devices automatically (same trick as tools/graphlint.py).
Workflow and contract format: docs/static-analysis.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ensure_devices(n: int) -> None:
    """Re-exec with ``n`` virtual CPU devices when fewer are visible
    (shared respawn: utils/compat.respawn_cli_with_virtual_devices)."""
    from perceiver_io_tpu.utils.compat import respawn_cli_with_virtual_devices

    respawn_cli_with_virtual_devices(n, __file__, "_GRAPHCHECK_RESPAWNED")


def main(argv=None) -> int:
    from perceiver_io_tpu.analysis.fingerprint import DEFAULT_MESH_SPEC, PROGRAMS

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--contracts", default=os.path.join(REPO, "contracts"),
                   help="contracts directory (default: <repo>/contracts)")
    p.add_argument("--programs", default=",".join(PROGRAMS),
                   help=f"comma list of programs to check (known: {','.join(PROGRAMS)})")
    p.add_argument("--geometry", choices=("micro", "flagship"), default="micro",
                   help="micro (default): flagship architecture at toy sizes — "
                        "graph-shape contracts are geometry-invariant and this "
                        "compiles in seconds on CPU")
    p.add_argument("--mesh", default=DEFAULT_MESH_SPEC, metavar="data=N[,fsdp=M]",
                   help="submesh for the sharded train programs "
                        f"(default {DEFAULT_MESH_SPEC}; re-execs with virtual "
                        "CPU devices when the host has too few)")
    p.add_argument("--features", default=None,
                   help="override the kernel feature set ('all', 'none', or a "
                        "comma list, same tokens as bench.py); default: the "
                        "ledger's default_on features")
    p.add_argument("--update", action="store_true",
                   help="re-snapshot the selected programs' contracts instead "
                        "of checking (requires --reason)")
    p.add_argument("--reason", default=None,
                   help="why the contract moved (recorded in the file; "
                        "mandatory with --update)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the full result JSON artifact")
    p.add_argument("--skip-floors", action="store_true",
                   help="skip the ledger's committed-bench floor checks")
    args = p.parse_args(argv)

    try:
        from perceiver_io_tpu.analysis import ledger as L
        from perceiver_io_tpu.analysis.fingerprint import (
            check_contracts,
            flagship_fingerprints,
            save_contract,
        )
        from perceiver_io_tpu.parallel.overlap import parse_mesh_spec, required_devices

        programs = tuple(x for x in args.programs.split(",") if x)
        unknown = [x for x in programs if x not in PROGRAMS]
        if unknown:
            print(f"unknown program(s) {unknown}; known: {PROGRAMS}")
            return 3
        if any(x in ("train_sharded", "train_overlap") for x in programs):
            _ensure_devices(required_devices(parse_mesh_spec(args.mesh)))

        ledger = L.load_ledger(args.contracts)
        ledger_problems = L.validate_ledger(ledger) if ledger is not None else []
        features = None
        if args.features is not None:
            from perceiver_io_tpu.ops.flash_attention import ALL_FEATURES

            features = {
                "all": tuple(ALL_FEATURES), "none": ()
            }.get(args.features, tuple(f for f in args.features.split(",") if f))
        elif ledger is not None and not ledger_problems:
            features = L.default_on_features(ledger) or None

        if args.update:
            if not args.reason or not args.reason.strip():
                print("--update requires --reason (the recorded justification)")
                return 3
            fps = flagship_fingerprints(
                programs, geometry=args.geometry, mesh_spec=args.mesh, features=features
            )
            updated = {}
            for name in programs:
                path = save_contract(
                    args.contracts, name, fps[name], args.reason, geometry=args.geometry
                )
                updated[name] = path
                print(f"updated {path}")
            if args.json:
                with open(args.json, "w") as f:
                    json.dump(
                        {"status": "updated", "reason": args.reason.strip(),
                         "updated": updated},
                        f, sort_keys=True, indent=1,
                    )
                print(f"wrote {args.json}")
            return 0

        result = check_contracts(
            args.contracts, programs=programs, geometry=args.geometry,
            mesh_spec=args.mesh, features=features,
        )
        for name in programs:
            entry = result["programs"][name]
            if "diff" in entry and entry["diff"].get("comparable"):
                from perceiver_io_tpu.analysis.fingerprint import (
                    Delta,
                    FingerprintDiff,
                )

                d = FingerprintDiff(
                    name=name, comparable=True, reason="",
                    deltas=[Delta(**x) for x in entry["diff"]["deltas"]],
                )
                print(d.format())
            else:
                print(f"graphcheck {name}: {entry['status']} — {entry.get('detail', '')}")
            print()

        if ledger is None:
            print("graphcheck: no contracts/ledger.json — feature graduation untracked")
        elif ledger_problems:
            print(f"graphcheck: INVALID ledger: {ledger_problems}")
        else:
            for fname, feat in sorted(ledger.get("features", {}).items()):
                print(f"ledger: {fname} = {feat['state']}")
        floor_failures = []
        if not args.skip_floors and ledger is not None and not ledger_problems:
            floor_failures = L.check_bench_floors(ledger, REPO)
            for f in floor_failures:
                print(f"bench floor FAILED: {f}")

        if args.json:
            doc = {
                "status": result["status"],
                "programs": result["programs"],
                "ledger": {
                    "present": ledger is not None,
                    "problems": ledger_problems,
                    "features": {
                        k: v.get("state")
                        for k, v in (ledger or {}).get("features", {}).items()
                    },
                },
                "floor_failures": floor_failures,
            }
            with open(args.json, "w") as f:
                json.dump(doc, f, sort_keys=True, indent=1)
            print(f"wrote {args.json}")

        if result["status"] == "regressed" or floor_failures or ledger_problems:
            print("graphcheck FAILED (regression / floor / ledger)")
            return 1
        if result["status"] in ("missing", "stale"):
            print("graphcheck: contracts missing or stale — "
                  "run tools/graphcheck.py --update --reason '...'")
            return 2
        print(f"graphcheck ok ({len(programs)} program(s) match contracts)")
        return 0
    except SystemExit:
        raise
    except Exception as e:  # noqa: BLE001 — the check broke, not the graph
        import traceback

        traceback.print_exc()
        print(f"graphcheck internal error: {e}")
        return 3


if __name__ == "__main__":
    sys.exit(main())
