"""Same-process interleaved A/B of round-4 step-level structural variants on
the flagship train step (cross-process comparisons drift 1.5-1.8x with the
chip clock — docs/performance.md):

- ``graph``   — in-graph prefix-dropout draw (top_k + sort)
- ``host``    — keep set sampled on the host, fed as ``prefix_keep_idx``
                (training/prefix_dropout.py)
- ``mask``    — keep-mask form (SURVEY §7.3): full-length prefix, dropped
                positions masked in the CA softmax (prefix_dropout_mode)
- ``bf16m``   — in-graph draw + bf16 Adam moment storage
                (optim.scale_by_adam_compact)
- ``host+bf16m`` — both levers

Since round 5, gather variants take the COMPACT route (selection before
embedding — the current default); append ``_embed`` to any variant name
(e.g. ``host+bf16m_embed``) to pin the round-4 embedded-row gather that the
historical numbers in docs/performance.md were measured on.

Since round 6, add a ``twoseg`` token (e.g. ``host+bf16m+twoseg``) to route
the prefix cross-attention through the two-segment packed flash kernels
(`fast_kernels({"twoseg"})` — the concatenated [prefix; latents] kv tensor
and its LayerNorm/K/V-projection materializations disappear). The flag is
trace-time: this harness compiles each variant inside its feature context,
which is the same-process A/B the kernel's docs/performance.md entry cites:

    python tools/step_ab.py --variants host+bf16m host+bf16m+twoseg

    python tools/step_ab.py [--batch-size 4] [--steps 20] [--microbatch 2]

Since round 14 (Specline) the harness also takes DECODE variants, so the
standing TPU A/B instruction in ROADMAP item 3 covers the speculative
ladder with the same interleaved same-process discipline: ``decode`` runs
the sequential host-driven pair (``generation.make_decode_fns``) and
``spec{K}x{D}`` (e.g. ``spec4x6``) the speculative pair with K draft
tokens per span and a depth-D self-drafter — batch 1, prompt sized for
the no-slide window, tok/s measured over the same paired-chain slope:

    python tools/step_ab.py --variants decode spec4x6 spec4x2
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, interleaved_slopes

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=16384)
    p.add_argument("--latents", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--microbatch", type=int, default=2)
    p.add_argument(
        "--variants", nargs="*", default=["graph", "host", "mask", "bf16m", "host+bf16m"]
    )
    args = p.parse_args()

    from perceiver_io_tpu.models.text import CausalLanguageModel
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step
    from perceiver_io_tpu.training.prefix_dropout import sample_prefix_keep_idx

    b, n = args.batch_size, args.seq_len
    prefix_len = n - args.latents
    rng = np.random.default_rng(0)
    t = rng.integers(0, 262, size=(b, n + 1))
    base_batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    keep_idx = jnp.asarray(sample_prefix_keep_idx(rng, b, prefix_len, 0.5))

    def build(variant):
        # "…_embed" forces the round-4 embedded-row gather (prefix_dropout_mode
        # "gather_embed"); plain gather variants take the round-5 compact route
        tokens = variant.split("+")
        if "mask" in tokens:
            mode = "mask"
        elif any(t.endswith("_embed") for t in tokens):
            mode = "gather_embed"
        else:
            mode = "gather"
        config = flagship_config(args.seq_len, args.latents)
        config.prefix_dropout_mode = mode
        model = CausalLanguageModel(config, dtype=jnp.bfloat16)
        params = model.init(
            jax.random.PRNGKey(0), base_batch["input_ids"][:, : args.latents + 1], prefix_len=1
        )
        moment_dtype = "bfloat16" if "bf16m" in variant else None
        tx = make_optimizer(1e-3, gradient_clip=1.0, moment_dtype=moment_dtype)
        state = TrainState.create(model.apply, params, tx, jax.random.PRNGKey(1))
        step = make_train_step(
            clm_loss_fn(model.apply, max_latents=args.latents),
            jit=False,
            microbatch=args.microbatch,
        )
        batch = dict(base_batch)
        if variant.startswith("host"):
            batch["prefix_keep_idx"] = keep_idx

        @functools.partial(jax.jit, static_argnums=2)
        def run(state, batch, k):
            def body(c, _):
                l, s = c
                s, metrics = step(s, batch)
                return (l + metrics["loss"], s), ()

            (l, _), _ = jax.lax.scan(body, (jnp.float32(0), state), None, length=k)
            return l

        return lambda k: float(run(state, batch, k))

    import re as _re

    def build_decode(variant):
        """DECODE-family variants (round 14): ``decode`` = the sequential
        host-driven pair, ``spec{K}x{D}`` = the speculative draft/verify
        pair. run(k) decodes >= k tokens from a fresh prefill; the prefill
        (and the spec path's over-shoot tail) cancels in the paired-chain
        slope exactly like decode_ab's prompt pass."""
        from perceiver_io_tpu.generation import (
            GenerationConfig,
            make_decode_fns,
            make_speculative_decode_fns,
        )

        m = _re.fullmatch(r"spec(\d+)x(\d+)", variant)
        budget = n_long + (int(m.group(1)) + 1 if m else 0)
        prompt_len = args.seq_len - budget
        num_latents = args.latents - budget
        config = flagship_config(args.seq_len, args.latents)
        model = CausalLanguageModel(config, dtype=jnp.bfloat16)
        # per-variant FIXED seed (not the shared mutated generator): the
        # prompt — and with it a spec variant's acceptance rate — must not
        # depend on which other variants ran first in --variants
        prompt = jnp.asarray(
            np.random.default_rng(7).integers(0, config.vocab_size, size=(1, prompt_len))
        )
        params = model.init(
            jax.random.PRNGKey(0), prompt[:, : num_latents + 1], prefix_len=1
        )
        gcfg = GenerationConfig(max_new_tokens=budget)
        if m:
            prefill, step = make_speculative_decode_fns(
                model, num_latents, gcfg,
                k=int(m.group(1)), draft_depth=int(m.group(2)),
            )

            def run(k):
                _, state = prefill(params, prompt, None, jax.random.PRNGKey(11))
                emitted, toks = 1, None
                while emitted < k:
                    state, toks, mm = step(state)
                    emitted += int(mm[0])
                return float(state["token"][0])
        else:
            prefill, step = make_decode_fns(model, num_latents, gcfg)

            def run(k):
                _, state = prefill(params, prompt, None, jax.random.PRNGKey(11))
                for _ in range(k - 1):
                    state, tok = step(state)
                return float(state["token"][0])

        return run

    from perceiver_io_tpu.ops.flash_attention import fast_kernels

    n_short, n_long = 2, 2 + args.steps
    decode_family = {
        v for v in args.variants if v == "decode" or _re.fullmatch(r"spec\d+x\d+", v)
    }
    runs = {}
    for name in args.variants:
        # kernel features are read at TRACE time: build AND compile each
        # variant inside its feature context (measurement trap (a) in
        # docs/performance.md round 3 — a variant compiled under the wrong
        # flag silently measures the other kernel)
        feats = frozenset({"twoseg"}) if "twoseg" in name.split("+") else frozenset()
        with fast_kernels(feats):
            runs[name] = build_decode(name) if name in decode_family else build(name)
            t0 = time.perf_counter()
            runs[name](n_short)
            runs[name](n_long)
        print(f"{name}: compiled in {time.perf_counter() - t0:.0f}s", flush=True)

    meds = interleaved_slopes(runs, n_short, n_long, reps=args.reps)
    print(f"{'variant':<16} {'ms/step':>8} {'tok/s':>12}")
    for v in args.variants:
        med = meds[v]
        if med is None:
            print(f"{v:<16}  all slope estimates non-positive (tunnel stall?) — rerun")
            continue
        # decode-family variants are batch-1 token loops: tok/s = 1/slope;
        # train variants keep the b*n tokens-per-step convention
        tok_s = (1 / med) if v in decode_family else (b * n / med)
        print(f"{v:<16} {med * 1e3:8.3f} {tok_s:12.0f}")


if __name__ == "__main__":
    main()
