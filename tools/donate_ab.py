"""Same-process A/B of buffer donation on the per-call train step (VERDICT
r3 item 1c: the 2.2 ms "copies" profile category).

The scan-based harnesses (bench.py, tools/step_ab.py) thread the state
through a `lax.scan` carry inside ONE jitted program, so `donate_argnums`
never comes into play there — XLA already aliases the carry. Donation
matters on the boundary the real Trainer uses: `make_train_step(...,
jit=True)` called once per step from Python, where an undonated state
forces XLA to allocate fresh param/moment output buffers (~590 MB at the
flagship's 37M-param f32 state + bf16 moments) and copy-retire them.

Measures the sustained per-call step time (two chain lengths of back-to-back
dispatches; the final loss fetch and fixed tunnel round-trip cancel in the
slope) with donation on vs off, plus the in-graph scan step for reference.

    python tools/donate_ab.py [--steps 24] [--reps 4]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=16384)
    p.add_argument("--latents", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--microbatch", type=int, default=2)
    p.add_argument("--steps", type=int, default=24)
    p.add_argument("--reps", type=int, default=4)
    args = p.parse_args()

    from bench import flagship_config, interleaved_slopes
    from perceiver_io_tpu.models.text import CausalLanguageModel
    from perceiver_io_tpu.training import TrainState, clm_loss_fn, make_optimizer
    from perceiver_io_tpu.training.loop import make_train_step

    b, n = args.batch_size, args.seq_len
    rng = np.random.default_rng(0)
    t = rng.integers(0, 262, size=(b, n + 1))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    config = flagship_config(args.seq_len, args.latents)
    model = CausalLanguageModel(config, dtype=jnp.bfloat16)
    params = model.init(
        jax.random.PRNGKey(0), batch["input_ids"][:, : args.latents + 1], prefix_len=1
    )

    def fresh_state():
        tx = make_optimizer(1e-3, gradient_clip=1.0, moment_dtype="bfloat16")
        # deep-copy: a donated variant consumes its state's buffers, and the
        # init params must survive to seed the other variant
        own = jax.tree.map(lambda a: a.copy(), params)
        return TrainState.create(model.apply, own, tx, jax.random.PRNGKey(1))

    def build(donate):
        step = make_train_step(
            clm_loss_fn(model.apply, max_latents=args.latents),
            jit=True,
            donate=donate,
            microbatch=args.microbatch,
        )
        # ONE long-lived state per variant: each timed chain is a window of
        # the ongoing step stream (step time is state-value independent).
        # Rebuilding the state per chain costs hundreds of per-leaf copy
        # dispatches through the tunnel and swamps the measurement.
        box = {"state": fresh_state()}

        def call(k):
            state, m = box["state"], None
            for _ in range(k):
                state, m = step(state, batch)
            _ = float(m["loss"])  # force through the tunnel
            box["state"] = state

        return call

    variants = {"donate": build(True), "nodonate": build(False)}
    n_short, n_long = 2, 2 + args.steps
    for name, call in variants.items():
        t0 = time.perf_counter()
        call(n_short)
        call(n_long)
        print(f"{name}: compiled in {time.perf_counter() - t0:.0f}s", flush=True)

    meds = interleaved_slopes(variants, n_short, n_long, reps=args.reps)
    tok = b * args.seq_len
    print(f"{'variant':<10} {'ms/step':>8} {'tok/s':>12}")
    for v in variants:
        med = meds[v]
        if med is None:
            print(f"{v:<10}  slope estimates non-positive — rerun")
            continue
        print(f"{v:<10} {med * 1e3:8.2f} {tok / med:12.0f}")


if __name__ == "__main__":
    main()
