"""Same-process interleaved A/B of decode-scan variants at the 16k flagship
(cross-process decode numbers track the chip clock 1.5-1.8x —
docs/performance.md):

- ``pack``   — small f32 parameter leaves consolidated into ONE packed
               buffer, re-sliced inside the scan body behind an
               optimization_barrier (generation._pack_small_params,
               round-5 default)
- ``nopack`` — the round-4 behavior: each LayerNorm scale/bias and
               projection bias is its own HBM buffer in the scan body

    python tools/decode_ab.py [--batch-size 8] [--cache-dtype int8]
                              [--weight-dtype int8]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, interleaved_slopes

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=16384)
    p.add_argument("--latents", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--steps", type=int, default=48)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--cache-dtype", choices=["model", "int8"], default="model")
    p.add_argument("--weight-dtype", choices=["model", "int8"], default="model")
    p.add_argument("--variants", nargs="*", default=["pack", "nopack"])
    args = p.parse_args()

    from perceiver_io_tpu.generation import (
        GenerationConfig,
        make_generate_fn,
        pack_small_params,
    )
    from perceiver_io_tpu.models.text import CausalLanguageModel

    config = flagship_config(args.seq_len, args.latents)
    model = CausalLanguageModel(config, dtype=jnp.bfloat16)
    b = args.batch_size
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, config.vocab_size, size=(b, args.seq_len)))
    params = model.init(jax.random.PRNGKey(0), prompt[:, : args.latents + 1], prefix_len=1)

    cache_dtype = jnp.int8 if args.cache_dtype == "int8" else jnp.bfloat16
    weight_dtype = jnp.int8 if args.weight_dtype == "int8" else None

    n_short, n_long = 8, 8 + args.steps

    def build(variant):
        fns = {}
        with pack_small_params(variant == "pack"):
            for k in (n_short, n_long):
                fns[k] = make_generate_fn(
                    model,
                    args.latents,
                    GenerationConfig(max_new_tokens=k, do_sample=True, top_k=10),
                    cache_dtype=cache_dtype,
                    weight_dtype=weight_dtype,
                )
                # compile inside the pack context (trace-time flag)
                float(fns[k](params, prompt)[0, -1])
        return lambda k: float(fns[k](params, prompt)[0, -1])

    runs = {v: build(v) for v in args.variants}
    meds = interleaved_slopes(runs, n_short, n_long, reps=args.reps)
    print(f"{'variant':<10} {'ms/token':>9} {'tok/s (batch)':>14}")
    for v in args.variants:
        med = meds[v]
        if med is None:
            print(f"{v:<10}  all slope estimates non-positive (tunnel stall?) — rerun")
            continue
        print(f"{v:<10} {med * 1e3:9.4f} {b / med:14.0f}")


if __name__ == "__main__":
    main()
