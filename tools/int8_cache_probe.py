"""Feasibility probe for an int8 KV cache on the batched decode hot loop.

Decode b=8 is bandwidth-SATURATED (ceiling_fraction ~1.0) and hard-capped at
vs_baseline 0.878 by v5e's 1.9x bandwidth deficit to A100 — as long as both
sides move bf16. Per-token-quantized int8 storage halves the dominant cache
traffic, and the scales fold into elementwise ops OUTSIDE the two cache
GEMMs (scores: per-column scale after the QK GEMM; values: fold the scale
into the attention weights before the AV GEMM), so the only question is
whether XLA reads an int8 GEMM operand at int8 bytes or materializes a
bf16-converted copy of the cache each step (which would UNDO the win — the
round-3 single-query f32-convert lesson, core/attention.py block-diag note).

This probe times the two decode GEMMs + softmax over a (B, M, C) cache in
bf16 vs int8-with-scales, shapes matched to the 16k flagship CA cache.

    python tools/int8_cache_probe.py
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_probe_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--slots", type=int, default=16384)
    p.add_argument("--channels", type=int, default=512)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--steps", type=int, default=64)
    p.add_argument("--reps", type=int, default=4)
    args = p.parse_args()

    b, m, c, h = args.batch, args.slots, args.channels, args.heads
    rng = np.random.default_rng(0)
    k_f = jnp.asarray(rng.normal(size=(b, m, c)), jnp.bfloat16)
    v_f = jnp.asarray(rng.normal(size=(b, m, c)), jnp.bfloat16)
    # per-token symmetric quantization
    k_np = np.asarray(k_f, np.float32)
    v_np = np.asarray(v_f, np.float32)
    ks = np.abs(k_np).max(-1, keepdims=True) / 127.0
    vs = np.abs(v_np).max(-1, keepdims=True) / 127.0
    k_q = jnp.asarray(np.round(k_np / ks).astype(np.int8))
    v_q = jnp.asarray(np.round(v_np / vs).astype(np.int8))
    k_s = jnp.asarray(ks[..., 0], jnp.bfloat16)  # (B, M)
    v_s = jnp.asarray(vs[..., 0], jnp.bfloat16)
    qd = jnp.asarray(rng.normal(size=(b, h, c)), jnp.bfloat16)

    def body_bf16(ops, carry):
        k, v = ops
        scores = jnp.einsum("bhc,bjc->bhj", qd + carry, k, preferred_element_type=jnp.float32)
        attn = jax.nn.softmax(scores)
        out = jnp.einsum("bhj,bjc->bhc", attn.astype(v.dtype), v)
        return carry + out.mean() * 1e-9

    def body_int8(ops, carry):
        k, v, s_k, s_v = ops
        scores = jnp.einsum(
            "bhc,bjc->bhj", (qd + carry).astype(jnp.bfloat16), k.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        scores = scores * s_k[:, None, :].astype(jnp.float32)
        attn = jax.nn.softmax(scores)
        aw = attn.astype(jnp.bfloat16) * s_v[:, None, :]
        out = jnp.einsum("bhj,bjc->bhc", aw, v.astype(jnp.bfloat16))
        return carry + out.mean() * 1e-9

    def make(body, ops):
        # the caches ride as ARGUMENTS (donated into the scan closure would
        # bake them into the HLO as constants — a 500 MB compile payload the
        # tunnel rejects outright)
        @functools.partial(jax.jit, static_argnums=2)
        def run(ops, c0, n):
            def step(c, _):
                return body(ops, c), ()

            cf, _ = jax.lax.scan(step, c0, None, length=n)
            return cf

        return lambda n: float(run(ops, jnp.zeros((), jnp.bfloat16), n).astype(jnp.float32))

    variants = {
        "bf16": make(body_bf16, (k_f, v_f)),
        "int8": make(body_int8, (k_q, v_q, k_s, v_s)),
    }
    n_s, n_l = 4, 4 + args.steps
    for name, call in variants.items():
        t0 = time.perf_counter()
        call(n_s)
        call(n_l)
        print(f"{name}: compiled in {time.perf_counter() - t0:.0f}s", flush=True)

    # traffic per iteration: k+v bytes (+scales for int8)
    bytes_bf16 = 2 * b * m * c * 2
    bytes_int8 = 2 * b * m * c * 1 + 2 * b * m * 2
    slopes = {v: [] for v in variants}
    for _ in range(3):
        best = {v: {"s": float("inf"), "l": float("inf")} for v in variants}
        for _ in range(args.reps):
            for v, call in variants.items():
                t0 = time.perf_counter(); call(n_s)
                best[v]["s"] = min(best[v]["s"], time.perf_counter() - t0)
                t0 = time.perf_counter(); call(n_l)
                best[v]["l"] = min(best[v]["l"], time.perf_counter() - t0)
        for v in variants:
            s = (best[v]["l"] - best[v]["s"]) / (n_l - n_s)
            if s > 0:
                slopes[v].append(s)

    print(f"{'variant':<8} {'us/iter':>8} {'GB/s eff':>9}")
    for v, byt in (("bf16", bytes_bf16), ("int8", bytes_int8)):
        ss = sorted(slopes[v])
        if not ss:
            print(f"{v:<8}  non-positive slopes — rerun")
            continue
        med = (ss[(len(ss) - 1) // 2] + ss[len(ss) // 2]) / 2
        print(f"{v:<8} {med * 1e6:8.1f} {byt / med / 1e9:9.0f}")


if __name__ == "__main__":
    main()
