"""The observability CI gate (``tasks.py obs``; wired into ``tasks.py perf``).

End-to-end certification that the Spanline surface holds together: run a
10-step synthetic CLM fit with full telemetry plus a few instrumented
generate requests into one run directory, then

1. ``obs.events.validate_events`` — every row parses, carries
   ``schema_version`` and its kind's required fields, and every
   ``span_id``/``parent_id`` reference resolves (schema drift or a span
   leak fails the gate, not the next consumer);
2. assert the stream's shape: step spans for every step, one ``request``
   row per generate call with histogram-derived TPOT percentiles, a
   ``metrics`` registry snapshot, an SLO report that aggregates them;
3. ``tools/obs_report.py`` renders the directory (a renderer crash is a
   gate failure);
4. ``tools/obs_diff.py`` run-vs-itself must be CLEAN (a self-diff that
   regresses means the differ, not the run, is broken);
5. with ``--baseline RUN_DIR`` (``tasks.py perf`` passes the committed
   baseline from ``$OBS_BASELINE_RUN``), diff baseline → this run and fail
   on regression; a non-comparable baseline exits 2 (stale, not red).

    python tools/obs_gate.py [--out DIR] [--steps N] [--requests N]
        [--baseline RUN_DIR] [--keep]

Exit codes: 0 clean, 1 gate failure (validation/shape/self-diff/baseline
regression), 2 stale baseline (not comparable), 3 internal error.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses resolve cls.__module__ through here
    spec.loader.exec_module(mod)
    return mod


def run_workload(out_dir: str, steps: int, requests: int) -> None:
    """The synthetic workload: a tiny CLM fit + instrumented generates, all
    logging into ``out_dir`` (the same model family the flagship uses, CPU
    geometry — the gate certifies the telemetry plumbing, not perf)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from perceiver_io_tpu.generation import GenerationConfig, make_instrumented_generate_fn
    from perceiver_io_tpu.models.text import CausalLanguageModel, CausalLanguageModelConfig
    from perceiver_io_tpu.obs import clm_train_telemetry
    from perceiver_io_tpu.training import (
        MetricsLogger,
        TrainState,
        Trainer,
        TrainerConfig,
        clm_loss_fn,
        make_optimizer,
    )

    config = CausalLanguageModelConfig(
        vocab_size=64, max_seq_len=24, max_latents=8, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.5,
    )
    model = CausalLanguageModel(config)
    rng = np.random.default_rng(0)
    t = rng.integers(0, config.vocab_size, size=(4, config.max_seq_len + 1))
    batch = {
        "labels": jnp.asarray(t[:, 1:]),
        "input_ids": jnp.asarray(t[:, :-1]),
        "pad_mask": None,
    }
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"], prefix_len=16)
    state = TrainState.create(
        model.apply, params, make_optimizer(1e-3), jax.random.PRNGKey(1)
    )
    tokens_per_sample, flops_per_sample = clm_train_telemetry(config)
    logger = MetricsLogger(out_dir, use_tensorboard=False)
    trainer = Trainer(
        clm_loss_fn(model.apply, max_latents=config.max_latents),
        logger=logger,
        config=TrainerConfig(
            max_steps=steps,
            log_interval=max(steps // 2, 1),
            prefetch_batches=0,
            tokens_per_sample=tokens_per_sample,
            flops_per_sample=flops_per_sample,
            # Probeline: the gate certifies a PROBED fit — per-scope stats
            # ride the step as aux outputs and land as `probe` event rows
            probes=True,
        ),
    )
    state = trainer.fit(state, iter([batch] * steps), model_config=config)
    prompt = jnp.asarray(rng.integers(0, config.vocab_size, size=(2, 12)))
    fn = make_instrumented_generate_fn(
        model,
        num_latents=4,
        config=GenerationConfig(max_new_tokens=8),
        events=trainer._ensure_events(),
        snapshot_interval_s=0.0,  # a metrics snapshot per request: gate-visible
        probes=True,  # decode health gauges on every request row
    )
    for _ in range(requests):
        fn(state.params, prompt)
    trainer.close()
    logger.close()


def check_stream(out_dir: str, steps: int, requests: int) -> list:
    """Validation + shape assertions; returns a list of problems."""
    from perceiver_io_tpu.obs.events import merged_events, validate_events
    from perceiver_io_tpu.obs.slo import write_slo_report

    fwd_warnings: list = []
    problems = list(validate_events(out_dir, warnings_out=fwd_warnings))
    for w in fwd_warnings:
        # unknown kinds are forward-compatibility WARNINGS, never failures
        print(f"obs_gate: warning: {w}")
    events = merged_events(out_dir)
    kinds = [e.get("event") for e in events]
    step_spans = [
        e for e in events if e.get("event") == "span" and e.get("name") == "step"
    ]
    if len(step_spans) != steps:
        problems.append(f"expected {steps} step spans, found {len(step_spans)}")
    reqs = [e for e in events if e.get("event") == "request"]
    if len(reqs) != requests:
        problems.append(f"expected {requests} request events, found {len(reqs)}")
    for r in reqs:
        if r.get("tpot_p50_s") is None or r.get("tpot_p99_s") is None:
            problems.append("request event missing histogram-derived TPOT percentiles")
        if not r.get("tpot_hist"):
            problems.append("request event missing its tpot_hist bucket counts")
    if "metrics" not in kinds:
        problems.append("no metrics registry snapshot row in the stream")
    if "fit_end" not in kinds:
        problems.append("no fit_end row in the stream")
    # Probeline rows: the probed fit must land per-scope snapshots, and the
    # probed decode must stamp health gauges onto every request
    probe_rows = [e for e in events if e.get("event") == "probe"]
    if not probe_rows:
        problems.append("no probe snapshot rows despite TrainerConfig.probes")
    for e in probe_rows:
        scopes = e.get("scopes")
        if not isinstance(scopes, dict) or not scopes:
            problems.append("probe row has empty/invalid scopes")
            continue
        for k, st in scopes.items():
            if not isinstance(st, dict) or not st:
                problems.append(f"probe scope {k!r} carries no stats")
    for r in reqs:
        if r.get("kv_cache_frac") is None or r.get("logit_entropy_mean") is None:
            problems.append("request event missing decode health gauges")
    slo = write_slo_report(out_dir)
    if slo is None:
        problems.append("SLO report empty despite request events")
    elif "tpot_s" not in slo:
        problems.append("SLO report lacks merged TPOT percentiles")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default=None, help="run dir (default: a temp dir)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--requests", type=int, default=3)
    p.add_argument("--baseline", default=None, help="committed baseline run dir to diff against")
    p.add_argument("--keep", action="store_true", help="keep the run dir (implied by --out)")
    args = p.parse_args(argv)

    out_dir = args.out or tempfile.mkdtemp(prefix="obs_gate_")
    keep = args.keep or args.out is not None
    try:
        print(f"obs_gate: running {args.steps}-step fit + {args.requests} requests -> {out_dir}")
        run_workload(out_dir, args.steps, args.requests)

        problems = check_stream(out_dir, args.steps, args.requests)
        if problems:
            print("obs_gate: event-stream validation FAILED:")
            for pr in problems:
                print(f"  - {pr}")
            return 1
        print("obs_gate: event stream valid (schema, spans, requests, SLO report)")

        obs_report = _load_tool("obs_report")
        text = obs_report.render(out_dir)
        for line in text.splitlines():
            print(f"  | {line}")

        obs_diff = _load_tool("obs_diff")
        self_summary = obs_diff.summarize_run(out_dir)
        self_diff = obs_diff.diff_runs(self_summary, self_summary)
        if not self_diff.ok():
            print("obs_gate: run-vs-itself diff NOT clean (differ broken):")
            print(self_diff.format())
            return 1
        print("obs_gate: obs_diff run-vs-itself clean")

        if args.baseline:
            base = obs_diff.summarize_run(args.baseline)
            diff = obs_diff.diff_runs(base, self_summary)
            print(diff.format())
            if not diff.comparable:
                print("obs_gate: baseline STALE (not comparable) — re-record it")
                return 2
            if not diff.ok():
                print("obs_gate: runtime REGRESSION vs committed baseline")
                return 1
        with open(os.path.join(out_dir, "slo_report.json")) as f:
            slo = json.load(f)
        print(
            "obs_gate: OK — "
            f"{slo['n_requests']} requests, tpot_p99={slo['tpot_s']['p99']}s"
        )
        return 0
    except Exception as e:  # noqa: BLE001 — CI must see crash != verdict
        print(f"obs_gate: internal error: {e}", file=sys.stderr)
        import traceback

        traceback.print_exc()
        return 3
    finally:
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
