"""hostlint CLI — static protocol analysis of the host-side serving stack.

Where graphlint lints the *compiled graphs*, hostlint lints the *Python
that drives them*: it parses ``perceiver_io_tpu/serving/`` and
``perceiver_io_tpu/obs/`` into per-function CFGs (exception edges
included), a call graph rooted at the declared entry contexts (drive
loops, ObsServer handlers, signal handlers, the loadgen producer) and
per-class attribute access sets, then runs the five protocol rules —
books-exactness, shared-state-race, clock-discipline, grant-pairing,
event-schema (catalog: docs/static-analysis.md#hostlint):

    python tools/hostlint.py                      # the committed gate
    python tools/hostlint.py --fail-on warn
    python tools/hostlint.py --rules books-exactness,shared-state-race
    python tools/hostlint.py --json hostlint.json
    python tools/hostlint.py --no-default-allow   # show every raw finding
    python tools/hostlint.py --paths serving=some/dir  # lint a fixture tree

The committed allowlist (``contracts/hostlint_allow.json``) carries one
reasoned entry per accepted finding on the real surface — an entry without
a non-empty ``reason`` fails to load. ``--allow`` adds ad-hoc entries on
top; ``--no-default-allow`` drops the committed file (the raw-surface
view used when triaging a new rule).

Exit codes (shared with tools/graphlint.py via analysis/lintcli.py):
0 — clean at ``--fail-on``; 1 — violations; 2 — usage error (unknown
``--rules`` name lists the registry); 3 — the lint itself crashed.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # `python tools/hostlint.py` from anywhere
    sys.path.insert(0, _REPO)

from perceiver_io_tpu.analysis.lintcli import (  # noqa: E402
    add_common_lint_args,
    finish_lint,
    lint_crashed,
    parse_rules,
)

DEFAULT_ALLOWLIST = os.path.join(_REPO, "contracts", "hostlint_allow.json")
DEFAULT_PATHS = (
    ("serving", os.path.join(_REPO, "perceiver_io_tpu", "serving")),
    ("obs", os.path.join(_REPO, "perceiver_io_tpu", "obs")),
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    add_common_lint_args(
        p,
        allow_help="extra allowlist entry (repeatable), fnmatch-ed against "
                   "'rule' and 'rule:scope' — e.g. "
                   "'shared-state-race:RequestFrontEnd.*'",
    )
    p.add_argument(
        "--paths", action="append", default=[], metavar="PREFIX=DIR",
        help="lint these package trees instead of the default "
             "serving/+obs/ pair (repeatable; PREFIX becomes the module "
             "prefix in violation scopes) — fixture trees in tests use this",
    )
    p.add_argument(
        "--no-default-allow", action="store_true",
        help="ignore the committed allowlist "
             "(contracts/hostlint_allow.json) — the raw-surface triage view",
    )
    args = p.parse_args(argv)

    from perceiver_io_tpu.analysis.hostrules import HOST_RULES

    rules = parse_rules(p, args.rules, HOST_RULES)

    packages = list(DEFAULT_PATHS)
    if args.paths:
        packages = []
        for spec in args.paths:
            prefix, sep, d = spec.partition("=")
            if not sep or not prefix or not d:
                p.error(f"--paths wants PREFIX=DIR, got {spec!r}")
            packages.append((prefix, d))

    allow = list(args.allow)
    try:
        from perceiver_io_tpu.analysis.hostgraph import build_package_graph
        from perceiver_io_tpu.analysis.hostrules import (
            default_host_policy,
            host_check,
            load_allowlist,
        )

        if not args.no_default_allow and os.path.exists(DEFAULT_ALLOWLIST):
            committed, _entries = load_allowlist(DEFAULT_ALLOWLIST)
            allow = list(committed) + allow
        graph = build_package_graph(packages)
        report = host_check(
            graph, policy=default_host_policy(), rules=rules,
            allow=tuple(allow),
        )
    except Exception as e:  # noqa: BLE001 — a crashed lint is not a verdict
        return lint_crashed("hostlint", e)

    return finish_lint("hostlint", {"host": report}, fail_on=args.fail_on,
                       json_path=args.json)


if __name__ == "__main__":
    sys.exit(main())
