#!/usr/bin/env python
"""Simline CLI — discrete-event scale certification of the real serving stack.

The standing multi-tenant serving gate (``tasks.py sim``; ``--smoke`` is
wired into ``tasks.py perf``): drive a merged multi-tenant Poisson workload
through the REAL :class:`~perceiver_io_tpu.serving.engine.EngineFrontEnd`
control plane — admission, paging, eviction, breaker, books — with only the
compiled prefill/decode replaced by a :class:`~perceiver_io_tpu.serving.sim.
ServiceTimeModel` fitted from the latest committed ``LOAD_r*.json`` round,
all on a ``ManualClock`` (zero wall-clock sleeps; tens of thousands of
offered req/s complete in host-loop time). Then assert the whole surface:

1. books balanced + zero leaked slots/pages (the same audit the chaos
   scenarios close with), zero errors;
2. the event stream validates — tenant-stamped ``request`` rows, one
   ``sim.summary`` row — and ``build_slo_report(by_tenant=True)`` carries
   one full sub-report per tenant;
3. the live scrape surface answers per tenant: ``/metrics`` exposes
   tenant-labeled ``serve_*`` series, ``/slo?tenant=`` narrows to that
   tenant's rows only;
4. the run summarizes into a SIM artifact body whose run-vs-itself
   :func:`~perceiver_io_tpu.serving.sim.diff_sim` is clean (the run is
   seeded end to end, so the self-diff is exact);
5. the ledger's ``SIM_r*.json`` floors hold against the latest committed
   artifact (fairness_jain minimum, max-starvation-age ceiling —
   contracts/ledger.json, the same floor machinery as LOAD/BENCH).

    python tools/sim.py                      # the full gate (>= 10k rps offered)
    python tools/sim.py --smoke              # CI-fast subset (2 tenants, ~2k reqs)
    python tools/sim.py --write-artifact     # refresh SIM_r<next>.json
    python tools/sim.py --diff OLD.json NEW.json [--tolerance k=v]

Exit codes (mirrors tools/loadgen.py): 0 clean, 1 gate failure /
regression, 2 not comparable (diff mode), 3 internal error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import shutil
import sys
import tempfile
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def build_tenants(smoke: bool, slots: int):
    """The gate's workload: heterogeneous tenants whose SUMMED offered rate
    is the certification scale (>= 10k simulated req/s for the full gate —
    two orders of magnitude above what the CPU chaos scenarios can decode),
    sized so every prompt+budget fits the engine geometry below."""
    from perceiver_io_tpu.serving import EngineConfig
    from perceiver_io_tpu.serving.sim import TenantSpec

    if smoke:
        tenants = [
            TenantSpec("acme", rate_rps=600.0, n_requests=1200,
                       prompt_lens=(8, 12), max_new_tokens=(4, 6), seed=101),
            TenantSpec("bcorp", rate_rps=400.0, n_requests=800,
                       prompt_lens=(12, 16), max_new_tokens=(6, 8), seed=202),
        ]
    else:
        tenants = [
            TenantSpec("api", rate_rps=5000.0, n_requests=6000,
                       prompt_lens=(8, 12), max_new_tokens=(4, 6), seed=101),
            TenantSpec("batch", rate_rps=3500.0, n_requests=4200,
                       prompt_lens=(12, 16), max_new_tokens=(8, 12), seed=202),
            TenantSpec("realtime", rate_rps=1500.0, n_requests=1800,
                       prompt_lens=(8,), max_new_tokens=(4,), seed=303),
        ]
    # geometry covers the widest tenant: prompt 16 + budget 12 <= 32 CA
    # tokens, 1 latent + 12 <= 16 SA tokens
    engine_cfg = EngineConfig(slots=slots, page_size=8,
                              max_ca_tokens=32, max_sa_tokens=16)
    return tenants, engine_cfg


def load_service_model():
    """Fit the service-time model from the LATEST committed LOAD round that
    carries warm TTFT/TPOT percentiles (the comparability stamp names it) —
    simulated service times are measured, not invented."""
    from perceiver_io_tpu.serving.sim import ServiceTimeModel

    rounds = sorted(
        ((int(m.group(1)), p)
         for p in glob.glob(os.path.join(_REPO, "LOAD_r*.json"))
         if (m := _ROUND_RE.search(p))),
        reverse=True,
    )
    for n, path in rounds:
        try:
            with open(path) as f:
                doc = json.load(f)
            return ServiceTimeModel.from_load_doc(doc, source=f"LOAD_r{n:02d}")
        except (OSError, json.JSONDecodeError, ValueError):
            continue
    raise RuntimeError("no committed LOAD_r*.json carries ttft/tpot p50+p99")


def _fetch(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode()


def run_gate(args) -> int:
    from perceiver_io_tpu.obs.events import EventLog, validate_events
    from perceiver_io_tpu.obs.flightrec import FlightRecorder, SLOBounds
    from perceiver_io_tpu.obs.metrics import MetricsRegistry
    from perceiver_io_tpu.obs.server import ObsServer
    from perceiver_io_tpu.obs.slo import build_slo_report
    from perceiver_io_tpu.serving import FrontEndConfig
    from perceiver_io_tpu.serving.sim import (
        build_sim_doc,
        diff_sim,
        format_sim_diff,
        run_sim,
    )

    out_dir = args.out or tempfile.mkdtemp(prefix="simgate_")
    keep = args.keep or args.out is not None
    problems: list = []
    try:
        tenants, engine_cfg = build_tenants(args.smoke, args.slots)
        service_model = load_service_model()
        offered = sum(t.rate_rps for t in tenants)
        n_requests = sum(t.n_requests for t in tenants)
        print(
            f"sim: {len(tenants)} tenants, {n_requests} requests offered at "
            f"{offered:.0f} req/s (service model {service_model.source}, "
            f"slots {engine_cfg.slots}) -> {out_dir}"
        )
        events = EventLog(out_dir, main_process=True)
        # generous standing bounds: the gate certifies scale accounting,
        # not a planted breach (per-tenant triggers are the chaos
        # scenarios' job — sim_noisy_neighbor)
        recorder = FlightRecorder(
            events, out_dir=out_dir, slo=SLOBounds(ttft_s=30.0, tpot_p99_s=30.0)
        )
        registry = MetricsRegistry()
        host_t0 = time.perf_counter()
        report = run_sim(
            tenants, service_model=service_model, engine_config=engine_cfg,
            config=FrontEndConfig(max_queue=256, admission_projection=False),
            events=recorder, registry=registry, seed=args.seed,
        )
        host_s = time.perf_counter() - host_t0
        fe, summary = report.frontend, report.summary
        print(
            f"sim: {summary['n_requests']} requests over {report.duration_s:.3f}s "
            f"VIRTUAL ({host_s:.2f}s host wall, zero sleeps): achieved "
            f"{summary['achieved_rps']:.0f} req/s, shed_rate {summary['shed_rate']}, "
            f"fairness {summary['fairness_jain']}, max starvation "
            f"{summary['max_starvation_age_s']}s"
        )

        # --- the clean-books audit every serving gate closes with ---------
        if not summary["books_balanced"]:
            problems.append(f"books not balanced: {summary['books']}")
        problems += [f"engine books: {p}" for p in fe.audit()]
        problems += [f"ca pages: {p}" for p in fe.ca_alloc.audit()]
        problems += [f"sa pages: {p}" for p in fe.sa_alloc.audit()]
        if fe.ca_alloc.pages_used or fe.sa_alloc.pages_used:
            problems.append(
                f"pages leaked after drain: ca={fe.ca_alloc.pages_used} "
                f"sa={fe.sa_alloc.pages_used}"
            )
        if summary["error_rate"] != 0.0:
            problems.append(f"simulated run errored: error_rate {summary['error_rate']}")

        # --- the scrape surface answers PER TENANT while the run is live --
        with ObsServer(registry=registry, run_dir=out_dir, health=fe.health) as server:
            metrics_text = _fetch(server.url + "/metrics")
            for t in tenants:
                if f'serve_submitted_total{{tenant="{t.name}"}}' not in metrics_text:
                    problems.append(
                        f"/metrics lacks the tenant-labeled series "
                        f'serve_submitted_total{{tenant="{t.name}"}}'
                    )
            if "serve_submitted_total " not in metrics_text:
                problems.append("/metrics lost the unlabeled all-tenant total")
            t0 = tenants[0]
            slo_t = json.loads(_fetch(server.url + f"/slo?tenant={t0.name}"))
            want = summary["tenants"][t0.name]["n_requests"]
            if slo_t.get("n_requests") != want:
                problems.append(
                    f"/slo?tenant={t0.name} n_requests {slo_t.get('n_requests')} "
                    f"!= {want} (tenant filter broken)"
                )
            slo_all = json.loads(_fetch(server.url + "/slo"))
            if slo_all.get("n_requests") != summary["n_requests"]:
                problems.append(
                    f"/slo n_requests {slo_all.get('n_requests')} != {summary['n_requests']}"
                )

        # --- event stream validates; per-tenant SLO sub-reports -----------
        warnings_out: list = []
        problems += validate_events(out_dir, warnings_out=warnings_out)
        for w in warnings_out:
            print(f"sim: warning: {w}")
        from perceiver_io_tpu.obs.events import merged_events

        stream = merged_events(out_dir)
        if not any(e.get("event") == "sim.summary" for e in stream):
            problems.append("no sim.summary event in the stream")
        req_rows = [e for e in stream if e.get("event") == "request"]
        if len(req_rows) != n_requests:
            problems.append(f"{len(req_rows)} request rows, want {n_requests}")
        untagged = [e for e in req_rows if e.get("tenant") is None]
        if untagged:
            problems.append(f"{len(untagged)} request rows lack the tenant stamp")
        slo_report = build_slo_report(stream, by_tenant=True)
        tenant_names = {t.name for t in tenants}
        if set((slo_report or {}).get("tenants", {})) != tenant_names:
            problems.append(
                f"per-tenant SLO report covers {sorted((slo_report or {}).get('tenants', {}))}, "
                f"want {sorted(tenant_names)}"
            )

        # --- artifact body + run-vs-itself comparability diff -------------
        doc = build_sim_doc(
            args.round or _next_round(), summary, tenants, service_model,
            engine_cfg,
        )
        self_diff = diff_sim(doc, doc)
        if not (self_diff["comparable"] and self_diff["ok"]):
            problems.append("run-vs-itself sim diff NOT clean (differ broken): "
                            + format_sim_diff(self_diff))
        else:
            print("sim: run-vs-itself comparability diff clean")

        if args.write_artifact:
            # write-side guard (the loadgen discipline): a sub-floor doc —
            # e.g. a --smoke-size run — must never become the latest round
            floor_fails = check_doc_floors(doc)
            if floor_fails:
                problems += [f"refusing to write artifact: {f}" for f in floor_fails]
            else:
                path = os.path.join(_REPO, f"SIM_r{doc['n']:02d}.json")
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                    f.write("\n")
                print(f"sim: wrote {path}")

        # --- ledger floors over the committed SIM artifacts ----------------
        problems += check_sim_floors()

        if problems:
            print("sim: gate FAILED:")
            for p in problems:
                print(f"  - {p}")
            return 1
        per = ", ".join(
            f"{name} {blk['achieved_rps']:.0f}/{blk['offered_rps']:.0f} rps"
            for name, blk in summary["tenants"].items()
        )
        print(
            f"sim: OK — fairness {summary['fairness_jain']} over [{per}], "
            f"max starvation {summary['max_starvation_age_s']}s, "
            f"{summary['evictions']} evictions / {summary['resumes']} resumes, "
            "books balanced"
        )
        return 0
    except Exception as e:  # noqa: BLE001 — CI must see crash != verdict
        print(f"sim: internal error: {e}", file=sys.stderr)
        import traceback

        traceback.print_exc()
        return 3
    finally:
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)


def _next_round() -> int:
    rounds = [
        int(m.group(1))
        for p in glob.glob(os.path.join(_REPO, "SIM_r*.json"))
        if (m := _ROUND_RE.search(p))
    ]
    return max(rounds) + 1 if rounds else 1


def _sim_floors() -> dict:
    from perceiver_io_tpu.analysis.ledger import load_ledger

    ledger = load_ledger(os.path.join(_REPO, "contracts")) or {}
    return {
        name: floor
        for name, floor in ledger.get("floors", {}).items()
        if str(floor.get("artifact", "")).startswith("SIM_")
    }


def check_doc_floors(doc: dict) -> list:
    """SIM-floor failures of ONE candidate doc before it is committed (the
    write-side guard; :func:`check_sim_floors` is the read-side gate over
    whatever is already on disk)."""
    from perceiver_io_tpu.analysis.ledger import _dig, doc_matches

    failures = []
    for name, floor in _sim_floors().items():
        if not doc_matches(doc, floor.get("match")):
            continue
        value = _dig(doc, floor["key"])
        if not isinstance(value, (int, float)):
            failures.append(f"{name}: {floor['key']} = {value!r} missing or non-numeric")
            continue
        if "min" in floor and value < floor["min"]:
            failures.append(f"{name}: {floor['key']} = {value!r} below floor {floor['min']}")
        if "max" in floor and value > floor["max"]:
            failures.append(f"{name}: {floor['key']} = {value!r} above ceiling {floor['max']}")
    return failures


def check_sim_floors() -> list:
    """Enforce every ``contracts/ledger.json`` floor whose artifact pattern
    targets SIM_r*.json (latest round wins — the same machinery as the
    committed-bench floors). No SIM floors or no committed artifact yet ->
    nothing to enforce."""
    from perceiver_io_tpu.analysis.ledger import check_bench_floors

    sim_floors = _sim_floors()
    if not sim_floors:
        return []
    return check_bench_floors({"floors": sim_floors}, _REPO)


def run_diff(args) -> int:
    from perceiver_io_tpu.serving.sim import SIM_METRICS, diff_sim, format_sim_diff

    tolerances = {}
    for spec in args.tolerance:
        if "=" not in spec:
            print(f"--tolerance wants METRIC=TOL, got {spec!r}", file=sys.stderr)
            return 3
        k, v = spec.split("=", 1)
        if k not in SIM_METRICS:
            print(f"unknown metric {k!r} (known: {', '.join(sorted(SIM_METRICS))})",
                  file=sys.stderr)
            return 3
        tolerances[k] = float(v)
    with open(args.diff[0]) as f:
        old = json.load(f)
    with open(args.diff[1]) as f:
        new = json.load(f)
    diff = diff_sim(old, new, tolerances)
    print(format_sim_diff(diff))
    if not diff["comparable"]:
        return 2
    return 0 if diff["ok"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="CI-fast gate: 2 tenants / ~2k requests, same assertions")
    p.add_argument("--slots", type=int, default=None,
                   help="engine decode slots (default: 64, or 16 with --smoke)")
    p.add_argument("--seed", type=int, default=1,
                   help="service-time sampling seed (workload seeds are per-tenant)")
    p.add_argument("--out", default=None, help="run dir (default: a temp dir)")
    p.add_argument("--keep", action="store_true",
                   help="keep the run dir (implied by --out)")
    p.add_argument("--write-artifact", action="store_true",
                   help="write/refresh SIM_r<round>.json at the repo root")
    p.add_argument("--round", type=int, default=None,
                   help="artifact round number (default: next free)")
    p.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"),
                   help="diff two SIM_r*.json artifacts instead of running")
    p.add_argument("--tolerance", action="append", default=[], metavar="METRIC=TOL")
    args = p.parse_args(argv)
    if args.diff:
        return run_diff(args)
    if args.slots is None:
        args.slots = 16 if args.smoke else 64
    return run_gate(args)


if __name__ == "__main__":
    sys.exit(main())
